"""Vocab-sharded embedding tables + shard-local rows-touched updates.

For 10M+-row vocabularies a replicated (Nc, V, D) table (plus two Adadelta
moment slots) is the HBM budget — so the engine shards the table's VOCAB
axis across the model mesh axis and keeps the rows-touched update
shard-local, per the cross-replica weight-update sharding design (arxiv
2004.13336): each device owns rows [s*V/S, (s+1)*V/S), receives the
(replicated, batch-proportional) unique-id list, routes ids to itself by
offset arithmetic, and applies the update rule to ITS slice only.  No
device ever materializes the full table, no step all-gathers it — the
only vocab-proportional object anywhere is the sharded table itself.

The DEFAULT_RULES spelling (parallel/sharding.py) shards the stacked
table's axis 0 — the FIELD axis — which caps parallelism at Nc and leaves
each device a full-vocab slice; VOCAB_SHARD_RULES overrides it (prepended
by train/loop.init_state when a sharded sparse plan engages, first match
wins) to split axis 1, the vocab.  Moment slots follow the table's
sharding automatically (init_state places slots with p.sharding).
"""

from __future__ import annotations

from typing import Optional

from jax.sharding import PartitionSpec

from ..parallel.mesh import MODEL_AXIS

# prepended to the rule list by init_state when the sparse plan engages
# sharded: stacked CategoricalEmbed tables (Nc, V, D) split the vocab axis
VOCAB_SHARD_RULES = (
    (r".*[Ee]mbedding.*", PartitionSpec(None, MODEL_AXIS, None)),)


def make_sharded_rows_update(mesh, *, nc: int, vocab: int, shards: int,
                             rule: str, use_pallas: Optional[bool] = None):
    """fn(table, slots, g, ids, lr) -> (new_table, new_slots) over GLOBAL
    vocab-sharded arrays, computed shard-locally under shard_map.

    table/slots/g: (Nc, V, D) sharded P(None, model, None); ids: (U, Nc)
    replicated unique ids (sentinel >= V for padding); lr: scalar.
    Requires vocab % shards == 0 (resolve_plan enforces it with the fix
    spelled out).  Each shard rebases ids by its row offset and maps every
    foreign/sentinel id to the LOCAL sentinel V/S, so the per-shard update
    (fused kernel or XLA reference, ops/pallas_embedding) drops them —
    id→shard routing is pure offset arithmetic, no collective.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..ops.pallas_embedding import fused_rows_update
    from ..utils.jaxcompat import shard_map

    if vocab % shards != 0:
        raise ValueError(f"vocab {vocab} not divisible by {shards} shards")
    vloc = vocab // shards
    tspec = P(None, MODEL_AXIS, None)
    slots_spec = (tspec, tspec) if rule == "adadelta" else ()

    def local(table_l, slots_l, g_l, ids, lr):
        shard = jax.lax.axis_index(MODEL_AXIS)
        lo = shard * vloc
        rebased = ids - lo
        # foreign shards' ids and the dedup sentinel both land on the local
        # sentinel vloc: gathered then dropped, identical to the
        # replicated path's handling of the global sentinel
        local_ids = jnp.where((rebased >= 0) & (rebased < vloc),
                              rebased, vloc)
        safe = jnp.clip(local_ids, 0, vloc - 1)
        g_rows = jnp.stack(
            [g_l[f, safe[:, f]].astype(jnp.float32) for f in range(nc)],
            axis=1)                                          # (U, Nc, D)
        return fused_rows_update(table_l, slots_l, g_rows, local_ids,
                                 rule, lr, use_pallas)

    fn = shard_map(local, mesh=mesh,
                   in_specs=(tspec, slots_spec, tspec, P(), P()),
                   out_specs=(tspec, slots_spec),
                   # axis_index + replicated-by-construction outputs: the
                   # per-device results agree across unmentioned axes, but
                   # the static replication checker can't see it
                   check_vma=False)

    def update(table, slots, g, ids, lr):
        return fn(table, slots, g, ids, jnp.asarray(lr, jnp.float32))

    return update


def assert_vocab_sharded(table, shards: int) -> None:
    """Test/debug assertion: every addressable shard of the table holds
    V/shards vocab rows — i.e. the full table is never materialized per
    device (ISSUE acceptance criterion)."""
    nc, v, d = table.shape
    for s in table.addressable_shards:
        got = s.data.shape
        if got[1] != v // shards:
            raise AssertionError(
                f"table shard on device {s.device} holds {got} — expected "
                f"vocab slice of {v // shards} rows ({shards} shards)")
