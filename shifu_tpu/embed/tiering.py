"""Frequency-tiered embedding placement: hot rows resident, cold tail on
a host memmap.

A 10M x 16 f32 stacked table is ~640 MB per field before moment slots —
past what a single device (or the CPU CI tunnel) wants resident — but
tabular id traffic is zipf-skewed: a small hot set serves almost every
lookup.  `TieredTable` keeps the hot rows in memory (HBM once placed) and
serves the cold tail from a disk-backed memmap in the cache-v2 wire
format (`.npd` entry dir + entry.json manifest, int8 rows riding the SAME
wire_quantize grid as the feature wire — data/pipeline.py is the single
quantizer), so cold bytes are 1/4 of f32.  Cold fetches run host-side in
the feeder (attach_dedup kicks `prefetch` for the next batch's unique
ids), overlapped with the device step per the MLPerf TPU-pod input-tier
design (arxiv 1909.09756) — the step itself never blocks on disk.

Fault containment: every cold read passes the `embed.offload` chaos site.
On a read fault the table journals `embed_offload_fallback` and serves
the rows from a freshly-opened memmap handle (or the retained source
table when `keep_source=True`) — training continues, metrics identical
(tests/test_embed_engine.py runs the drill).

Scope: the cold tier serves host-side lookups (feeder prefetch, bench,
scoring warm paths) and bounds HOST memory; swapping cold rows in and out
of the device param mid-step is ROADMAP follow-up work.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict
from typing import Optional

import numpy as np

_MANIFEST = "entry.json"
_PAYLOAD = "table.bin"
# prefetch row-cache bound: (field, id) -> row, FIFO evicted.  Sized for a
# few batches of cold misses, not the vocab.
_PREFETCH_CAP = 65536


class TieredTable:
    """Host-side two-tier view of one stacked (Nc, V, D) embedding table."""

    def __init__(self, cold_dir: str, hot_ids: np.ndarray,
                 hot_rows: np.ndarray, source: Optional[np.ndarray] = None):
        self.cold_dir = cold_dir
        with open(os.path.join(cold_dir, _MANIFEST)) as f:
            self.manifest = json.load(f)
        self.shape = tuple(self.manifest["shape"])       # (Nc, V, D)
        self._dtype = self.manifest["dtype"]             # float32 | int8
        self._scale = float(self.manifest.get("scale", 1.0))
        self._mm = self._open()
        self.hot_ids = hot_ids                           # (Nc, H) sorted
        self.hot_rows = hot_rows                         # (Nc, H, D) f32
        self._source = source
        self._cache: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        self.stats = {"lookups": 0, "hits": 0, "misses": 0,
                      "cold_bytes": 0, "cold_seconds": 0.0,
                      "prefetch_hits": 0, "fallbacks": 0}

    # -- construction -------------------------------------------------------

    @staticmethod
    def build(table: np.ndarray, cold_dir: str, *, hot_rows: int = 0,
              hot_fraction: float = 0.05, freq: Optional[np.ndarray] = None,
              tier_dtype: str = "float32",
              keep_source: bool = False) -> "TieredTable":
        """Write the cold store for `table` (Nc, V, D) under
        `cold_dir/embed_cold.npd/` and return the tiered view.

        Hot set: top-`hot_rows` ids per field by `freq` ((Nc, V) counts)
        when given, else the LOWEST ids (Shifu's binning emits vocabs in
        descending frequency order, so low id ~ hot).  tier_dtype="int8"
        stores cold rows on the wire_quantize grid (scale = max|x|/127,
        symmetric) — ~1e-2 absolute error at default inits, bench-scale
        only; "float32" is exact.  keep_source retains the f32 table as
        the last-resort fallback for the chaos drill (memory-costly:
        leave False for 10M-vocab runs).
        """
        table = np.asarray(table, np.float32)
        nc, v, d = table.shape
        entry = os.path.join(cold_dir, "embed_cold.npd")
        os.makedirs(entry, exist_ok=True)
        manifest = {"shape": [nc, v, d], "dtype": tier_dtype, "version": 1}
        # stream the payload in ~64 MB row slices: a 10M x 16 table must
        # never materialize a second full-size intermediate on the host —
        # bounding build memory is the point of the tier
        chunk = max(1, (64 << 20) // max(d * 4, 1))
        if tier_dtype == "int8":
            from ..data.pipeline import wire_quantize
            amax = 0.0
            for f in range(nc):
                for lo in range(0, v, chunk):
                    amax = max(amax, float(
                        np.abs(table[f, lo:lo + chunk]).max(initial=0.0)))
            scale = max(amax, 1e-12) / 127.0
            manifest["scale"] = scale
            enc = lambda x: wire_quantize(x, np.float32(scale),
                                          np.float32(0.0))
        elif tier_dtype == "float32":
            enc = lambda x: np.ascontiguousarray(x, np.float32)
        else:
            raise ValueError(f"tier_dtype must be float32|int8: {tier_dtype!r}")
        with open(os.path.join(entry, _PAYLOAD), "wb") as fh:
            for f in range(nc):
                for lo in range(0, v, chunk):
                    fh.write(enc(table[f, lo:lo + chunk]).tobytes())
        with open(os.path.join(entry, _MANIFEST), "w") as f:
            json.dump(manifest, f)

        h = int(hot_rows) if hot_rows > 0 else max(1, int(v * hot_fraction))
        h = min(h, v)
        if freq is not None:
            order = np.argsort(-np.asarray(freq), axis=1, kind="stable")
            hot_ids = np.sort(order[:, :h].astype(np.int64), axis=1)
        else:
            hot_ids = np.tile(np.arange(h, dtype=np.int64)[None, :], (nc, 1))
        hot = np.stack([table[f, hot_ids[f]] for f in range(nc)])
        return TieredTable(entry, hot_ids, hot,
                           source=table if keep_source else None)

    def _open(self):
        mm_dtype = np.int8 if self._dtype == "int8" else np.float32
        return np.memmap(os.path.join(self.cold_dir, _PAYLOAD),
                         dtype=mm_dtype, mode="r", shape=self.shape)

    # -- reads --------------------------------------------------------------

    @property
    def hot_count(self) -> int:
        return self.hot_ids.shape[1]

    def _decode(self, rows: np.ndarray) -> np.ndarray:
        if self._dtype == "int8":
            from ..data.pipeline import wire_dequantize
            return wire_dequantize(rows, self._scale, 0.0)
        return np.asarray(rows, np.float32)

    def _cold_read(self, f: int, ids: np.ndarray) -> np.ndarray:
        """Fetch cold rows (field f, ids sorted-unique not required) through
        the chaos site, with the journaled fallback chain on fault."""
        from .. import chaos, obs
        t0 = time.perf_counter()
        try:
            chaos.maybe_fail("embed.offload", path=self.cold_dir, field=f)
            rows = np.asarray(self._mm[f, ids])
        except (chaos.ChaosError, OSError, ValueError) as e:
            self.stats["fallbacks"] += 1
            obs.event("embed_offload_fallback", field=f,
                      rows=int(ids.size), error=type(e).__name__,
                      detail=str(e)[:200])
            obs.counter("embed_offload_fallbacks_total",
                        "cold-tier read faults served by the fallback "
                        "chain").inc()
            if self._source is not None:
                rows = self._source[f, ids]
            else:
                self._mm = self._open()  # fresh handle, then direct read
                rows = np.asarray(self._mm[f, ids])
        self.stats["cold_seconds"] += time.perf_counter() - t0
        self.stats["cold_bytes"] += int(rows.nbytes)
        return self._decode(rows)

    def lookup(self, ids: np.ndarray) -> np.ndarray:
        """(B, Nc) int32 -> (B, Nc, D) f32, hot rows from memory, cold rows
        via memmap (prefetch cache consulted first).  Out-of-range ids
        (the dedup sentinel) return zero rows."""
        ids = np.asarray(ids)
        b, nc = ids.shape
        out = np.zeros((b, nc, self.shape[2]), np.float32)
        self.stats["lookups"] += 1
        for f in range(nc):
            col = ids[:, f]
            valid = (col >= 0) & (col < self.shape[1])
            pos = np.searchsorted(self.hot_ids[f], col)
            pos_c = np.minimum(pos, self.hot_count - 1)
            hot = valid & (self.hot_ids[f][pos_c] == col)
            out[hot, f] = self.hot_rows[f, pos_c[hot]]
            self.stats["hits"] += int(hot.sum())
            cold = valid & ~hot
            n_cold = int(cold.sum())
            if not n_cold:
                continue
            self.stats["misses"] += n_cold
            cold_ids = col[cold]
            rows = np.empty((n_cold, self.shape[2]), np.float32)
            need = np.ones(n_cold, bool)
            with self._lock:
                for j, cid in enumerate(cold_ids):
                    r = self._cache.get((f, int(cid)))
                    if r is not None:
                        rows[j] = r
                        need[j] = False
                        self.stats["prefetch_hits"] += 1
            if need.any():
                rows[need] = self._cold_read(f, cold_ids[need])
            out[cold, f] = rows
        return out

    # -- prefetch -----------------------------------------------------------

    def prefetch(self, ids: np.ndarray) -> threading.Thread:
        """Warm the row cache for a coming batch's cold ids on a background
        thread (the feeder calls this one batch ahead).  Returns the thread
        (joinable in tests); faults inside follow the same fallback chain."""
        ids = np.array(ids, copy=True)

        def work():
            for f in range(ids.shape[1]):
                col = np.unique(ids[:, f])
                col = col[(col >= 0) & (col < self.shape[1])]
                pos = np.minimum(np.searchsorted(self.hot_ids[f], col),
                                 self.hot_count - 1)
                cold = col[self.hot_ids[f][pos] != col]
                if not cold.size:
                    continue
                rows = self._cold_read(f, cold)
                with self._lock:
                    for cid, r in zip(cold, rows):
                        self._cache[(f, int(cid))] = r
                    while len(self._cache) > _PREFETCH_CAP:
                        self._cache.popitem(last=False)

        t = threading.Thread(target=work, name="embed-prefetch", daemon=True)
        t.start()
        return t

    # -- telemetry ----------------------------------------------------------

    def tier_report(self) -> dict:
        """Journal the tier counters as `embed_tier_report` (+ gauges) and
        return them.  `shifu-tpu profile`/`top` render this event — the
        renderers read the journal only, never this object."""
        from .. import obs
        s = dict(self.stats)
        total = s["hits"] + s["misses"]
        s["hit_rate"] = round(s["hits"] / total, 4) if total else 1.0
        s["hot_rows"] = self.hot_count
        s["vocab"] = self.shape[1]
        obs.event("embed_tier_report", **s)
        obs.gauge("embed_tier_hit_rate",
                  "hot-tier hit rate over row lookups").set(s["hit_rate"])
        obs.gauge("embed_cold_fetch_bytes_total",
                  "bytes fetched from the cold tier").set(s["cold_bytes"])
        obs.gauge("embed_cold_fetch_seconds_total",
                  "host seconds spent in cold-tier reads").set(
                      round(s["cold_seconds"], 6))
        return s
