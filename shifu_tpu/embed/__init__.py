"""Sparse embedding engine (docs/EMBEDDING.md).

Four cooperating legs replace "gather + dense-or-scatter optimizer" for
large-vocabulary tables:

- kernels: fused rows-touched update + scalar-prefetch lookup
  (ops/pallas_embedding — kept there with the other hot-op kernels);
- dedup:   per-batch unique-id compaction in the feeder (`dedup`);
- shard:   vocab-sharded tables, shard-local updates (`shard`);
- tiering: hot rows resident, cold tail on a host memmap (`tiering`).

train/sparse_embed.py is the policy layer that wires these into the step;
this package holds the mechanisms.
"""

from .dedup import (INVERSE_KEY, UNIQUE_KEY, attach_dedup, dedup_ids,
                    dedup_lookup, host_ids)
from .shard import (VOCAB_SHARD_RULES, assert_vocab_sharded,
                    make_sharded_rows_update)
from .tiering import TieredTable

__all__ = [
    "INVERSE_KEY", "UNIQUE_KEY", "attach_dedup", "dedup_ids",
    "dedup_lookup", "host_ids", "VOCAB_SHARD_RULES",
    "assert_vocab_sharded", "make_sharded_rows_update", "TieredTable",
]
