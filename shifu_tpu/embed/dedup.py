"""Per-batch unique-id compaction (the sparse embedding engine's dedup leg).

Tabular batches are duplicate-heavy — a 4k-row batch over a zipf-skewed
vocab touches far fewer distinct rows than it has cells — yet the raw-id
update path gathers/scatters one row per CELL.  This module compacts each
host batch to its per-field unique-id set in the feeder placement stage
(`attach_dedup` composes in front of the wire cast, so it runs inside the
producer thread, off the step critical path) and ships
`(embed_unique, embed_inverse)` over H2D alongside the batch.  The update
then touches each distinct row exactly ONCE — which is also what licenses
the fused Pallas rows-update kernel, whose DMA write-back has no
deterministic duplicate resolution (ops/pallas_embedding contract).

Exactness: the backward already SUMS duplicate rows' gradients
(segment-sum / one-hot matmul), so the dense (Nc, V, D) grad row for id i
equals the sum over every cell holding i; applying it once at i is
bit-identical to the raw path's `.at[].set` writing the same value once
per duplicate (tests/test_embed_engine.py pins bit-identity).

Shapes stay static across batches: the unique array is padded with the
SENTINEL id `vocab` (one past the last row) to a fixed capacity (the batch
size), so jit never recompiles on the per-batch unique count — sentinel
rows gather-clamp garbage and their scatter DROPS, on both the XLA
reference and the kernel's `pl.when` skip.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

# batch keys the feeder attaches (train/step.make_apply_gradients consumes
# embed_unique; embed_inverse rides along for lookup-side dedup consumers)
UNIQUE_KEY = "embed_unique"
INVERSE_KEY = "embed_inverse"


def host_ids(features: np.ndarray, layout) -> np.ndarray:
    """(B, F) float feature matrix -> (B, Nc) clipped int32 ids, replicating
    models/embedding.split_features EXACTLY (cast then per-field clip into
    [0, vocab)) so the dedup'd touched-row set equals the forward's."""
    raw = features[:, np.asarray(layout.categorical_positions, np.int64)]
    ids = raw.astype(np.int32)
    vocab = np.asarray(layout.vocab_sizes, np.int32)
    return np.clip(ids, 0, vocab - 1)


def dedup_ids(ids: np.ndarray, sentinel: int,
              capacity: Optional[int] = None
              ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-field unique compaction of a (B, Nc) id batch.

    Returns (unique (capacity, Nc) int32 — tail padded with `sentinel`,
    inverse (B, Nc) int32 — ids[b, f] == unique[inverse[b, f], f],
    counts (Nc,) int64 — distinct ids per field).  capacity defaults to B
    (np.unique can never exceed it), keeping device shapes static.
    """
    b, nc = ids.shape
    if capacity is None:
        capacity = b
    unique = np.full((capacity, nc), sentinel, np.int32)
    inverse = np.empty((b, nc), np.int32)
    counts = np.empty((nc,), np.int64)
    for f in range(nc):
        u, inv = np.unique(ids[:, f], return_inverse=True)
        if u.size > capacity:
            raise ValueError(
                f"dedup capacity {capacity} < {u.size} distinct ids "
                f"(field {f})")
        unique[:u.size, f] = u
        inverse[:, f] = inv
        counts[f] = u.size
    return unique, inverse, counts


def attach_dedup(layout, sentinel: int, *,
                 report_every: int = 256,
                 tiered=None) -> Callable[[dict], dict]:
    """Host-side batch transform for the feeder placement stage: adds
    UNIQUE_KEY/INVERSE_KEY to each batch dict (leaves batches without a
    'features' matrix untouched).  Composes IN FRONT of the wire cast —
    dedup reads the decoded f32 features (categorical jobs always ride the
    f32 wire).  Emits an `embed_dedup_report` journal event every
    `report_every` batches (mean rows touched vs raw cells — the number
    the update-path win scales with).  When a TieredTable is supplied its
    next-batch cold-row prefetch is kicked here, overlapping the host
    fetch with the device step."""
    state = {"batches": 0, "unique": 0, "cells": 0}

    def transform(batch: dict) -> dict:
        feats = batch.get("features")
        if feats is None or getattr(feats, "ndim", 0) != 2:
            return batch
        ids = host_ids(np.asarray(feats), layout)
        unique, inverse, counts = dedup_ids(ids, sentinel)
        if tiered is not None:
            tiered.prefetch(unique)
        out = dict(batch)
        out[UNIQUE_KEY] = unique
        out[INVERSE_KEY] = inverse
        state["batches"] += 1
        state["unique"] += int(counts.sum())
        state["cells"] += int(ids.size)
        if state["batches"] % report_every == 0:
            _report(state)
        return out

    def finalize() -> None:
        """Flush the tail report: a run shorter than `report_every` batches
        (most CLI jobs' last partial window) would otherwise journal no
        `embed_dedup_report` at all — the train loop calls this at teardown."""
        if state["batches"] and state["batches"] % report_every != 0:
            _report(state)

    transform.dedup_state = state  # introspectable for tests/loop teardown
    transform.finalize = finalize
    return transform


def _report(state: dict) -> None:
    from .. import obs
    cells = max(state["cells"], 1)
    obs.event("embed_dedup_report",
              batches=state["batches"],
              rows_touched=state["unique"],
              raw_cells=state["cells"],
              dedup_ratio=round(state["unique"] / cells, 4))
    obs.gauge("embed_dedup_ratio",
              "touched unique rows / raw id cells (lower = more "
              "duplicate-heavy batches, bigger sparse-update win)"
              ).set(state["unique"] / cells)


def dedup_lookup(table, unique, inverse, use_pallas: Optional[bool] = None):
    """Device-side lookup through the compacted ids: gather the unique rows
    once, then expand back to (B, Nc, D) with the inverse map.  Forward is
    bit-identical to the raw-id gather (same rows, same values); the
    backward reassociates the duplicate-row gradient sum (take_along_axis'
    scatter-add vs segment-sum order), so grads match to float tolerance,
    not bitwise — tests pin both."""
    import jax.numpy as jnp

    from ..ops.pallas_embedding import embedding_lookup

    rows = embedding_lookup(table, unique, use_pallas)       # (U, Nc, D)
    return jnp.take_along_axis(rows, inverse[:, :, None], axis=0)
