from .schema import (
    CheckpointConfig,
    ColumnSpec,
    ConfigError,
    DataConfig,
    DataSchema,
    JobConfig,
    MeshConfig,
    ModelSpec,
    ObsConfig,
    OptimizerConfig,
    RuntimeConfig,
    ServingConfig,
    TrainConfig,
)
from .shifu_compat import (
    job_config_from_shifu,
    parse_column_config,
    parse_model_config,
)

__all__ = [
    "CheckpointConfig",
    "ColumnSpec",
    "ConfigError",
    "DataConfig",
    "DataSchema",
    "JobConfig",
    "MeshConfig",
    "ModelSpec",
    "ObsConfig",
    "OptimizerConfig",
    "RuntimeConfig",
    "ServingConfig",
    "TrainConfig",
    "job_config_from_shifu",
    "parse_column_config",
    "parse_model_config",
]
