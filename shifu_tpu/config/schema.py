"""Typed configuration schema for shifu_tpu.

The reference spreads configuration across three places: Hadoop XML key/value
layers (reference: yarn/util/GlobalConfigurationKeys.java:22-155), Shifu's
ModelConfig.json hyperparameters (reference: resources/ssgd_monitor.py:91-107,
177-183) and a Java->Python env-var bridge (reference:
yarn/container/TensorflowTaskExecutor.java:200-238).  Here everything collapses
into one typed, serializable tree of dataclasses; `shifu_compat` fills it from
the unchanged Shifu JSON files.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence


class ConfigError(ValueError):
    """Raised when a config is structurally invalid."""


# ---------------------------------------------------------------------------
# Columns / dataset
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ColumnSpec:
    """One column of the normalized tabular input.

    Mirrors what the reference extracts from ColumnConfig.json into the
    SELECTED_COLUMN_NUMS / TARGET_COLUMN_NUM / WEIGHT_COLUMN_NUM env vars
    (reference: yarn/client/TensorflowClient.java + TensorflowTaskExecutor.java:200-238).
    """

    index: int
    name: str
    is_target: bool = False
    is_weight: bool = False
    is_selected: bool = False
    # categorical metadata (used by Wide&Deep / DeepFM embedding paths; the
    # reference MLP treats everything as pre-normalized floats)
    is_categorical: bool = False
    vocab_size: int = 0


@dataclass(frozen=True)
class DataSchema:
    """Column layout of one pipe-delimited normalized row."""

    columns: tuple[ColumnSpec, ...] = ()
    target_index: int = -1
    weight_index: int = -1          # -1 => implicit weight 1.0 (reference: ssgd_monitor.py:417-421)
    selected_indices: tuple[int, ...] = ()
    # Shifu multi-target mode (multitask models): ordered target columns.
    # Empty => single-target via target_index.
    target_indices: tuple[int, ...] = ()

    @property
    def feature_count(self) -> int:
        return len(self.selected_indices)

    @property
    def categorical_indices(self) -> tuple[int, ...]:
        by_index = {c.index: c for c in self.columns}
        return tuple(i for i in self.selected_indices
                     if i in by_index and by_index[i].is_categorical)

    @property
    def all_target_indices(self) -> tuple[int, ...]:
        return self.target_indices if self.target_indices else (self.target_index,)

    def validate(self) -> None:
        if self.target_index < 0 and not self.target_indices:
            raise ConfigError("DataSchema.target_index must be set (>= 0)")
        if not self.selected_indices:
            raise ConfigError("DataSchema.selected_indices must be non-empty")
        for t in self.all_target_indices:
            if t in self.selected_indices:
                raise ConfigError("target column cannot also be a selected feature")
        if self.weight_index >= 0 and self.weight_index in self.selected_indices:
            raise ConfigError("weight column cannot also be a selected feature")


@dataclass(frozen=True)
class DataConfig:
    """Input pipeline configuration.

    The reference round-robins gzip files across workers
    (yarn/appmaster/TrainingDataSet.java:65-82) and re-draws a random row-level
    train/valid split every run (ssgd_monitor.py:395 `random.random()`); here
    the split is a deterministic per-row hash so resume/restart sees the same
    partition.
    """

    paths: tuple[str, ...] = ()
    delimiter: str = "|"
    valid_ratio: float = 0.1        # reference default VALID_TRAINING_DATA_RATIO (ssgd_monitor.py:27)
    split_seed: int = 0
    batch_size: int = 100           # reference default BATCH_SIZE (ssgd_monitor.py:33)
    shuffle_seed: int = 0
    shuffle: bool = True
    drop_remainder: bool = True     # static shapes for XLA
    prefetch: int = 2
    # host-side queue depth of the input feeders: the streamed first
    # epoch's parse-result queue and the overlap engine's host staging
    # queue (data/pipeline.EpochFeeder) both run this many items ahead.
    # Distinct from `prefetch`, which bounds DEVICE-resident blocks (HBM);
    # this knob bounds host RAM held by assembled-but-unstaged chunks.
    # 0 = auto: the feeder instead resizes its DEVICE staging gate per
    # epoch from the goodput ledger's exposed-input measurement
    # (data/pipeline.next_prefetch_depth — HBM-side run-ahead between 2
    # and 8 chunks, superseding `prefetch`; the host queue stays at 4).
    prefetch_depth: int = 4
    # cross-epoch overlap engine (train/loop.py + data/pipeline.EpochFeeder):
    # a persistent feeder shuffles and assembles epoch N+1's batches on host
    # threads while epoch N still executes on device, and next-epoch work
    # overlaps the eval dispatch tail — batch order stays a pure function of
    # (seed, epoch), byte-identical to the non-overlapped order.  False
    # restores the per-epoch producer thread (stop-the-world boundaries).
    overlap_epochs: bool = True
    # staged epochs: device-put (block_batches, B, F) blocks once and
    # lax.scan the train step on device — one H2D transfer per block instead
    # of per batch; the 10M+ samples/sec input path (SURVEY.md section 7.3)
    staged: bool = True
    block_batches: int = 32
    # device-resident tier: when the training partition fits in this many
    # bytes of HBM, transfer it once and reorder batches on device each epoch
    # (zero steady-state H2D).  0 disables.
    device_resident_bytes: int = 2 << 30
    # parse-once columnar cache directory (data/cache.py); None defers to the
    # SHIFU_TPU_DATA_CACHE env var, empty-or-unset means no cache.
    cache_dir: str | None = None
    # cache entry format generation (data/cache.py CACHE_FORMAT_VERSION):
    # 0 = latest (v2: wire-format projected entries with compact
    # target/weight storage and an entry.json manifest — ¼ the disk bytes
    # of raw float32, zero re-quantize on warm starts); 1 pins the legacy
    # v1 layout for interop with pre-v2 readers sharing the cache dir.
    # Both formats reconstruct bit-identical arrays on load.
    cache_format: int = 0
    # file-level read parallelism for load_datasets; 0 = one thread per file
    # capped at cpu_count.
    read_threads: int = 0
    # cold-ingest parse pool width: how many part-files inflate+parse
    # concurrently (native parser per file; v2 cache writes overlap on a
    # separate writer thread).  0 = auto (read_threads when set, else one
    # worker per file capped at cpu_count).  Takes precedence over
    # read_threads when both are set; intra-file parser threads scale down
    # as the pool widens so total parallelism stays ~cores, not cores².
    ingest_workers: int = 0
    # out-of-core mode: consolidate the host shard into on-disk projected
    # arrays once (requires cache_dir) and train from read-only memmaps —
    # host shards larger than RAM stream through the staged tier
    # (data/outofcore.py).
    out_of_core: bool = False
    # stream the FIRST trained epoch: start training on parsed blocks while
    # the remaining files still parse (single-host staged path; parse, H2D,
    # and device compute overlap instead of running serially — the fix for
    # the reference's parse-everything-then-train anti-pattern,
    # ssgd_monitor.py:348-454).  Later epochs train from the fully loaded,
    # globally shuffled dataset as usual.
    stream_first_epoch: bool = True
    # host->device wire dtype for the FEATURES array: "auto" sends bfloat16
    # when the model computes in bfloat16 anyway (the model casts inputs
    # first — models/base.py) and no categorical id columns ride in features
    # (ids > 256 are not bf16-exact); halves H2D bytes and the resident
    # tier's HBM footprint.  "float32"/"bfloat16" force a choice.  "int8"
    # quantizes features to a per-column affine grid on the host and
    # dequantizes on device (train/step.py make_wire_decode): 1 byte/value
    # on the wire — 2x the effective H2D roofline of bf16 — at a max
    # rounding error of wire_int8_clip/254 per value, which ZSCALE-
    # normalized data tolerates (AUC parity pinned by
    # tests/test_wire_int8.py).  int8 requires a categorical-free feature
    # matrix (ids cannot ride an affine grid; JobConfig.validate enforces).
    wire_dtype: str = "auto"
    # symmetric per-column clip for the int8 wire grid, in (normalized)
    # feature units: values quantize to round(x * 127/clip) in [-127, 127],
    # so anything beyond +-clip saturates.  Shifu ZSCALE clamps at 4-6
    # sigma, so the default 8.0 never clips in-contract data.
    wire_int8_clip: float = 8.0
    # compact wire for the TARGET column: "auto" sends uint8 (1 B instead of
    # 4) exactly when every value in the block is an integer in [0, 255] —
    # always true for Shifu's binary labels — decoded back to f32 on device
    # (train/step.py); lossless by construction, falls back to f32 per block
    # otherwise.  "uint8" forces (non-representable targets raise);
    # "float32" disables.
    wire_label_dtype: str = "auto"
    # compact wire for the WEIGHT column: "auto" elides the column entirely
    # (0 B on the wire) when every weight in the block is exactly 1.0 — the
    # common case for Shifu jobs without a weightColumnName — with the
    # device step synthesizing ones (bit-identical losses).  "elide" forces
    # (non-unit weights raise); "float32" disables.
    wire_weight_mode: str = "auto"
    # pod-scale host shard assignment (data/pipeline.host_shard_assignment):
    # how source files map onto hosts as a pure function of
    # (process_index, process_count, seed, epoch).  "auto"/"static" = the
    # fixed round-robin (i % num_hosts, the legacy scheme — stable across
    # epochs, so per-host caches and out-of-core entries stay hot).
    # "rotate" rotates the round-robin by a deterministic per-epoch offset
    # (shard_rotation): across epochs every host visits every slice, and a
    # host rejoining after an elastic reshape re-derives its slice from
    # the same formula.  Epoch 0 is identical in all modes.
    host_shard: str = "auto"
    # in-HBM format for the device-resident tier's feature blocks: "auto"
    # keeps the wire format (no silent precision change), "wire" says the
    # same explicitly, "int8" forces int8 residency — features quantize to
    # the wire_params grid at tier build even when the per-batch wire is
    # f32/bf16, quartering resident HBM vs f32 staging, with dequantization
    # fused into the first-layer matmul where ops/pallas_int8_matmul is
    # available (XLA decode otherwise).  Same categorical-free requirement
    # as wire_dtype="int8" (JobConfig.validate enforces).
    resident_format: str = "auto"

    def validate(self) -> None:
        if not (0.0 <= self.valid_ratio < 1.0):
            raise ConfigError(f"valid_ratio must be in [0,1): {self.valid_ratio}")
        if self.batch_size <= 0:
            raise ConfigError("batch_size must be positive")
        if self.prefetch_depth < 0:
            raise ConfigError(
                f"prefetch_depth must be >= 0 (0 = auto): "
                f"{self.prefetch_depth}")
        if self.cache_format not in (0, 1, 2):
            raise ConfigError(
                f"cache_format must be 0 (latest), 1, or 2: "
                f"{self.cache_format}")
        if self.ingest_workers < 0:
            raise ConfigError(
                f"ingest_workers must be >= 0 (0 = auto): "
                f"{self.ingest_workers}")
        if self.wire_dtype not in ("auto", "float32", "bfloat16", "int8"):
            raise ConfigError(
                f"wire_dtype must be auto/float32/bfloat16/int8: "
                f"{self.wire_dtype!r}")
        if self.wire_int8_clip <= 0:
            raise ConfigError(
                f"wire_int8_clip must be positive: {self.wire_int8_clip}")
        if self.wire_label_dtype not in ("auto", "uint8", "float32"):
            raise ConfigError(
                f"wire_label_dtype must be auto/uint8/float32: "
                f"{self.wire_label_dtype!r}")
        if self.wire_weight_mode not in ("auto", "elide", "float32"):
            raise ConfigError(
                f"wire_weight_mode must be auto/elide/float32: "
                f"{self.wire_weight_mode!r}")
        if self.resident_format not in ("auto", "wire", "int8"):
            raise ConfigError(
                f"resident_format must be auto/wire/int8: "
                f"{self.resident_format!r}")
        if self.host_shard not in ("auto", "static", "rotate"):
            raise ConfigError(
                f"host_shard must be auto/static/rotate: "
                f"{self.host_shard!r}")


# ---------------------------------------------------------------------------
# Model
# ---------------------------------------------------------------------------

VALID_MODEL_TYPES = ("mlp", "wide_deep", "deepfm", "multitask",
                     "ft_transformer", "moe_mlp")
VALID_ACTIVATIONS = ("sigmoid", "tanh", "relu", "leakyrelu")


@dataclass(frozen=True)
class ModelSpec:
    """Model topology.

    For `mlp` this mirrors ModelConfig.json train params NumHiddenLayers /
    NumHiddenNodes / ActivationFunc (reference: ssgd_monitor.py:93-106) with a
    sigmoid scoring head named `shifu_output_0` (ssgd_monitor.py:121).
    """

    model_type: str = "mlp"
    hidden_nodes: tuple[int, ...] = (20,)     # reference fallback HIDDEN_NODES_COUNT=20 (ssgd_monitor.py:26)
    activations: tuple[str, ...] = ("leakyrelu",)  # reference default (ssgd_monitor.py:77-90)
    # Reference quirk, kept as explicit options: xavier init on *biases* too
    # (ssgd_monitor.py:66-70) and an L2 regularizer that is declared but never
    # added to the optimized loss (ssgd_monitor.py:59, loss at :129).
    xavier_bias_init: bool = True
    l2_scale: float = 0.0
    # embedding path (wide_deep / deepfm / ft_transformer)
    embedding_dim: int = 16
    # multitask: number of output heads (Shifu multi-target mode)
    num_heads: int = 1
    head_names: tuple[str, ...] = ("shifu_output_0",)
    # ft_transformer
    num_layers: int = 3
    num_attention_heads: int = 8
    token_dim: int = 64
    mlp_ratio: int = 4
    dropout_rate: float = 0.0
    # attention implementation for the transformer blocks: "local" (every
    # device holds the full token axis), "ring" (ppermute K/V rotation —
    # ops/attention.ring_attention), "ulysses" (all-to-all head scatter —
    # ops/attention.ulysses_attention), or "flash" (blockwise Pallas kernel,
    # O(S) memory — ops/pallas_attention.flash_attention).  ring/ulysses take
    # effect when the training mesh has a `seq` axis of size > 1; flash is a
    # per-device kernel choice; scoring/export always runs local.
    attention_impl: str = "local"
    # fused transformer block (ft_transformer): run each TransformerBlock's
    # attention + FFN as one Pallas pass (ops/pallas_ft_block) when the
    # feature-token count fits the kernel's shape class.  "auto" engages on
    # TPU backends (or under SHIFU_TPU_PALLAS interpret opt-in), "on"
    # forces (interpret mode off-TPU — the CI exactness path), "off"
    # keeps the unfused module math.  Inapplicable shapes, train-time
    # dropout, and ring/ulysses sequence parallelism always fall back.
    fused_block: str = "auto"
    # pipeline parallelism (ft_transformer): split the transformer blocks
    # into this many stages over the mesh's `pipe` axis, GPipe-style
    # microbatch schedule (parallel/pipeline.py).  1 = off.  Training-time
    # knob only: export always canonicalizes to the single-device graph.
    pipeline_stages: int = 1
    # microbatches per global batch when pipelined; 0 = pipeline_stages
    # (the minimum that keeps every stage busy at steady state)
    pipeline_microbatches: int = 0
    # moe_mlp: dense-gated mixture of expert MLP trunks; the expert axis
    # shards over the `model` mesh axis (true expert parallelism)
    num_experts: int = 4
    # rematerialization (gradient checkpointing): recompute each transformer
    # block's activations in the backward pass instead of storing them —
    # trades FLOPs for HBM on deep stacks / long token axes (jax.checkpoint)
    remat: bool = False
    # numerics
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"

    def validate(self) -> None:
        if self.model_type not in VALID_MODEL_TYPES:
            raise ConfigError(f"unknown model_type {self.model_type!r}; "
                              f"expected one of {VALID_MODEL_TYPES}")
        if len(self.hidden_nodes) != len(self.activations):
            raise ConfigError("hidden_nodes and activations must have equal length")
        for a in self.activations:
            if a not in VALID_ACTIVATIONS:
                raise ConfigError(f"unknown activation {a!r}")
        if self.num_heads != len(self.head_names):
            raise ConfigError("num_heads must match len(head_names)")
        if self.attention_impl not in ("local", "ring", "ulysses", "flash"):
            raise ConfigError(
                f"unknown attention_impl {self.attention_impl!r}; "
                "expected local|ring|ulysses|flash")
        if self.fused_block not in ("auto", "on", "off"):
            raise ConfigError(
                f"fused_block must be auto/on/off: {self.fused_block!r}")
        if self.model_type == "moe_mlp" and self.num_experts < 2:
            raise ConfigError("moe_mlp requires num_experts >= 2")
        if self.pipeline_stages < 1 or self.pipeline_microbatches < 0:
            raise ConfigError("pipeline_stages must be >= 1 and "
                              "pipeline_microbatches >= 0")
        if self.pipeline_stages > 1:
            if self.model_type != "ft_transformer":
                raise ConfigError("pipeline_stages > 1 requires "
                                  "model_type='ft_transformer'")
            if self.num_layers % self.pipeline_stages != 0:
                raise ConfigError(
                    f"num_layers ({self.num_layers}) must be divisible by "
                    f"pipeline_stages ({self.pipeline_stages})")
            if self.attention_impl in ("ring", "ulysses"):
                raise ConfigError(
                    "pipeline_stages > 1 composes with local/flash attention "
                    "only (sequence parallelism uses its own mesh axis)")
            if self.dropout_rate > 0:
                raise ConfigError("pipeline_stages > 1 requires dropout_rate=0")


# ---------------------------------------------------------------------------
# Training
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class OptimizerConfig:
    """Optimizer selection.

    Reference default is Adadelta (ssgd_monitor.py:140) at LearningRate from
    ModelConfig.json, falling back to 0.003 (ssgd_monitor.py:134-137).
    """

    name: str = "adadelta"
    learning_rate: float = 0.003
    momentum: float = 0.9
    weight_decay: float = 0.0
    grad_clip_norm: float = 0.0     # 0 disables
    # gradient accumulation: the TPU analog of SAGN's 5-step local window
    # (reference: resources/SAGN.py:110-142) — accumulate k microbatch grads
    # before applying one update.
    accumulate_steps: int = 1
    # learning-rate schedule over optimizer steps (the reference only had a
    # constant LR): constant | cosine | exponential | warmup_cosine
    schedule: str = "constant"
    warmup_steps: int = 0           # linear warmup from 0 (warmup_cosine)
    decay_steps: int = 0            # horizon for cosine/exponential (required)
    decay_rate: float = 0.96        # per-decay_steps factor (exponential)
    end_lr_factor: float = 0.0      # final lr = learning_rate * this (cosine)

    def validate(self) -> None:
        if self.learning_rate <= 0:
            raise ConfigError("learning_rate must be positive")
        if self.accumulate_steps < 1:
            raise ConfigError("accumulate_steps must be >= 1")
        if self.schedule not in ("constant", "cosine", "exponential",
                                 "warmup_cosine"):
            raise ConfigError(f"unknown schedule {self.schedule!r}; expected "
                              "constant|cosine|exponential|warmup_cosine")
        if self.schedule != "constant" and self.decay_steps <= 0:
            raise ConfigError(
                f"schedule {self.schedule!r} requires decay_steps > 0")
        if self.warmup_steps < 0:
            raise ConfigError("warmup_steps must be >= 0")
        if (self.schedule == "warmup_cosine"
                and self.decay_steps <= self.warmup_steps):
            raise ConfigError(
                f"warmup_cosine requires decay_steps ({self.decay_steps}) > "
                f"warmup_steps ({self.warmup_steps})")


@dataclass(frozen=True)
class TrainConfig:
    epochs: int = 100               # reference: ModelConfig train.numTrainEpochs
    loss: str = "weighted_mse"      # reference semantics: tf.losses.mean_squared_error on sigmoid (ssgd_monitor.py:129)
    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    seed: int = 42
    eval_every_epochs: int = 1      # reference evaluates the valid set every epoch (ssgd_monitor.py:281-284)
    log_every_steps: int = 0        # 0: epoch-level logging only, like the reference
    bagging_sample_rate: float = 1.0
    # early stopping on the valid error (no reference analog — it always ran
    # all numTrainEpochs): stop after this many evaluated epochs without an
    # improvement of at least early_stop_min_delta.  0 disables.
    early_stop_patience: int = 0
    early_stop_min_delta: float = 0.0
    # True local SGD (the reference's SAGN trainer, resources/SAGN.py:110-196):
    # each data shard runs `local_sgd_window` plain-SGD updates on its OWN
    # parameter replica between global syncs (parameter all-mean).  0 = off
    # (every step is globally synchronous, the ssgd_monitor semantics).
    # Parameter averaging after K local lr-steps equals the reference's
    # "average the window's accumulated grads, apply globally, resync" with
    # an SGD apply at learning rate K*lr (it divides the window sum by K,
    # SAGN.py:137-142); shifu_compat divides a migrated SAGN config's
    # LearningRate by K to keep the effective step size.  KNOWN deviation:
    # the reference's local AND global applies use Adam (SAGN.py:107-108,
    # 158-159 — GradientDescent is commented out); this tier is plain SGD
    # (see validate() below and PARITY.md "Local SGD").
    local_sgd_window: int = 0
    # rows-touched-only optimizer updates for gather-path embedding tables
    # (train/sparse_embed.py — the SPMD successor of TF's IndexedSlices
    # sparse applies the reference relied on, ssgd_monitor.py:203-206).
    # "auto": engage when the optimizer has a sparse rule (adadelta/sgd),
    # the table is not model-axis sharded, and the vocab is large enough
    # that dense optimizer traffic dominates; "on": require it (raise with
    # the specific blocker otherwise); "off": always dense.
    sparse_embedding_update: str = "auto"
    # minimum acceptable train_scaling_efficiency for the pod data-plane
    # scaling sweep (bench.py / tools/perf_gate.py 13th axis): achieved
    # speedup over n_hosts divided by ideal.  0 disables the gate; the
    # perf gate's own floor (0.6) still applies to recorded benchmarks.
    scaling_gate: float = 0.6

    def validate(self) -> None:
        if self.epochs <= 0:
            raise ConfigError("epochs must be positive")
        if not (0.0 <= self.scaling_gate <= 1.0):
            raise ConfigError(
                f"scaling_gate must be in [0, 1]: {self.scaling_gate}")
        if self.sparse_embedding_update not in ("auto", "on", "off"):
            raise ConfigError(
                f"sparse_embedding_update must be auto/on/off: "
                f"{self.sparse_embedding_update!r}")
        if self.early_stop_patience < 0 or self.early_stop_min_delta < 0:
            raise ConfigError("early_stop_patience and early_stop_min_delta "
                              "must be >= 0")
        if not (0.0 < self.bagging_sample_rate <= 1.0):
            raise ConfigError("bagging_sample_rate must be in (0, 1]: "
                              f"{self.bagging_sample_rate}")
        if self.loss not in ("weighted_mse", "bce", "weighted_bce"):
            raise ConfigError(f"unknown loss {self.loss!r}")
        if self.local_sgd_window < 0:
            raise ConfigError("local_sgd_window must be >= 0")
        if self.local_sgd_window > 0:
            # this tier's local updates are plain p - lr*g; the reference
            # SAGN ran Adam locally AND globally (SAGN.py:107-108,158-159),
            # but momentum/adaptive state on diverged local replicas has no
            # sound averaging semantic here — reject rather than guess, and
            # document the optimizer-family deviation (PARITY.md)
            if self.optimizer.name != "sgd":
                raise ConfigError(
                    "local_sgd_window requires optimizer 'sgd' (this tier "
                    "implements plain-SGD local updates; the reference "
                    "SAGN's Adam family is a documented deviation), "
                    f"got {self.optimizer.name!r}")
            if self.optimizer.accumulate_steps > 1:
                raise ConfigError("local_sgd_window and accumulate_steps "
                                  "are mutually exclusive")
            if self.optimizer.schedule != "constant":
                raise ConfigError("local_sgd_window supports only the "
                                  "constant learning-rate schedule (local "
                                  "updates use the static lr)")
            if self.optimizer.grad_clip_norm > 0 or self.optimizer.weight_decay > 0:
                raise ConfigError(
                    "local_sgd_window applies plain p - lr*g local updates; "
                    "grad_clip_norm/weight_decay would be silently ignored "
                    "— unset them (the reference SAGN has neither)")
        self.optimizer.validate()


# ---------------------------------------------------------------------------
# Observability (device flight recorder — obs/devprof.py)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ObsConfig:
    """Device-profiling plane knobs (docs/OBSERVABILITY.md "Device flight
    recorder").  The reference's only profiling hook was a dead
    start_tensorboard (ssgd_monitor.py:493-502); here trace capture is a
    scheduled, bounded, journaled part of the train loop."""

    # which epochs capture a jax.profiler trace window, parsed into a
    # per-kernel `device_profile` journal event: "off" (default — the
    # flight recorder ring/watermarks stay on, only the profiler is
    # idle), "first" (the first trained epoch only), "every:N", or a
    # comma list of epoch numbers ("0,2,5").
    trace_epochs: str = "off"
    # where trace windows land; "" anchors a trace/ dir beside the
    # telemetry sinks (local job dirs; remote telemetry disables capture
    # — jax.profiler writes real files).
    trace_dir: str = ""
    # per-kernel rollup rows kept in the device_profile event (the tail
    # folds into other_us) — bounds journal bytes and label cardinality.
    trace_top_k: int = 16
    # poll device.memory_stats() at epoch boundaries into hbm_* gauges +
    # an hbm_watermark event (XLA memory-analysis estimate on backends
    # without allocator stats).
    hbm_watermarks: bool = True
    # flight recorder: ring size (last K per-chunk timings), the robust
    # z-score an anomalous chunk must exceed, how many prior chunks the
    # detector needs before judging, and the minimum slowdown ratio over
    # the ring median (the guard that keeps near-constant quiet series
    # from flagging scheduler jitter).
    anomaly_window: int = 32
    anomaly_zscore: float = 6.0
    anomaly_min_chunks: int = 8
    anomaly_min_ratio: float = 0.5

    def validate(self) -> None:
        from ..obs import devprof  # parse, don't duplicate the grammar
        try:
            devprof.parse_trace_epochs(self.trace_epochs)
        except ValueError as e:
            raise ConfigError(str(e))
        if self.trace_top_k < 1:
            raise ConfigError(
                f"obs.trace_top_k must be >= 1: {self.trace_top_k}")
        if self.anomaly_window < 4:
            raise ConfigError(
                f"obs.anomaly_window must be >= 4: {self.anomaly_window}")
        if self.anomaly_zscore <= 0 or self.anomaly_min_ratio < 0:
            raise ConfigError(
                "obs.anomaly_zscore must be > 0 and anomaly_min_ratio >= 0")
        if self.anomaly_min_chunks < 2:
            raise ConfigError(
                f"obs.anomaly_min_chunks must be >= 2: "
                f"{self.anomaly_min_chunks}")


# ---------------------------------------------------------------------------
# Sparse embedding engine (shifu_tpu/embed/ — docs/EMBEDDING.md)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class EmbedConfig:
    """Sparse embedding engine knobs (docs/EMBEDDING.md).  Rides on top of
    train.sparse_embedding_update: dedup and sharding shape HOW the
    rows-touched update runs; tiering governs where 10M+-vocab tables
    live (hot rows in HBM, cold tail on a host memmap)."""

    # per-batch unique-id compaction in the feeder placement stage:
    # "auto" (default — engages whenever a sparse plan engages), "off".
    # Ships (unique_ids, inverse) over H2D alongside features, so the
    # update touches each row once; exact under duplicates by
    # construction (tests/test_embed_engine.py pins bit-identity).
    dedup: str = "auto"
    # frequency-tiered table placement: "off" (default — the whole table
    # is device-resident) or "host" (cold tail served from a host
    # memmap; see embed/tiering.py).  Training-step residency swap is
    # future work (ROADMAP); "host" today serves bench/feeder lookups.
    tiering: str = "off"
    # cold-tier storage dtype: "float32" (exact) or "int8" (4x smaller,
    # rides the cache-v2 wire quantization grid — lossy, bench-only).
    tier_dtype: str = "float32"
    # hot-tier size: explicit row count, or 0 to derive from
    # hot_fraction of the vocab.
    hot_rows: int = 0
    hot_fraction: float = 0.05
    # where the cold-tier memmap + manifest land ("" = beside the job's
    # cache dir; bench passes a tempdir).
    cold_dir: str = ""
    # overlap next-batch cold-row fetches with the device step
    # (feeder-style background thread).
    prefetch: bool = True

    def validate(self) -> None:
        if self.dedup not in ("auto", "off"):
            raise ConfigError(
                f"embed.dedup must be auto|off: {self.dedup!r}")
        if self.tiering not in ("off", "host"):
            raise ConfigError(
                f"embed.tiering must be off|host: {self.tiering!r}")
        if self.tier_dtype not in ("float32", "int8"):
            raise ConfigError(
                f"embed.tier_dtype must be float32|int8: "
                f"{self.tier_dtype!r}")
        if self.hot_rows < 0:
            raise ConfigError(f"embed.hot_rows must be >= 0: "
                              f"{self.hot_rows}")
        if not (0.0 < self.hot_fraction <= 1.0):
            raise ConfigError(
                f"embed.hot_fraction must be in (0, 1]: "
                f"{self.hot_fraction}")


# ---------------------------------------------------------------------------
# Serving plane (runtime/serve.py — docs/SERVING.md)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class DriftConfig:
    """Model-quality / data-drift observatory knobs (`shifu.drift.*` XML
    keys, obs/drift.py — docs/OBSERVABILITY.md "Drift observatory").

    Nested under ServingConfig so it threads unchanged through the
    daemon, fleet members and the loadtest probe.  Drift only engages
    when the served artifact actually carries a `baseline_profile.json`;
    `enabled` is the operator kill switch on top of that."""

    # kill switch: False silences the whole drift plane — no sketch
    # accumulation, no tick thread, zero drift events (the overhead
    # guard's contract).
    enabled: bool = True
    # fast/slow trailing windows (seconds): an alert objective must
    # violate in BOTH to fire (transient bursts don't page) and the
    # fast window alone resolves it (recovery is quick).
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    # per-feature PSI threshold on the int8 wire grid, folded to 17
    # groups; conventional reading: < 0.1 stable, 0.1-0.25 moderate,
    # > 0.25 significant.  0 disables the feature_psi objective.
    psi_threshold: float = 0.25
    # KL(baseline || live) threshold for the score distribution;
    # 0 disables the score_kl objective.
    score_kl_threshold: float = 0.1
    # how many worst features a drift_report / drift_alert names
    top_k: int = 5
    # fast window must hold at least this many rows before any
    # judgment (quiet traffic never pages; idle unlatch below this).
    min_rows: int = 200
    # labeled-feedback path (wire FEEDBACK frame -> live AUC /
    # auc_decay); off rejects FEEDBACK frames with STATUS_ERROR.
    feedback: bool = True
    # score-bin resolution of the feedback AUC accumulator
    feedback_bins: int = 1024

    def validate(self) -> None:
        if self.fast_window_s <= 0 \
                or self.slow_window_s < self.fast_window_s:
            raise ConfigError(
                "drift windows need 0 < fast_window_s <= slow_window_s: "
                f"{self.fast_window_s}/{self.slow_window_s}")
        if self.psi_threshold < 0:
            raise ConfigError(
                f"drift.psi-threshold must be >= 0: {self.psi_threshold}")
        if self.score_kl_threshold < 0:
            raise ConfigError("drift.score-kl-threshold must be >= 0: "
                              f"{self.score_kl_threshold}")
        if self.top_k < 1:
            raise ConfigError(f"drift.top-k must be >= 1: {self.top_k}")
        if self.min_rows < 1:
            raise ConfigError(
                f"drift.min-rows must be >= 1: {self.min_rows}")
        if self.feedback_bins < 2:
            raise ConfigError("drift.feedback-bins must be >= 2: "
                              f"{self.feedback_bins}")


@dataclass(frozen=True)
class ServingConfig:
    """Knobs for the persistent scoring daemon (`shifu-tpu serve`).

    Standalone, not a JobConfig member: serving is driven from an export
    ARTIFACT, not a training job — the XML spelling (`shifu.serving.*`,
    utils/xmlconfig.serving_config_from_conf) layers the same way train
    keys do, with CLI flags as the top override."""

    # scoring engine tier: auto / native / numpy / stablehlo / jax / aot
    # (same ladder as `shifu-tpu score --engine`; `aot` forces the
    # artifact's pre-compiled executable pack, degrading to jax when the
    # pack is absent or fingerprint-incompatible)
    engine: str = "auto"
    # adaptive micro-batcher: a LONE request is dispatched after at most
    # this budget (ms); under load batches fill to max_batch and dispatch
    # immediately — the deadline only ever binds when traffic is sparse.
    latency_budget_ms: float = 2.0
    # largest coalesced batch (queue-depth-driven: everything waiting is
    # taken up to this, so batch size tracks load)
    max_batch: int = 4096
    # smallest padded-bucket shape for static-shape engines (jax /
    # stablehlo): batches pad up the power-of-two ladder
    # min_batch_bucket, 2x, 4x ... max_batch so the jit cache holds at
    # most log2(max_batch/min_batch_bucket)+1 executables
    min_batch_bucket: int = 16
    # admission bound: requests beyond this queue depth are rejected
    # with ServeOverload (backpressure to the caller, never a silent
    # drop or an unbounded-latency queue)
    queue_limit: int = 100_000
    # scoring worker threads draining the admission queue (numpy/native
    # release the GIL in their kernels, so >1 can help on big hosts)
    workers: int = 1
    # `serving_report` journal cadence (seconds); 0 disables the reporter
    report_every_s: float = 10.0
    # TCP port for `shifu-tpu serve` (0 = ephemeral, printed at startup)
    port: int = 8571
    # bind host for the wire server
    host: str = "127.0.0.1"
    # per-request lifecycle tracing (obs/slo.py, docs/OBSERVABILITY.md
    # "Serving SLO engine"): journal one sampled `request_trace` event —
    # the admission/queue/coalesce/dispatch/device/reply span chain whose
    # stage durations sum to the end-to-end latency — for every Nth
    # admitted request (deterministic 1-in-N).  0 disables sampling; the
    # per-stage `serve_stage_seconds` histograms stay on regardless.
    trace_sample: int = 0
    # p99 exemplars: how many slowest-request trace_ids a loadtest run
    # reports in its `loadtest_report` (0 disables; only meaningful with
    # trace_sample > 0 — exemplars come from the sampled traces)
    trace_exemplars: int = 5
    # serving SLO objectives (`shifu.serving.slo.*` XML keys); 0 disables
    # each.  p99 target in ms — pick a value on the latency bucket grid
    # (1/2.5/5/10/25...) so the violation count is bucket-exact; error
    # rate and availability are fractions (e.g. 0.001 / 0.999).
    slo_p99_ms: float = 0.0
    slo_error_rate: float = 0.0
    slo_availability: float = 0.0
    # multiwindow burn-rate alerting: both the fast and the slow trailing
    # window must burn the objective's budget at >= slo_burn_threshold x
    # the sustainable rate to fire ONE `slo_alert`; the alert latches
    # until the fast window is healthy again (burn < 1), then resolves.
    slo_fast_window_s: float = 60.0
    slo_slow_window_s: float = 300.0
    slo_burn_threshold: float = 2.0
    # model-quality / data-drift observatory (`shifu.drift.*` keys,
    # obs/drift.py); engages only when the artifact carries a
    # baseline_profile.json.
    drift: DriftConfig = field(default_factory=DriftConfig)
    # export-time opt-in (`shifu.serving.aot-pack` / `--aot-pack`):
    # compile the scorer for every rung of the padded bucket ladder at
    # save_artifact time and ship the serialized executables inside the
    # artifact (export/aot.py) — a fleet member then cold-starts by
    # deserializing instead of compiling.  Load side needs no flag: a
    # pack that matches the host fingerprint is used, anything else
    # falls back to jit.
    aot_pack: bool = False
    # warm EVERY bucket of the ladder (largest-first, small thread pool)
    # before a load/swap flips the registry pointer — so a post-failover
    # burst at any batch size never compiles in the hot path.  False
    # restores the old single 1-row warm.
    prewarm_ladder: bool = True

    def validate(self) -> None:
        if self.engine not in ("auto", "native", "numpy", "stablehlo",
                               "jax", "aot"):
            raise ConfigError(f"serving.engine must be one of auto/native/"
                              f"numpy/stablehlo/jax/aot: {self.engine!r}")
        if self.latency_budget_ms <= 0:
            raise ConfigError("serving.latency_budget_ms must be > 0: "
                              f"{self.latency_budget_ms}")
        if self.max_batch < 1 or self.min_batch_bucket < 1:
            raise ConfigError("serving.max_batch and min_batch_bucket must "
                              "be >= 1")
        if self.min_batch_bucket > self.max_batch:
            raise ConfigError(
                f"serving.min_batch_bucket ({self.min_batch_bucket}) must "
                f"not exceed max_batch ({self.max_batch})")
        if self.queue_limit < 1:
            raise ConfigError("serving.queue_limit must be >= 1")
        if self.workers < 1:
            raise ConfigError("serving.workers must be >= 1")
        if self.report_every_s < 0:
            raise ConfigError("serving.report_every_s must be >= 0")
        if not (0 <= self.port <= 65535):
            raise ConfigError(f"serving.port out of range: {self.port}")
        if self.trace_sample < 0:
            raise ConfigError("serving.trace_sample must be >= 0 "
                              f"(0 = off, N = 1-in-N): {self.trace_sample}")
        if self.trace_exemplars < 0:
            raise ConfigError("serving.trace-exemplars must be >= 0: "
                              f"{self.trace_exemplars}")
        if self.slo_p99_ms < 0:
            raise ConfigError(
                f"serving.slo.p99-ms must be >= 0: {self.slo_p99_ms}")
        if not (0 <= self.slo_error_rate < 1):
            raise ConfigError("serving.slo.error-rate must be in [0, 1): "
                              f"{self.slo_error_rate}")
        if not (0 <= self.slo_availability < 1):
            raise ConfigError("serving.slo.availability must be in [0, 1): "
                              f"{self.slo_availability}")
        if self.slo_fast_window_s <= 0 \
                or self.slo_slow_window_s < self.slo_fast_window_s:
            raise ConfigError(
                "serving SLO windows need 0 < slo_fast_window_s <= "
                f"slo_slow_window_s: {self.slo_fast_window_s}/"
                f"{self.slo_slow_window_s}")
        if self.slo_burn_threshold < 1:
            raise ConfigError("serving.slo.burn-threshold must be >= 1: "
                              f"{self.slo_burn_threshold}")
        self.drift.validate()


@dataclass(frozen=True)
class FleetConfig:
    """Knobs for the serving fleet (`shifu-tpu fleet`, runtime/fleet.py —
    docs/SERVING.md "Fleet").

    XML spelling `shifu.fleet.*` (utils/xmlconfig.fleet_config_from_conf)
    layers under CLI flags exactly like ServingConfig does.  The fleet is
    the successor of the reference AM's container supervision: N scoring
    daemons + hot-standby backups, heartbeat membership, a routing
    front-end, and burn-rate-driven scale decisions."""

    # active scoring daemons the manager keeps in rotation
    n_daemons: int = 2
    # pre-warmed hot standbys (loaded on the current artifact, wire
    # server bound, OUT of rotation) promoted on a member failure
    standbys: int = 1
    # heartbeat cadence: every member writes a lease this often; a lease
    # older than heartbeat_every_s * heartbeat_misses marks the member
    # DOWN and triggers failover
    heartbeat_every_s: float = 0.5
    heartbeat_misses: int = 3
    # router: per-request round-trip timeout before the one hedged retry
    # to a healthy peer, and the connect timeout for (re)building a
    # member connection
    route_timeout_ms: float = 1000.0
    connect_timeout_ms: float = 250.0
    # overload shedding: a primary whose fast-window slo_burn_rate is at
    # or above this routes around to the least-burned member
    shed_burn: float = 1.0
    # decorrelated-jitter reconnect backoff bounds for a member the
    # router observed failing (same shape as fsio's retry ladder)
    backoff_base_ms: float = 50.0
    backoff_cap_ms: float = 2000.0
    # scale loop: 0 disables; both burn windows must agree (fast AND
    # slow >= scale_up_burn on the worst member -> spawn; fast AND slow
    # <= scale_down_burn on every member -> retire) with a cooldown
    # between decisions
    scale_every_s: float = 0.0
    scale_up_burn: float = 2.0
    scale_down_burn: float = 0.25
    scale_cooldown_s: float = 30.0
    min_daemons: int = 1
    max_daemons: int = 8
    # consistent-ring virtual nodes per member (per-model routing)
    vnodes: int = 32
    # --- host plane (cross-host fleet, docs/SERVING.md) ---
    # launcher/pod.py host grammar: "" = single-host in-proc fleet (the
    # pre-host-plane behavior), "local:N" = N simulated hosts (tier-1
    # drills), "h1,h2"/"@file" = one `shifu-tpu serve` member per slot
    # over ssh
    hosts: str = ""
    # member spawn mode: "auto" (in-proc on local transport, process on
    # ssh), or force "inproc"/"process"
    member_mode: str = "auto"
    # first wire port for process-mode members (member i binds base+i)
    member_port_base: int = 8600
    # atomic artifact sync: each host pulls the export once, verifies it
    # against the exporter's blake2b manifest, atomically renames into
    # its cache, and only then swaps (torn/corrupt pulls quarantine the
    # member; the old version keeps serving)
    sync_artifacts: bool = True
    # split-brain guard: a DOWN member whose lease resurrects (partition
    # healed) rejoins as a STANDBY — never re-promoted into its old slot
    rejoin_standby: bool = True
    # fleet timeline (obs/timeline.py): estimate per-host clock offsets
    # from lease round-trips and merge member journals in the corrected
    # order; off = raw per-journal timestamps (debugging the estimator)
    timeline_skew_correct: bool = True
    # clamp on any single host's estimated |offset| — a lease stamped by
    # a wildly wrong clock must not fling the merged timeline
    timeline_max_offset_s: float = 300.0

    @property
    def heartbeat_ttl_s(self) -> float:
        """Lease freshness bound: miss this many beats -> DOWN."""
        return self.heartbeat_every_s * self.heartbeat_misses

    def validate(self) -> None:
        if self.n_daemons < 1:
            raise ConfigError(f"fleet.n-daemons must be >= 1: "
                              f"{self.n_daemons}")
        if self.standbys < 0:
            raise ConfigError(f"fleet.standbys must be >= 0: "
                              f"{self.standbys}")
        if self.heartbeat_every_s <= 0:
            raise ConfigError("fleet.heartbeat-every-s must be > 0: "
                              f"{self.heartbeat_every_s}")
        if self.heartbeat_misses < 1:
            raise ConfigError("fleet.heartbeat-misses must be >= 1: "
                              f"{self.heartbeat_misses}")
        if self.route_timeout_ms <= 0 or self.connect_timeout_ms <= 0:
            raise ConfigError("fleet.route-timeout-ms and "
                              "connect-timeout-ms must be > 0")
        if self.shed_burn <= 0:
            raise ConfigError(f"fleet.shed-burn must be > 0: "
                              f"{self.shed_burn}")
        if self.backoff_base_ms <= 0 \
                or self.backoff_cap_ms < self.backoff_base_ms:
            raise ConfigError(
                "fleet backoff needs 0 < backoff-base-ms <= "
                f"backoff-cap-ms: {self.backoff_base_ms}/"
                f"{self.backoff_cap_ms}")
        if self.scale_every_s < 0 or self.scale_cooldown_s < 0:
            raise ConfigError("fleet.scale-every-s and scale-cooldown-s "
                              "must be >= 0")
        if self.scale_down_burn < 0 \
                or self.scale_up_burn <= self.scale_down_burn:
            raise ConfigError(
                "fleet scale thresholds need 0 <= scale-down-burn < "
                f"scale-up-burn: {self.scale_down_burn}/"
                f"{self.scale_up_burn}")
        if not (1 <= self.min_daemons <= self.max_daemons):
            raise ConfigError(
                "fleet daemon bounds need 1 <= min-daemons <= "
                f"max-daemons: {self.min_daemons}/{self.max_daemons}")
        if not (self.min_daemons <= self.n_daemons <= self.max_daemons):
            raise ConfigError(
                f"fleet.n-daemons ({self.n_daemons}) must sit within "
                f"[min-daemons, max-daemons] = [{self.min_daemons}, "
                f"{self.max_daemons}]")
        if self.vnodes < 1:
            raise ConfigError(f"fleet.vnodes must be >= 1: {self.vnodes}")
        if self.member_mode not in ("auto", "inproc", "process"):
            raise ConfigError(
                "fleet.member-mode must be auto/inproc/process: "
                f"{self.member_mode!r}")
        if not (0 < self.member_port_base < 65536):
            raise ConfigError(
                f"fleet.member-port-base out of range: "
                f"{self.member_port_base}")
        if self.timeline_max_offset_s <= 0:
            raise ConfigError(
                "fleet.timeline-max-offset-s must be > 0: "
                f"{self.timeline_max_offset_s}")
        if self.hosts:
            # fail at config time, not at fleet start: the same grammar
            # parse_hosts uses later, minus the file read for @lists
            h = self.hosts.strip()
            if h.startswith("local:"):
                try:
                    n = int(h.split(":", 1)[1])
                except ValueError:
                    n = 0
                if n < 1:
                    raise ConfigError(
                        f"fleet.hosts {self.hosts!r}: need local:N "
                        "with N >= 1")
            elif not h.startswith("@") \
                    and not [x for x in h.split(",") if x.strip()]:
                raise ConfigError(f"fleet.hosts {self.hosts!r}: no hosts")


# ---------------------------------------------------------------------------
# Runtime / parallelism
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class MeshConfig:
    """Logical device mesh.

    Replaces the reference's PS/worker container topology
    (yarn/util/CommonUtils.java:336-369 parseContainerRequests): `data` is the
    batch (data-parallel) axis — the successor of N workers; `model` shards
    parameters/embedding vocab — the successor of variable placement across PS
    tasks (ssgd_monitor.py:202-206 replica_device_setter); `seq` is the
    sequence/context-parallel axis for attention over long token axes.
    """

    data: int = 1
    model: int = 1
    seq: int = 1
    # pipeline-parallel axis: transformer stages hold disjoint layer blocks,
    # activations hop stage->stage over ICI (parallel/pipeline.py)
    pipe: int = 1
    axis_order: tuple[str, ...] = ("data", "seq", "pipe", "model")

    @property
    def num_devices(self) -> int:
        return self.data * self.model * self.seq * self.pipe

    def validate(self) -> None:
        for name in ("data", "model", "seq", "pipe"):
            if getattr(self, name) < 1:
                raise ConfigError(f"mesh axis {name} must be >= 1")
        known = {"data", "seq", "pipe", "model"}
        if not set(self.axis_order) <= known or len(set(self.axis_order)) != len(self.axis_order):
            raise ConfigError(f"axis_order must be distinct axes from {sorted(known)}: "
                              f"{self.axis_order}")
        for name in known - set(self.axis_order):
            if getattr(self, name) != 1:
                raise ConfigError(f"mesh axis {name} > 1 but missing from axis_order")


@dataclass(frozen=True)
class CheckpointConfig:
    directory: str = ""
    save_every_epochs: int = 1
    # time-based cadence (reference parity: Supervisor save_model_secs=10,
    # ssgd.py:124-128): also save mid-epoch when this many seconds elapsed
    # since the last save — per batch on the per-batch tier, per chunk on
    # the staged/streamed tiers (whose long out-of-HBM epochs are exactly
    # where mid-epoch durability matters).  0 disables.  A mid-epoch save
    # records the CURRENT epoch, so resume replays the interrupted epoch
    # from its start — a bounded re-application window, the price of
    # mid-epoch durability (the reference's restore was equally coarse).
    save_every_seconds: int = 0
    max_to_keep: int = 3
    resume: bool = True             # auto-resume from newest checkpoint (reference: MonitoredTrainingSession checkpoint_dir, ssgd_monitor.py:251-257)
    # async saves overlap checkpoint IO with the next epoch's compute.  Off
    # by default: the synchronous contract ("the save is durable before the
    # epoch callback runs, so an external kill never loses a completed
    # epoch") is the stronger fault-tolerance guarantee; turn on for large
    # models where the save stall matters and losing the newest in-flight
    # checkpoint to a kill only costs one extra epoch of recompute.
    async_save: bool = False


@dataclass(frozen=True)
class RuntimeConfig:
    mesh: MeshConfig = field(default_factory=MeshConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    # job-level controls (successors of shifu.application.* keys,
    # GlobalConfigurationKeys.java:34-60)
    app_name: str = "shifu_tpu"
    timeout_seconds: int = 0        # 0: no timeout; reference client kills the YARN app on timeout (TensorflowClient.java:625-658)
    max_restarts: int = 2           # checkpoint-restart budget; successor of backup-worker promotion (TensorflowApplicationMaster.java:410-426)
    # Supervisor liveness window (`shifu.liveness.seconds`): if the console
    # board stops growing for this long the child is presumed hung, killed,
    # and restarted (charging the restart budget) — successor of the AM's
    # heartbeat-expiry monitor (TensorflowApplicationMaster.java:63-112,
    # 1s x 25 misses).  Default 0 = off: the board is written once per
    # EPOCH, so a sane window must exceed the job's epoch time — a fixed
    # 25s default would false-kill any long epoch.
    liveness_seconds: float = 0.0
    # Elastic reshape floor (`shifu.pod.min-hosts`): when a pod gang
    # exhausts its restart budget and the SAME host keeps failing, the
    # dispatcher drops that host and restarts the gang at the reduced
    # world size (file shards rebalance through the env contract, the
    # global batch re-rounds to the new mesh, training resumes from
    # checkpoint) — as long as at least this many hosts remain.  The SPMD
    # successor of the reference's degraded start, which launched with
    # >= 95% of requested workers and re-packed task indices
    # (TensorflowApplicationMaster.java:230-338, thresholds
    # Constants.java:91-94).  0 = off (same-shape restarts only).
    min_hosts: int = 0
    final_model_path: str = ""      # FINAL_MODEL_PATH env in the reference
    tmp_model_path: str = ""        # TMP_MODEL_PATH env in the reference
    # Kerberos for secured HDFS access — successor of the reference client's
    # delegation-token fetch (TensorflowClient.java:481-502); a configured
    # principal+keytab runs kinit before data access, otherwise the ambient
    # ticket cache is used (libhdfs via pyarrow.fs picks it up)
    kerberos_principal: str = ""
    kerberos_keytab: str = ""
    distributed: bool = False       # multi-host: jax.distributed.initialize
    # tensor-parallel / custom parameter sharding from config: ordered
    # (param-path regex, per-dim axis names) rules, first match wins, axes
    # from the mesh ("data"/"seq"/"pipe"/"model") or None for unsharded.
    # XML: shifu.sharding.rules = "regex=axis,axis;regex2=axis" (see
    # utils/xmlconfig.parse_sharding_rules).  Applied before the built-in
    # embedding/pipeline rules in train/loop.init_state.
    param_sharding_rules: tuple[tuple[str, tuple[Optional[str], ...]], ...] = ()


# ---------------------------------------------------------------------------
# The whole job
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class JobConfig:
    schema: DataSchema = field(default_factory=DataSchema)
    data: DataConfig = field(default_factory=DataConfig)
    model: ModelSpec = field(default_factory=ModelSpec)
    train: TrainConfig = field(default_factory=TrainConfig)
    runtime: RuntimeConfig = field(default_factory=RuntimeConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    embed: EmbedConfig = field(default_factory=EmbedConfig)

    def validate(self) -> "JobConfig":
        self.schema.validate()
        self.data.validate()
        self.model.validate()
        self.train.validate()
        self.runtime.mesh.validate()
        self.obs.validate()
        self.embed.validate()
        if self.train.bagging_sample_rate < 1.0 and self.data.out_of_core:
            # subsampling fancy-indexes the dataset, which would materialize
            # memmap-backed out-of-core shards into RAM
            raise ConfigError("bagging_sample_rate < 1 is not supported with "
                              "out-of-core datasets")
        if self.data.wire_dtype == "int8" and self.schema.categorical_indices:
            # integer ids cannot ride an affine quantization grid (an id of
            # 300 would saturate at the clip); embedding models keep
            # f32/bf16 wire
            raise ConfigError(
                "wire_dtype=int8 requires a categorical-free feature matrix "
                f"({len(self.schema.categorical_indices)} categorical "
                "columns selected); use auto/bfloat16/float32")
        if (self.data.resident_format == "int8"
                and self.schema.categorical_indices):
            # the resident tier shares the wire_params affine grid
            raise ConfigError(
                "resident_format=int8 requires a categorical-free feature "
                f"matrix ({len(self.schema.categorical_indices)} categorical "
                "columns selected); use auto/wire")
        return self

    # -- serialization ------------------------------------------------------

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "JobConfig":
        return _from_dict(cls, d)

    @classmethod
    def from_json(cls, s: str) -> "JobConfig":
        return cls.from_dict(json.loads(s))

    def replace(self, **kw: Any) -> "JobConfig":
        return dataclasses.replace(self, **kw)


def _deep_tuple(v: Any) -> Any:
    """Lists (from JSON) to tuples at every nesting level — dataclass tuple
    fields like param_sharding_rules nest two deep, and equality/hash of the
    frozen configs requires tuples all the way down."""
    if isinstance(v, list):
        return tuple(_deep_tuple(x) for x in v)
    return v


def _from_dict(cls: type, d: Any) -> Any:
    """Recursively build a (possibly nested) dataclass from plain dicts/lists."""
    if not dataclasses.is_dataclass(cls):
        return d
    kwargs: dict[str, Any] = {}
    fields = {f.name: f for f in dataclasses.fields(cls)}
    for key, value in d.items():
        if key not in fields:
            raise ConfigError(f"unknown config key {key!r} for {cls.__name__}")
        f = fields[key]
        ftype = f.type if isinstance(f.type, type) else None
        # resolve nested dataclass types by inspecting the default factory
        default = f.default_factory() if f.default_factory is not dataclasses.MISSING else f.default  # type: ignore[misc]
        if dataclasses.is_dataclass(default) and isinstance(value, dict):
            kwargs[key] = _from_dict(type(default), value)
        elif key == "columns" and isinstance(value, (list, tuple)):
            kwargs[key] = tuple(_from_dict(ColumnSpec, v) if isinstance(v, dict) else v
                                for v in value)
        elif isinstance(value, list):
            kwargs[key] = _deep_tuple(value)
        else:
            kwargs[key] = value
    return cls(**kwargs)
