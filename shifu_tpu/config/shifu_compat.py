"""Ingestion of unchanged Shifu `ModelConfig.json` / `ColumnConfig.json`.

Compatibility north star: the Shifu pipeline (`init -> stats -> normalize ->
train -> eval`) keeps its JSON contracts; only the train/eval backends change.
The reference consumes these files in two places:

- the Java client ships them into every container
  (reference: yarn/client/TensorflowClient.java:356-382) and derives
  SELECTED_COLUMN_NUMS / TARGET_COLUMN_NUM / WEIGHT_COLUMN_NUM env vars
  (yarn/container/TensorflowTaskExecutor.java:200-238);
- the Python trainer reads topology + hyperparameters from
  ModelConfig.json train params NumHiddenLayers / NumHiddenNodes /
  ActivationFunc / LearningRate and train.numTrainEpochs
  (reference: resources/ssgd_monitor.py:91-107,177-183).

This module maps both files onto the typed `JobConfig` tree.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional, Sequence

from ..utils.xmlconfig import parse_bool
from .schema import (
    ColumnSpec,
    ConfigError,
    DataConfig,
    DataSchema,
    JobConfig,
    ModelSpec,
    OptimizerConfig,
    TrainConfig,
)

# Shifu columnFlag values (from Shifu's ColumnConfig model)
_FLAG_TARGET = "Target"
_FLAG_WEIGHT = "Weight"
_FLAG_META = "Meta"
_FLAG_FORCE_SELECT = "ForceSelect"
_FLAG_FORCE_REMOVE = "ForceRemove"

_ACTIVATION_ALIASES = {
    "sigmoid": "sigmoid",
    "tanh": "tanh",
    "relu": "relu",
    "leakyrelu": "leakyrelu",
    "leaky_relu": "leakyrelu",
}

# Shifu `train.algorithm` / params -> shifu_tpu model_type
_ALGORITHM_TO_MODEL_TYPE = {
    "NN": "mlp",
    "TENSORFLOW": "mlp",
    "WDL": "wide_deep",
    "WIDEDEEP": "wide_deep",
    "WIDE_DEEP": "wide_deep",
    "DEEPFM": "deepfm",
    "MTL": "multitask",
    "MULTITASK": "multitask",
    "FTTRANSFORMER": "ft_transformer",
    "FT_TRANSFORMER": "ft_transformer",
    "MOE": "moe_mlp",
    "MOE_MLP": "moe_mlp",
}


def _norm_delimiter(value: Any) -> str:
    """dataSet.dataDelimiter is a Java regex in Shifu: unescape escaped
    literal characters ("\\|" -> "|", "\\t" -> tab); empty/missing means
    the pipe default.  Regex character classes ("\\s", "\\d", ...) have no
    literal-delimiter equivalent and are rejected up front rather than
    silently splitting rows on a letter; likewise anything that unescapes
    to more than one character (e.g. "\\|\\|") is a regex pattern, not a
    delimiter, and would silently split nothing if taken literally."""
    d = str(value or "|")
    out: list[str] = []
    unescaped_meta = False
    i = 0
    while i < len(d):
        c = d[i]
        if c == "\\" and i + 1 < len(d):
            nxt = d[i + 1]
            if nxt == "t":
                out.append("\t")
            elif not nxt.isalnum():  # escaped punctuation: the literal char
                out.append(nxt)
            else:
                raise ConfigError(
                    f"dataSet.dataDelimiter {d!r} contains the regex "
                    f"character class \\{nxt}; use a literal delimiter "
                    "character instead")
            i += 2
            continue
        if c in "|.*+?()[]{}^$":
            unescaped_meta = True
        out.append(c)
        i += 1
    lit = "".join(out)
    # metachar-free multi-char strings ("::", or fully escaped "\\|\\|")
    # are literal delimiters under Java regex too — the reader's multi-char
    # split path handles them.  Multi-char strings with UNESCAPED
    # metacharacters ("||" = alternation) are genuine regex patterns with
    # no literal-delimiter equivalent: reject rather than split on the
    # wrong literal.  (A lone unescaped metachar keeps its historical
    # literal reading — "|" is the default delimiter.)
    if len(lit) > 1 and unescaped_meta:
        raise ConfigError(
            f"dataSet.dataDelimiter {d!r} is a multi-character regex "
            "pattern with unescaped metacharacters; escape them "
            "(e.g. '\\\\|\\\\|') or use a literal delimiter")
    return lit


def _norm_activation(name: Optional[str]) -> str:
    # Reference: unknown/None activation falls back to leaky_relu
    # (ssgd_monitor.py:77-90).
    if not name:
        return "leakyrelu"
    return _ACTIVATION_ALIASES.get(str(name).lower(), "leakyrelu")


def load_json(path: str) -> Any:
    with open(path, "r") as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# ColumnConfig.json -> DataSchema
# ---------------------------------------------------------------------------

def parse_column_config(
    column_config: Sequence[dict[str, Any]],
    target_column_name: Optional[str] = None,
    weight_column_name: Optional[str] = None,
    multi_target_names: Optional[Sequence[str]] = None,
) -> DataSchema:
    """Build a DataSchema from Shifu's ColumnConfig.json list.

    Selection semantics mirror the reference's env-var derivation: selected
    features are `finalSelect` columns that are not target/weight/meta; the
    target/weight columns come from flags or from ModelConfig's dataSet
    section.  A column is categorical when columnType == "C".
    """
    columns: list[ColumnSpec] = []
    target_index = -1
    weight_index = -1
    selected: list[int] = []
    multi_targets = list(multi_target_names or [])
    target_index_by_name: dict[str, int] = {}

    for entry in column_config:
        index = int(entry.get("columnNum", entry.get("index", len(columns))))
        name = str(entry.get("columnName", f"col_{index}"))
        flag = entry.get("columnFlag")
        ctype = str(entry.get("columnType", "N") or "N").upper()
        final_select = bool(entry.get("finalSelect", False))

        is_target = (flag == _FLAG_TARGET) or (
            target_column_name is not None and name == target_column_name) or (
            name in multi_targets)
        if name in multi_targets:
            target_index_by_name[name] = index
        is_weight = (flag == _FLAG_WEIGHT) or (
            weight_column_name is not None and name == weight_column_name)
        is_meta = flag == _FLAG_META
        is_categorical = ctype.startswith("C")

        vocab_size = 0
        if is_categorical:
            binning = entry.get("columnBinning") or {}
            categories = binning.get("binCategory") or entry.get("binCategory") or []
            # +1 for the unseen/missing bucket, matching Shifu's binning where
            # unknown categories land in an extra bin.
            vocab_size = len(categories) + 1 if categories else 0

        is_selected = final_select and not (is_target or is_weight or is_meta)
        spec = ColumnSpec(
            index=index,
            name=name,
            is_target=is_target,
            is_weight=is_weight,
            is_selected=is_selected,
            is_categorical=is_categorical,
            vocab_size=vocab_size,
        )
        columns.append(spec)
        if is_target:
            target_index = index
        if is_weight:
            weight_index = index
        if is_selected:
            selected.append(index)

    if not selected:
        # Reference fallback: if no columns are selected, use every column
        # except target and weight (ssgd_monitor.py:388-393).
        selected = [c.index for c in columns
                    if not (c.is_target or c.is_weight or c.index in (target_index, weight_index))]
        columns = [ColumnSpec(**{**c.__dict__, "is_selected": c.index in set(selected)})
                   for c in columns]

    target_indices = tuple(target_index_by_name[n] for n in multi_targets
                           if n in target_index_by_name)
    if target_indices and target_index < 0:
        target_index = target_indices[0]
    schema = DataSchema(
        columns=tuple(columns),
        target_index=target_index,
        weight_index=weight_index,
        selected_indices=tuple(sorted(selected)),
        target_indices=target_indices,
    )
    schema.validate()
    return schema


# ---------------------------------------------------------------------------
# ModelConfig.json -> ModelSpec / TrainConfig / DataConfig pieces
# ---------------------------------------------------------------------------

def parse_model_config(model_config: dict[str, Any]) -> tuple[ModelSpec, TrainConfig, dict[str, Any]]:
    """Parse Shifu's ModelConfig.json `train` section.

    Returns (ModelSpec, TrainConfig, dataset_section) where dataset_section is
    ModelConfig's `dataSet` dict (for target/weight column names and the data
    path).
    """
    train = model_config.get("train", {}) or {}
    params = train.get("params", {}) or {}
    dataset = model_config.get("dataSet", {}) or {}

    num_hidden_layers = int(params.get("NumHiddenLayers", 1))
    hidden_nodes = [int(s) for s in params.get("NumHiddenNodes", [20])]
    activations = [_norm_activation(s) for s in params.get("ActivationFunc", [None])]
    # Clamp lists to NumHiddenLayers the way the reference indexes them
    # (ssgd_monitor.py:95-106 iterates range(num_hidden_layer)).
    if len(hidden_nodes) < num_hidden_layers:
        raise ConfigError(
            f"NumHiddenNodes has {len(hidden_nodes)} entries < NumHiddenLayers={num_hidden_layers}")
    hidden_nodes = hidden_nodes[:num_hidden_layers]
    if len(activations) < num_hidden_layers:
        activations = activations + [activations[-1]] * (num_hidden_layers - len(activations))
    activations = activations[:num_hidden_layers]

    algorithm = str(train.get("algorithm", "NN") or "NN").upper()
    model_type = _ALGORITHM_TO_MODEL_TYPE.get(algorithm, "mlp")
    # SAGN = the reference's local-SGD trainer (resources/SAGN.py): same MLP,
    # K=5 local plain-SGD updates per global sync (update_window=5,
    # SAGN.py:110-142); params.LocalSgdWindow overrides / enables it for any
    # algorithm
    local_sgd_window = int(params.get(
        "LocalSgdWindow", 5 if algorithm == "SAGN" else 0))
    # Explicit override hook for new model families wired through the Shifu
    # train step (BASELINE configs 2-5): params.ModelType wins over algorithm.
    if "ModelType" in params:
        model_type = str(params["ModelType"]).lower()

    head_names: list[str] = ["shifu_output_0"]
    num_heads = 1
    multi_targets = dataset.get("multiTargetColumnNames") or params.get("TargetNames")
    if model_type == "multitask" and multi_targets:
        num_heads = len(multi_targets)
        head_names = [f"shifu_output_{i}" for i in range(num_heads)]

    model_spec = ModelSpec(
        model_type=model_type,
        hidden_nodes=tuple(hidden_nodes),
        activations=tuple(activations),
        embedding_dim=int(params.get("EmbeddingDim", 16)),
        num_experts=int(params.get("NumExperts", 4)),
        num_heads=num_heads,
        head_names=tuple(head_names),
        num_layers=int(params.get("NumTransformerLayers",
                                  params.get("NumLayers", 3))),
        num_attention_heads=int(params.get("NumAttentionHeads", 8)),
        token_dim=int(params.get("TokenDim", 64)),
        dropout_rate=float(params.get("DropoutRate", 0.0)),
        attention_impl=str(params.get("AttentionImpl", "local")).lower(),
        pipeline_stages=int(params.get("PipelineStages", 1)),
        pipeline_microbatches=int(params.get("PipelineMicrobatches", 0)),
        remat=parse_bool(params.get("Remat", False)),
    )

    lr = float(params.get("LearningRate", 0.003))  # reference fallback 0.003 (ssgd_monitor.py:136)
    # An explicit params.Optimizer wins; otherwise legacy Propagation codes.
    # Local-SGD mode: the reference SAGN trainer ignores Propagation and
    # uses AdamOptimizer for BOTH its local window updates and the global
    # apply (the GradientDescentOptimizer lines are commented out —
    # SAGN.py:107-108,158-159).  The TPU local-SGD tier implements
    # plain-SGD local updates instead (per-replica adaptive state on
    # diverged replicas has no reference-sound semantics; see
    # TrainConfig.validate and PARITY.md "Local SGD"), so Optimizer
    # defaults to sgd here — a KNOWN, documented deviation from the
    # reference's optimizer family.
    if local_sgd_window > 0:
        opt_name = str(params.get("Optimizer", "sgd")).lower()
        # The param-averaging formulation advances the persistent params by
        # ~K*lr per window where the reference advanced by one LearningRate
        # step of the window-mean grad (SAGN.py:137-167); dividing the
        # mapped lr by K keeps a migrated SAGN config's effective step size
        # at its LearningRate instead of silently K x larger.
        lr = lr / local_sgd_window
    else:
        opt_name = str(params.get(
            "Optimizer", params.get("Propagation", "adadelta"))).lower()
    optimizer = OptimizerConfig(
        name=opt_name,
        learning_rate=lr,
        accumulate_steps=int(params.get("AccumulateSteps", 1)),
        schedule=str(params.get("LearningRateSchedule", "constant")).lower(),
        warmup_steps=int(params.get("WarmupSteps", 0)),
        decay_steps=int(params.get("DecaySteps", 0)),
        decay_rate=float(params.get("DecayRate", 0.96)),
        end_lr_factor=float(params.get("EndLearningRateFactor", 0.0)),
    )
    # Shifu Propagation codes (Q=quick/adadelta-era encog codes) all map to the
    # reference backend's single behavior: Adadelta (ssgd_monitor.py:140).
    if optimizer.name in ("q", "b", "r", "quick", "back", "resilient"):
        import dataclasses as _dc
        optimizer = _dc.replace(optimizer, name="adadelta")

    # Shifu ModelConfigs conventionally carry Loss='squared' (which the
    # reference ignored, always using weighted MSE — ssgd_monitor.py:129) or
    # 'log'; map those onto the equivalent losses here.
    loss_name = str(params.get("Loss", "weighted_mse")).lower()
    loss_name = {"squared": "weighted_mse", "log": "weighted_bce"}.get(loss_name, loss_name)
    train_config = TrainConfig(
        epochs=int(train.get("numTrainEpochs", 100)),
        loss=loss_name,
        optimizer=optimizer,
        bagging_sample_rate=float(train.get("baggingSampleRate", 1.0)),
        early_stop_patience=int(params.get("EarlyStopPatience", 0)),
        early_stop_min_delta=float(params.get("EarlyStopMinDelta", 0.0)),
        local_sgd_window=local_sgd_window,
    )
    train_config.validate()
    model_spec.validate()
    return model_spec, train_config, dataset


# ---------------------------------------------------------------------------
# Whole-job assembly
# ---------------------------------------------------------------------------

def job_config_from_shifu(
    model_config_path: str,
    column_config_path: str,
    data_paths: Sequence[str] = (),
    **overrides: Any,
) -> JobConfig:
    """Build a complete JobConfig from unchanged Shifu JSON files.

    `overrides` are applied onto the top-level JobConfig via dataclasses.replace
    (e.g. runtime=..., data=...).
    """
    model_config = load_json(model_config_path)
    model_spec, train_config, dataset = parse_model_config(model_config)

    column_config = load_json(column_config_path)
    schema = parse_column_config(
        column_config,
        target_column_name=dataset.get("targetColumnName"),
        weight_column_name=dataset.get("weightColumnName"),
        multi_target_names=dataset.get("multiTargetColumnNames"),
    )

    valid_ratio = float((model_config.get("train") or {}).get("validSetRate", 0.1))
    paths = tuple(data_paths)
    if not paths:
        data_path = dataset.get("dataPath") or ""
        if data_path:
            paths = (str(data_path),)

    # dataSet.dataDelimiter rides into the reader (the reference hardcoded
    # '|' regardless — ssgd_monitor.py row split).  Shifu treats the field
    # as a Java regex, so configs commonly carry escaped forms ("\\|",
    # "\\t"); normalize those to the literal character.
    data_config = DataConfig(paths=paths, valid_ratio=valid_ratio,
                             delimiter=_norm_delimiter(
                                 dataset.get("dataDelimiter")))

    job = JobConfig(schema=schema, data=data_config, model=model_spec, train=train_config)
    if overrides:
        job = job.replace(**overrides)
    return job.validate()
