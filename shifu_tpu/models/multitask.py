"""Multi-task model: shared trunk + per-task towers (BASELINE ladder config
#4: fraud + chargeback heads, Shifu multi-target mode).

New capability over the reference (single sigmoid head only,
resources/ssgd_monitor.py:121).  Each task h gets its own small tower and a
logit; heads are named `shifu_output_{h}` so the export sidecar enumerates
them; the loss averages per-head weighted losses (ops/losses.multitask_loss).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import ModelSpec
from .base import MLPTrunk, ShifuDense, dtype_of


class MultiTask(nn.Module):
    spec: ModelSpec

    @nn.compact
    def __call__(self, features: jax.Array, *, train: bool = False) -> jax.Array:
        x = features.astype(dtype_of(self.spec.compute_dtype))
        trunk = MLPTrunk(spec=self.spec, name="trunk")(x, train=train)
        logits = []
        tower_width = max(self.spec.hidden_nodes[-1] // 2, 4)
        for h in range(self.spec.num_heads):
            t = ShifuDense(features=tower_width,
                           activation=self.spec.activations[-1],
                           xavier_bias=self.spec.xavier_bias_init,
                           param_dtype=self.spec.param_dtype,
                           compute_dtype=self.spec.compute_dtype,
                           name=f"tower_{h}")(trunk)
            logits.append(ShifuDense(features=1, activation=None,
                                     xavier_bias=self.spec.xavier_bias_init,
                                     param_dtype=self.spec.param_dtype,
                                     compute_dtype=self.spec.compute_dtype,
                                     name=f"shifu_output_{h}")(t))
        return jnp.concatenate(logits, axis=-1).astype(jnp.float32)
