"""Wide&Deep tabular model (BASELINE ladder config #2: ~1000-column
risk-scoring).  New capability over the reference (which only had the MLP);
wired through the same Shifu config/data contracts.

Wide: a linear model over numeric features + per-field categorical biases
(degree-1 memorization).  Deep: the ModelConfig MLP trunk over
[numeric, flattened categorical embeddings] (generalization).  Output head is
the reference-named `shifu_output_0` sigmoid (applied in the loss/scorer).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import DataSchema, ModelSpec
from ..ops.initializers import xavier_uniform
from .base import MLPTrunk, ShifuDense, dtype_of
from .embedding import (FieldLayout, field_layout, paired_cat_embed,
                        split_features)


class WideDeep(nn.Module):
    spec: ModelSpec
    layout: FieldLayout

    @nn.compact
    def __call__(self, features: jax.Array, *, train: bool = False) -> jax.Array:
        cdt = dtype_of(self.spec.compute_dtype)
        numeric, ids = split_features(features, self.layout)
        numeric = numeric.astype(cdt)

        # -- wide: linear numeric + categorical per-id bias ------------------
        wide = ShifuDense(features=self.spec.num_heads, activation=None,
                          xavier_bias=self.spec.xavier_bias_init,
                          param_dtype=self.spec.param_dtype,
                          compute_dtype=self.spec.compute_dtype,
                          name="wide_linear")(numeric)
        # wide per-id bias + deep embedding read the SAME ids: one fused
        # lookup (embedding.fused_lookup) — gather/segment-grad cost is
        # per-row, not per-byte
        emb = None
        if self.layout.num_categorical:
            emb, cat_bias = paired_cat_embed(
                self.layout, self.spec, "deep_embedding",
                "wide_cat_embedding", ids)
            wide = wide + jnp.sum(cat_bias, axis=1)

        # -- deep: MLP over [numeric, cat embeddings] ------------------------
        deep_in = numeric
        if emb is not None:
            deep_in = jnp.concatenate(
                [numeric, emb.reshape(emb.shape[0], -1)], axis=-1)
        deep = MLPTrunk(spec=self.spec, name="trunk")(deep_in, train=train)
        deep = ShifuDense(features=self.spec.num_heads, activation=None,
                          xavier_bias=self.spec.xavier_bias_init,
                          param_dtype=self.spec.param_dtype,
                          compute_dtype=self.spec.compute_dtype,
                          name="shifu_output_0")(deep)

        return (wide + deep).astype(jnp.float32)
