"""The Shifu MLP — parity model with the reference trainer's network.

Reference graph (resources/ssgd_monitor.py:93-129): input (B, F) float ->
N hidden xavier dense layers with per-layer activations from ModelConfig ->
Dense(1) sigmoid head `shifu_output_0`, trained with weighted MSE.  Here the
model emits logits (B, num_heads); sigmoid is applied by the loss and scorer.
"""

from __future__ import annotations

import flax.linen as nn
import jax

from ..config.schema import ModelSpec
from .base import MLPTrunk, ScoringHead, dtype_of


class ShifuMLP(nn.Module):
    spec: ModelSpec

    @nn.compact
    def __call__(self, features: jax.Array, *, train: bool = False) -> jax.Array:
        x = features.astype(dtype_of(self.spec.compute_dtype))
        x = MLPTrunk(spec=self.spec, name="trunk")(x, train=train)
        return ScoringHead(spec=self.spec, name="head")(x)
