"""The Shifu MLP — parity model with the reference trainer's network.

Reference graph (resources/ssgd_monitor.py:93-129): input (B, F) float ->
N hidden xavier dense layers with per-layer activations from ModelConfig ->
Dense(1) sigmoid head `shifu_output_0`, trained with weighted MSE.  Here the
model emits logits (B, num_heads); sigmoid is applied by the loss and scorer.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import ModelSpec
from .base import MLPTrunk, ScoringHead, dtype_of


class ShifuMLP(nn.Module):
    spec: ModelSpec
    # int8 wire grid (data/pipeline.wire_params) when the training loop
    # feeds wire-format features straight into the model; layer 0 then
    # fuses the dequant into its matmul (models/base._WireDense)
    wire: Optional[Tuple[Tuple[float, ...],
                         Optional[Tuple[float, ...]]]] = None

    @nn.compact
    def __call__(self, features: jax.Array, *, train: bool = False) -> jax.Array:
        if self.wire is not None and features.dtype == jnp.int8:
            x = features  # layer 0 consumes the wire format natively
        else:
            x = features.astype(dtype_of(self.spec.compute_dtype))
        x = MLPTrunk(spec=self.spec, wire=self.wire, name="trunk")(
            x, train=train)
        return ScoringHead(spec=self.spec, name="head")(x)
