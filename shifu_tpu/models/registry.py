"""Model factory: ModelSpec.model_type -> Flax module.

The model ladder tracks BASELINE.md's benchmark configs: MLP (parity with the
reference trainer), Wide&Deep, DeepFM, multi-task heads, FT-Transformer.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn

from ..config.schema import DataSchema, ModelSpec

_BUILDERS: dict[str, Callable[[ModelSpec, DataSchema], nn.Module]] = {}


def register(name: str):
    def deco(fn):
        _BUILDERS[name] = fn
        return fn
    return deco


def build_model(spec: ModelSpec, schema: DataSchema, mesh=None,
                wire=None) -> nn.Module:
    """`mesh` (jax.sharding.Mesh) is forwarded to models that can exploit it
    (FT-Transformer sequence-parallel attention).  Every registered builder
    must accept (spec, schema, mesh=None) and may ignore the mesh.  Scoring/
    export paths pass no mesh and get the single-host local-attention
    graph.

    `wire` is the int8 grid (scale_tuple, offset_tuple_or_None) from
    data/pipeline.wire_params when the training loop feeds wire-format
    int8 features into the model (train/step.wire_fused_into_model); the
    MLP builder attaches it to layer 0 so dequantization fuses into the
    first matmul.  Builders that never see wire inputs ignore it — the
    param tree is unchanged either way."""
    try:
        builder = _BUILDERS[spec.model_type]
    except KeyError:
        raise KeyError(
            f"unknown model_type {spec.model_type!r}; available: {sorted(_BUILDERS)}") from None
    if spec.model_type == "mlp" and wire is not None:
        return builder(spec, schema, mesh=mesh, wire=wire)
    return builder(spec, schema, mesh=mesh)


@register("mlp")
def _build_mlp(spec: ModelSpec, schema: DataSchema,
               mesh=None, wire=None) -> nn.Module:
    from .mlp import ShifuMLP
    return ShifuMLP(spec=spec, wire=wire)


@register("wide_deep")
def _build_wide_deep(spec: ModelSpec, schema: DataSchema,
                     mesh=None) -> nn.Module:
    from .embedding import field_layout
    from .wide_deep import WideDeep
    return WideDeep(spec=spec, layout=field_layout(schema))


@register("deepfm")
def _build_deepfm(spec: ModelSpec, schema: DataSchema,
                  mesh=None) -> nn.Module:
    from .deepfm import DeepFM
    from .embedding import field_layout
    return DeepFM(spec=spec, layout=field_layout(schema))


@register("multitask")
def _build_multitask(spec: ModelSpec, schema: DataSchema,
                     mesh=None) -> nn.Module:
    from .multitask import MultiTask
    return MultiTask(spec=spec)


@register("moe_mlp")
def _build_moe_mlp(spec: ModelSpec, schema: DataSchema,
                   mesh=None) -> nn.Module:
    from .moe import MoEMLP
    return MoEMLP(spec=spec)


@register("ft_transformer")
def _build_ft_transformer(spec: ModelSpec, schema: DataSchema,
                          mesh=None) -> nn.Module:
    from .embedding import field_layout
    from .ft_transformer import FTTransformer
    return FTTransformer(spec=spec, layout=field_layout(schema), mesh=mesh)
