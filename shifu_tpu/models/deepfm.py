"""DeepFM tabular model (BASELINE ladder config #3: CTR with
high-cardinality categoricals and a sharded embedding table).

Every selected column is a "field" with a k-dim latent vector: categorical
fields via table lookup, numeric fields via value-scaled vectors
(models/embedding.py).  Components share those vectors:

- first-order: sum of per-field scalar weights,
- FM second-order: 0.5 * ((sum_f v_f)^2 - sum_f v_f^2) summed over dims —
  all pairwise interactions in O(fields * dim),
- deep: the ModelConfig MLP trunk over the flattened field vectors.

The embedding tables match parallel/sharding.py's DEFAULT_RULES (vocab axis
on `model`) — the fresh design SURVEY.md section 7.3 called for, succeeding
PS-side variable placement.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import ModelSpec
from .base import MLPTrunk, ShifuDense, dtype_of
from .embedding import (FieldLayout, NumericEmbed, paired_cat_embed,
                        split_features)


class DeepFM(nn.Module):
    spec: ModelSpec
    layout: FieldLayout

    @nn.compact
    def __call__(self, features: jax.Array, *, train: bool = False) -> jax.Array:
        numeric, ids = split_features(features, self.layout)

        # field vectors (B, F, k): numeric + categorical share the FM space.
        # The k-dim FM/deep table and the scalar first-order table read the
        # SAME ids, so they share one fused lookup (embedding.fused_lookup)
        # — the gather/segment-grad cost is per-row, not per-byte.
        vecs = []
        cat_first = None
        if self.layout.num_numeric:
            vecs.append(NumericEmbed(layout=self.layout, dim=self.spec.embedding_dim,
                                     param_dtype=self.spec.param_dtype,
                                     compute_dtype=self.spec.compute_dtype,
                                     name="numeric_embedding")(numeric))
        if self.layout.num_categorical:
            cat_vec, cat_first = paired_cat_embed(
                self.layout, self.spec, "cat_embedding", "first_order_cat",
                ids)
            vecs.append(cat_vec)
        v = jnp.concatenate(vecs, axis=1)  # (B, F, k)

        # first-order terms (B, H)
        first = ShifuDense(features=self.spec.num_heads, activation=None,
                           xavier_bias=self.spec.xavier_bias_init,
                           param_dtype=self.spec.param_dtype,
                           compute_dtype=self.spec.compute_dtype,
                           name="first_order_numeric")(
            numeric.astype(dtype_of(self.spec.compute_dtype)))
        if cat_first is not None:
            first = first + jnp.sum(cat_first, axis=1)

        # FM second-order: 0.5 * ((sum v)^2 - sum v^2), summed over k -> (B, 1)
        sum_sq = jnp.square(jnp.sum(v, axis=1))
        sq_sum = jnp.sum(jnp.square(v), axis=1)
        fm = 0.5 * jnp.sum(sum_sq - sq_sum, axis=-1, keepdims=True)

        # deep over flattened field vectors
        deep = MLPTrunk(spec=self.spec, name="trunk")(v.reshape(v.shape[0], -1),
                                                      train=train)
        deep = ShifuDense(features=self.spec.num_heads, activation=None,
                          xavier_bias=self.spec.xavier_bias_init,
                          param_dtype=self.spec.param_dtype,
                          compute_dtype=self.spec.compute_dtype,
                          name="shifu_output_0")(deep)

        return (first + fm.astype(jnp.float32) + deep).astype(jnp.float32)
