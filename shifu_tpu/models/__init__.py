from .base import MLPTrunk, ScoringHead, ShifuDense
from .mlp import ShifuMLP
from .registry import build_model, register

__all__ = ["MLPTrunk", "ScoringHead", "ShifuDense", "ShifuMLP", "build_model", "register"]
