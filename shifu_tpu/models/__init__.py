from .base import MLPTrunk, ScoringHead, ShifuDense
from .deepfm import DeepFM
from .embedding import CategoricalEmbed, FieldLayout, NumericEmbed, field_layout, split_features
from .ft_transformer import FTTransformer
from .mlp import ShifuMLP
from .multitask import MultiTask
from .registry import build_model, register
from .wide_deep import WideDeep

__all__ = [
    "MLPTrunk", "ScoringHead", "ShifuDense", "DeepFM", "CategoricalEmbed",
    "FieldLayout", "NumericEmbed", "field_layout", "split_features",
    "FTTransformer", "ShifuMLP", "MultiTask", "build_model", "register",
    "WideDeep",
]
