"""Feature embeddings for the tabular model ladder.

Nothing like this exists in the reference (its MLP consumes pre-normalized
floats only — resources/ssgd_monitor.py:113-121); the design is fresh for the
BASELINE ladder's Wide&Deep / DeepFM / FT-Transformer rungs.  TPU-first
choices: one fused table per categorical field; lookups are `jnp.take` so XLA
lowers them to gathers that shard cleanly when tables carry a
`PartitionSpec("model", None)` (parallel/sharding.py DEFAULT_RULES) — the
successor of the reference's variables-on-PS placement
(ssgd_monitor.py:202-206), with the gather's collective riding ICI.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import DataSchema, ModelSpec
from ..ops.initializers import xavier_uniform
from .base import dtype_of


@dataclasses.dataclass(frozen=True)
class FieldLayout:
    """Positions of numeric vs categorical fields inside the (B, F) feature
    matrix (categorical cells hold integer ids stored as floats)."""

    numeric_positions: tuple[int, ...]
    categorical_positions: tuple[int, ...]
    vocab_sizes: tuple[int, ...]

    @property
    def num_numeric(self) -> int:
        return len(self.numeric_positions)

    @property
    def num_categorical(self) -> int:
        return len(self.categorical_positions)

    @property
    def num_fields(self) -> int:
        return self.num_numeric + self.num_categorical


def field_layout(schema: DataSchema) -> FieldLayout:
    cat_set = set(schema.categorical_indices)
    by_index = {c.index: c for c in schema.columns}
    numeric, cats, vocabs = [], [], []
    for pos, idx in enumerate(schema.selected_indices):
        if idx in cat_set:
            cats.append(pos)
            v = by_index[idx].vocab_size
            vocabs.append(v if v > 0 else 1024)  # hashed fallback vocab
        else:
            numeric.append(pos)
    return FieldLayout(tuple(numeric), tuple(cats), tuple(vocabs))


def split_features(features: jax.Array, layout: FieldLayout
                   ) -> tuple[jax.Array, jax.Array]:
    """(B, F) float -> (numeric (B, Nn) float, categorical ids (B, Nc) int32).

    Ids clip into [0, vocab): out-of-range/unseen ids land in the last bucket,
    matching Shifu's unseen-category bin behavior.  embed/dedup.host_ids is
    the host-side (numpy) replica of this extraction — the feeder's
    unique-id compaction must yield EXACTLY the forward's touched-row set,
    so any change here must land there too."""
    num = features[:, jnp.array(layout.numeric_positions, dtype=jnp.int32)] \
        if layout.num_numeric else jnp.zeros((features.shape[0], 0), features.dtype)
    if layout.num_categorical:
        raw = features[:, jnp.array(layout.categorical_positions, dtype=jnp.int32)]
        ids = raw.astype(jnp.int32)
        vocab = jnp.array(layout.vocab_sizes, dtype=jnp.int32)
        ids = jnp.clip(ids, 0, vocab - 1)
    else:
        ids = jnp.zeros((features.shape[0], 0), jnp.int32)
    return num, ids


class CategoricalEmbed(nn.Module):
    """Per-field embedding tables: ids (B, Nc) -> (B, Nc, dim).

    Tables are stacked per field (ragged vocabs padded to the max) so one
    gather serves all fields — fewer, larger ops for XLA, and a single
    sharding rule puts the vocab axis on `model`.  `table()` exposes the
    compute-dtype table so a caller holding several embeds over the SAME
    ids can concat along dim and pay ONE lookup (see fused_lookup) — the
    per-update cost of a gather/segment-grad pair is mostly per-row, not
    per-byte, so two lookups cost nearly twice one.
    """

    layout: FieldLayout
    dim: int
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"

    def setup(self):
        if self.layout.num_categorical:
            max_vocab = max(self.layout.vocab_sizes)
            # one stacked table (num_fields, max_vocab, dim); per-field rows
            # beyond that field's vocab are dead weight but keep shapes static
            self.embedding = self.param(
                "embedding", xavier_uniform,
                (self.layout.num_categorical, max_vocab, self.dim),
                dtype_of(self.param_dtype))

    def table(self) -> jax.Array:
        return self.embedding.astype(dtype_of(self.compute_dtype))

    def __call__(self, ids: jax.Array) -> jax.Array:
        if self.layout.num_categorical == 0:
            return jnp.zeros((ids.shape[0], 0, self.dim),
                             dtype_of(self.compute_dtype))
        # gather per field: ids (B, Nc) -> (B, Nc, dim).  Routed through
        # ops/pallas_embedding.embedding_lookup: XLA gather by default, the
        # manual-DMA Pallas kernel under SHIFU_TPU_PALLAS=1.
        from ..ops.pallas_embedding import embedding_lookup
        return embedding_lookup(self.table(), ids.astype(jnp.int32))


def fused_lookup(embeds: Sequence[CategoricalEmbed], ids: jax.Array
                 ) -> list[jax.Array]:
    """One lookup for several CategoricalEmbeds sharing the same ids.

    Concats the tables along dim (cheap: HBM copy, exact), gathers once,
    splits the result back per embed.  Identical values to calling each
    embed separately; roughly halves the sparse-path cost for the models
    that pair a k-dim FM/deep table with a scalar first-order table over
    the same fields (DeepFM, Wide&Deep).

    Under the SHIFU_TPU_PALLAS=1 opt-in the embeds are looked up
    separately instead: the manual-DMA kernel requires D % 128 == 0, and
    a concat of a 128-aligned table with a scalar one would silently
    demote BOTH to the XLA gather.
    """
    from ..ops.pallas_embedding import embedding_lookup
    from ..ops.pallas_common import pallas_opt_in

    if pallas_opt_in():
        return [e(ids) for e in embeds]
    fused = embedding_lookup(
        jnp.concatenate([e.table() for e in embeds], axis=-1),
        ids.astype(jnp.int32))
    outs, off = [], 0
    for e in embeds:
        outs.append(fused[..., off:off + e.dim])
        off += e.dim
    return outs


def paired_cat_embed(layout: FieldLayout, spec: ModelSpec, big_name: str,
                     small_name: str, ids: jax.Array
                     ) -> tuple[jax.Array, jax.Array]:
    """The (embedding_dim table, num_heads table) pair over shared ids
    that DeepFM and Wide&Deep both use, through one fused lookup.
    Returns ((B, Nc, embedding_dim), (B, Nc, num_heads))."""
    big, small = fused_lookup(
        [CategoricalEmbed(layout=layout, dim=spec.embedding_dim,
                          param_dtype=spec.param_dtype,
                          compute_dtype=spec.compute_dtype, name=big_name),
         CategoricalEmbed(layout=layout, dim=spec.num_heads,
                          param_dtype=spec.param_dtype,
                          compute_dtype=spec.compute_dtype,
                          name=small_name)], ids)
    return big, small


class NumericEmbed(nn.Module):
    """Numeric feature tokens: x_j -> x_j * w_j + b_j, (B, Nn) -> (B, Nn, dim).

    Used by DeepFM (value-scaled field vectors) and FT-Transformer (numeric
    tokenizer)."""

    layout: FieldLayout
    dim: int
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"

    @nn.compact
    def __call__(self, numeric: jax.Array) -> jax.Array:
        if self.layout.num_numeric == 0:
            return jnp.zeros((numeric.shape[0], 0, self.dim),
                             dtype_of(self.compute_dtype))
        w = self.param("weight", xavier_uniform,
                       (self.layout.num_numeric, self.dim),
                       dtype_of(self.param_dtype))
        b = self.param("bias", nn.initializers.zeros,
                       (self.layout.num_numeric, self.dim),
                       dtype_of(self.param_dtype))
        x = numeric.astype(dtype_of(self.compute_dtype))
        return x[:, :, None] * w[None, :, :] + b[None, :, :]
