"""FT-Transformer tabular model (BASELINE ladder config #5, stretch rung).

Feature Tokenizer + Transformer: every selected column becomes a token
(numeric: x_j * w_j + b_j; categorical: table lookup — models/embedding.py),
a CLS token is prepended, L pre-LN transformer blocks attend over the feature
axis, and the CLS representation feeds the `shifu_output_0` head.  New
capability over the reference (no attention anywhere — SURVEY.md section 5.7).

TPU-first notes: local attention routes through
ops/pallas_small_attention.small_token_attention — on TPU, small token
counts with small head dims take the batch-in-lanes pallas kernel (no
(S, S) score tensor in HBM, true f32 softmax; ~2.5x the XLA path on the
bench rung), everything else the XLA reference ops/attention.mha.  With a
`seq`-axis mesh the same math is available sequence-parallel via
ops/attention.ring_attention (feature-token counts ~10^2-10^3 fit
single-chip, so the model defaults to local attention).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import numpy as np
from jax.nn import initializers as jinit

from ..config.schema import ModelSpec
from ..ops.attention import ring_attention, ulysses_attention
from ..ops.pallas_attention import flash_attention
from ..ops.pallas_ft_block import (fused_block_engaged,
                                   fused_transformer_block)
from ..ops.pallas_small_attention import small_token_attention
from ..ops.initializers import xavier_uniform
from ..parallel.mesh import PIPE_AXIS, SEQ_AXIS
from .base import ShifuDense, dtype_of
from .embedding import (CategoricalEmbed, FieldLayout, NumericEmbed,
                        split_features)


def _seq_parallel_size(mesh: Optional[Mesh]) -> int:
    if mesh is None or SEQ_AXIS not in mesh.shape:
        return 1
    return int(mesh.shape[SEQ_AXIS])


def _pipe_parallel_size(mesh: Optional[Mesh]) -> int:
    if mesh is None or PIPE_AXIS not in mesh.shape:
        return 1
    return int(mesh.shape[PIPE_AXIS])


class _LNParams(nn.Module):
    """Param-holder twin of nn.LayerNorm: declares the identical
    scale/bias leaves (names, shapes, f32, init fns) without running the
    norm — the fused-block path reads them and normalizes in-kernel."""

    dim: int

    @nn.compact
    def __call__(self):
        return (self.param("scale", jinit.ones, (self.dim,), jnp.float32),
                self.param("bias", jinit.zeros, (self.dim,), jnp.float32))


class _DenseParams(nn.Module):
    """Param-holder twin of the block's nn.Dense layers (xavier kernel,
    zero bias) for the fused path; same tree, same init RNG draw."""

    in_dim: int
    out_dim: int
    param_dtype: str = "float32"

    @nn.compact
    def __call__(self):
        pdt = dtype_of(self.param_dtype)
        return (self.param("kernel", xavier_uniform,
                           (self.in_dim, self.out_dim), pdt),
                self.param("bias", jinit.zeros, (self.out_dim,), pdt))


class TransformerBlock(nn.Module):
    spec: ModelSpec
    mesh: Optional[Mesh] = None  # enables ring/ulysses when it has a seq axis

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        cdt = dtype_of(self.spec.compute_dtype)
        d = self.spec.token_dim
        h = self.spec.num_attention_heads
        assert d % h == 0, "token_dim must divide num_attention_heads"
        dh = d // h
        b, s, _ = x.shape
        n_sp = _seq_parallel_size(self.mesh)

        if fused_block_engaged(self.spec, s, train=train,
                               n_seq_parallel=n_sp):
            # one Pallas pass for the whole block (ops/pallas_ft_block):
            # param-holder children pin the exact tree of the unfused path
            # — checkpoints and exports are interchangeable between modes
            pdt = self.spec.param_dtype
            r = self.spec.mlp_ratio
            p = {}
            p["ln_attn_scale"], p["ln_attn_bias"] = (
                _LNParams(d, name="ln_attn")())
            p["qkv_kernel"], p["qkv_bias"] = (
                _DenseParams(d, 3 * d, pdt, name="qkv")())
            p["proj_kernel"], p["proj_bias"] = (
                _DenseParams(d, d, pdt, name="proj")())
            p["ln_mlp_scale"], p["ln_mlp_bias"] = (
                _LNParams(d, name="ln_mlp")())
            p["mlp_in_kernel"], p["mlp_in_bias"] = (
                _DenseParams(d, r * d, pdt, name="mlp_in")())
            p["mlp_out_kernel"], p["mlp_out_bias"] = (
                _DenseParams(r * d, d, pdt, name="mlp_out")())
            return fused_transformer_block(x, p, self.spec)

        # pre-LN attention
        y = nn.LayerNorm(dtype=cdt, name="ln_attn")(x)
        qkv = nn.Dense(3 * d, kernel_init=xavier_uniform, dtype=cdt,
                       param_dtype=dtype_of(self.spec.param_dtype),
                       name="qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, h, dh).transpose(0, 2, 1, 3)
        k = k.reshape(b, s, h, dh).transpose(0, 2, 1, 3)
        v = v.reshape(b, s, h, dh).transpose(0, 2, 1, 3)
        if self.spec.attention_impl == "flash":
            # blockwise Pallas kernel (O(S) memory per device); orthogonal to
            # the mesh — with a seq axis use ring/ulysses instead
            attn = flash_attention(q, k, v)
        elif self.spec.attention_impl != "local" and n_sp > 1:
            # sequence/context parallelism over the token axis; same math as
            # mha (tests/test_attention.py), collectives over ICI
            if s % n_sp != 0:
                raise ValueError(
                    f"attention_impl={self.spec.attention_impl!r} needs the "
                    f"token count ({s}) divisible by the seq mesh axis "
                    f"({n_sp}); pad features or adjust the mesh")
            sp = (ring_attention if self.spec.attention_impl == "ring"
                  else ulysses_attention)
            attn = sp(q, k, v, self.mesh)
        else:
            # auto-routes to the batch-in-lanes pallas kernel on TPU for
            # small token counts / head dims (feature-token attention's
            # shape), where the classic score tensor is lane-padding-bound;
            # falls back to mha everywhere else — and the kernel is the
            # MORE precise path (true f32 VPU vs single-pass bf16 MXU)
            attn = small_token_attention(q, k, v)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, s, d)
        attn = nn.Dense(d, kernel_init=xavier_uniform, dtype=cdt,
                        param_dtype=dtype_of(self.spec.param_dtype),
                        name="proj")(attn)
        if self.spec.dropout_rate > 0:
            attn = nn.Dropout(self.spec.dropout_rate, deterministic=not train)(attn)
        x = x + attn

        # pre-LN MLP
        y = nn.LayerNorm(dtype=cdt, name="ln_mlp")(x)
        y = nn.Dense(self.spec.mlp_ratio * d, kernel_init=xavier_uniform,
                     dtype=cdt, param_dtype=dtype_of(self.spec.param_dtype),
                     name="mlp_in")(y)
        y = nn.gelu(y)
        y = nn.Dense(d, kernel_init=xavier_uniform, dtype=cdt,
                     param_dtype=dtype_of(self.spec.param_dtype),
                     name="mlp_out")(y)
        if self.spec.dropout_rate > 0:
            y = nn.Dropout(self.spec.dropout_rate, deterministic=not train)(y)
        return x + y


# -- pipeline-parallel trunk -------------------------------------------------

# stacked param name -> canonical (module, leaf) path inside block_{i}/
_BLOCK_PARAM_PATHS = {
    "ln_attn_scale": ("ln_attn", "scale"), "ln_attn_bias": ("ln_attn", "bias"),
    "qkv_kernel": ("qkv", "kernel"), "qkv_bias": ("qkv", "bias"),
    "proj_kernel": ("proj", "kernel"), "proj_bias": ("proj", "bias"),
    "ln_mlp_scale": ("ln_mlp", "scale"), "ln_mlp_bias": ("ln_mlp", "bias"),
    "mlp_in_kernel": ("mlp_in", "kernel"), "mlp_in_bias": ("mlp_in", "bias"),
    "mlp_out_kernel": ("mlp_out", "kernel"), "mlp_out_bias": ("mlp_out", "bias"),
}


def _layernorm(x: jax.Array, scale: jax.Array, bias: jax.Array,
               cdt, eps: float = 1e-6) -> jax.Array:
    """Flax-default LayerNorm (float32 statistics, eps 1e-6) as a pure fn —
    the same math the artifact's `layernorm` op executes (export/program.py)."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(cdt)


def _block_forward(p: dict, x: jax.Array, spec: ModelSpec) -> jax.Array:
    """One pre-LN transformer block as a pure function over a param dict —
    the same math as TransformerBlock (module form), reused by the stacked
    (lax.scan) and pipelined (shard_map) trunks."""
    cdt = dtype_of(spec.compute_dtype)
    d = spec.token_dim
    h = spec.num_attention_heads
    dh = d // h
    b, s, _ = x.shape

    if fused_block_engaged(spec, s):
        # the stacked/pipelined trunks carry the same stacked-name dict the
        # fused kernel takes — route the whole block through one pass
        return fused_transformer_block(x, p, spec)

    y = _layernorm(x, p["ln_attn_scale"], p["ln_attn_bias"], cdt)
    qkv = y @ p["qkv_kernel"].astype(cdt) + p["qkv_bias"].astype(cdt)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, s, h, dh).transpose(0, 2, 1, 3)
    k = k.reshape(b, s, h, dh).transpose(0, 2, 1, 3)
    v = v.reshape(b, s, h, dh).transpose(0, 2, 1, 3)
    attn = (flash_attention(q, k, v) if spec.attention_impl == "flash"
            else small_token_attention(q, k, v))
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, d)
    attn = attn @ p["proj_kernel"].astype(cdt) + p["proj_bias"].astype(cdt)
    x = x + attn

    y = _layernorm(x, p["ln_mlp_scale"], p["ln_mlp_bias"], cdt)
    y = y @ p["mlp_in_kernel"].astype(cdt) + p["mlp_in_bias"].astype(cdt)
    y = nn.gelu(y)
    y = y @ p["mlp_out_kernel"].astype(cdt) + p["mlp_out_bias"].astype(cdt)
    return x + y


def make_stage_fn(spec: ModelSpec):
    """stage_fn(local_params, h) for parallel/pipeline.pipeline_apply: scan
    `_block_forward` over this stage's share of the stacked layers.  With
    spec.remat each block recomputes its activations in the backward pass
    (jax.checkpoint) instead of storing them across the scan."""
    block = lambda p, x: _block_forward(p, x, spec)
    if spec.remat:
        block = jax.checkpoint(block)

    def stage_fn(params, h):
        def body(carry, layer_params):
            return block(layer_params, carry), None
        out, _ = jax.lax.scan(body, h, params)
        return out
    return stage_fn


class StackedBlocks(nn.Module):
    """The transformer trunk with layer-stacked parameters (leaves
    (num_layers, ...)), enabling pipeline parallelism: with a `pipe` mesh
    axis the stacked leaves shard by stage (place_params rule in
    train/loop.init_state) and microbatches flow through
    parallel/pipeline.pipeline_apply; otherwise the same params run as one
    lax.scan.  `canonicalize_params` converts the stacked tree to the
    per-block module tree for export (export/artifact.py)."""

    spec: ModelSpec
    mesh: Optional[Mesh] = None

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        spec = self.spec
        L, d, r = spec.num_layers, spec.token_dim, spec.mlp_ratio
        pdt = dtype_of(spec.param_dtype)
        stacked_xavier = jinit.variance_scaling(
            1.0, "fan_avg", "uniform", in_axis=-2, out_axis=-1, batch_axis=(0,))
        f32 = jnp.float32  # LayerNorm params stay float32 like flax's
        # nn.LayerNorm default, so canonicalized artifacts match exactly
        shapes = {
            "ln_attn_scale": ((L, d), jinit.ones, f32),
            "ln_attn_bias": ((L, d), jinit.zeros, f32),
            "qkv_kernel": ((L, d, 3 * d), stacked_xavier, pdt),
            "qkv_bias": ((L, 3 * d), jinit.zeros, pdt),
            "proj_kernel": ((L, d, d), stacked_xavier, pdt),
            "proj_bias": ((L, d), jinit.zeros, pdt),
            "ln_mlp_scale": ((L, d), jinit.ones, f32),
            "ln_mlp_bias": ((L, d), jinit.zeros, f32),
            "mlp_in_kernel": ((L, d, r * d), stacked_xavier, pdt),
            "mlp_in_bias": ((L, r * d), jinit.zeros, pdt),
            "mlp_out_kernel": ((L, r * d, d), stacked_xavier, pdt),
            "mlp_out_bias": ((L, d), jinit.zeros, pdt),
        }
        params = {name: self.param(name, init, shape, dt)
                  for name, (shape, init, dt) in shapes.items()}

        n_pipe = _pipe_parallel_size(self.mesh)
        stage_fn = make_stage_fn(spec)
        if n_pipe <= 1:
            return stage_fn(params, x)

        from ..parallel.pipeline import pipeline_apply
        n_micro = spec.pipeline_microbatches or spec.pipeline_stages
        b = x.shape[0]
        if b % n_micro != 0:
            raise ValueError(
                f"pipeline needs batch ({b}) divisible by microbatch count "
                f"({n_micro}); adjust batch_size or pipeline_microbatches")
        micro = x.reshape(n_micro, b // n_micro, *x.shape[1:])
        out = pipeline_apply(stage_fn, params, micro, self.mesh)
        return out.reshape(b, *x.shape[1:])


def stack_block_params(params: dict, spec: ModelSpec) -> dict:
    """Inverse of `canonicalize_params`: fold 'block_{i}/<module>/<leaf>'
    subtrees into the StackedBlocks 'blocks/<name>' (L, ...) leaves, so a
    per-block checkpoint restores into a pipeline-stacked trunk (the two
    layouts are interchangeable views of the same weights)."""
    if "blocks" in params:
        return params
    out = {k: v for k, v in params.items()
           if not (k.startswith("block_") and k[6:].isdigit())}
    stacked = {}
    for name, (module, leaf) in _BLOCK_PARAM_PATHS.items():
        stacked[name] = np.stack(
            [np.asarray(params[f"block_{i}"][module][leaf])
             for i in range(spec.num_layers)])
    out["blocks"] = stacked
    return out


def canonicalize_params(params: dict, spec: ModelSpec) -> dict:
    """Convert a StackedBlocks ('blocks/<name>' leaves (L, ...)) param tree
    into the canonical per-block tree ('block_{i}/<module>/<leaf>') the
    export program references (export/program.py transformer_block op keys),
    so a pipeline-trained model ships the exact same artifact as a
    single-device one.  Non-stacked trees pass through unchanged."""
    if "blocks" not in params:
        return params
    out = {k: v for k, v in params.items() if k != "blocks"}
    stacked = {name: np.asarray(leaf) for name, leaf in params["blocks"].items()}
    for i in range(spec.num_layers):
        block: dict = {}
        for name, (module, leaf) in _BLOCK_PARAM_PATHS.items():
            block.setdefault(module, {})[leaf] = stacked[name][i]
        out[f"block_{i}"] = block
    return out


class FTTransformer(nn.Module):
    spec: ModelSpec
    layout: FieldLayout
    mesh: Optional[Mesh] = None  # for sequence-parallel attention_impl

    @nn.compact
    def __call__(self, features: jax.Array, *, train: bool = False) -> jax.Array:
        cdt = dtype_of(self.spec.compute_dtype)
        d = self.spec.token_dim
        numeric, ids = split_features(features, self.layout)

        tokens = []
        if self.layout.num_numeric:
            tokens.append(NumericEmbed(layout=self.layout, dim=d,
                                       param_dtype=self.spec.param_dtype,
                                       compute_dtype=self.spec.compute_dtype,
                                       name="numeric_tokenizer")(numeric))
        if self.layout.num_categorical:
            tokens.append(CategoricalEmbed(layout=self.layout, dim=d,
                                           param_dtype=self.spec.param_dtype,
                                           compute_dtype=self.spec.compute_dtype,
                                           name="cat_tokenizer")(ids))
        x = jnp.concatenate(tokens, axis=1)  # (B, F, d)

        cls = self.param("cls_token", xavier_uniform, (1, 1, d),
                         dtype_of(self.spec.param_dtype))
        cls = jnp.broadcast_to(cls.astype(cdt), (x.shape[0], 1, d))
        x = jnp.concatenate([cls, x.astype(cdt)], axis=1)

        if self.spec.pipeline_stages > 1:
            if _seq_parallel_size(self.mesh) > 1:
                raise ValueError("pipeline_stages > 1 does not compose with a "
                                 "seq mesh axis; use one or the other")
            x = StackedBlocks(spec=self.spec, mesh=self.mesh,
                              name="blocks")(x, train=train)
        else:
            # static_argnums marks `train` (arg 2, after self/x) static so
            # jax.checkpoint never traces the bool — dropout's
            # `deterministic=not train` stays a Python branch under remat
            block_cls = (nn.remat(TransformerBlock, static_argnums=(2,))
                         if self.spec.remat else TransformerBlock)
            for i in range(self.spec.num_layers):
                x = block_cls(spec=self.spec, mesh=self.mesh,
                              name=f"block_{i}")(x, train)

        cls_out = nn.LayerNorm(dtype=cdt, name="ln_final")(x[:, 0, :])
        return ShifuDense(features=self.spec.num_heads, activation=None,
                          xavier_bias=self.spec.xavier_bias_init,
                          param_dtype=self.spec.param_dtype,
                          compute_dtype=self.spec.compute_dtype,
                          name="shifu_output_0")(cls_out).astype(jnp.float32)
