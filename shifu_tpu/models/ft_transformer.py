"""FT-Transformer tabular model (BASELINE ladder config #5, stretch rung).

Feature Tokenizer + Transformer: every selected column becomes a token
(numeric: x_j * w_j + b_j; categorical: table lookup — models/embedding.py),
a CLS token is prepended, L pre-LN transformer blocks attend over the feature
axis, and the CLS representation feeds the `shifu_output_0` head.  New
capability over the reference (no attention anywhere — SURVEY.md section 5.7).

TPU-first notes: attention runs through ops/attention.mha (float32 softmax,
bf16 matmuls on the MXU); with a `seq`-axis mesh the same math is available
sequence-parallel via ops/attention.ring_attention (feature-token counts
~10^2-10^3 fit single-chip, so the model defaults to local attention).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..config.schema import ModelSpec
from ..ops.attention import mha, ring_attention, ulysses_attention
from ..ops.pallas_attention import flash_attention
from ..ops.initializers import xavier_uniform
from ..parallel.mesh import SEQ_AXIS
from .base import ShifuDense, dtype_of
from .embedding import (CategoricalEmbed, FieldLayout, NumericEmbed,
                        split_features)


def _seq_parallel_size(mesh: Optional[Mesh]) -> int:
    if mesh is None or SEQ_AXIS not in mesh.shape:
        return 1
    return int(mesh.shape[SEQ_AXIS])


class TransformerBlock(nn.Module):
    spec: ModelSpec
    mesh: Optional[Mesh] = None  # enables ring/ulysses when it has a seq axis

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        cdt = dtype_of(self.spec.compute_dtype)
        d = self.spec.token_dim
        h = self.spec.num_attention_heads
        assert d % h == 0, "token_dim must divide num_attention_heads"
        dh = d // h
        b, s, _ = x.shape

        # pre-LN attention
        y = nn.LayerNorm(dtype=cdt, name="ln_attn")(x)
        qkv = nn.Dense(3 * d, kernel_init=xavier_uniform, dtype=cdt,
                       param_dtype=dtype_of(self.spec.param_dtype),
                       name="qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, h, dh).transpose(0, 2, 1, 3)
        k = k.reshape(b, s, h, dh).transpose(0, 2, 1, 3)
        v = v.reshape(b, s, h, dh).transpose(0, 2, 1, 3)
        n_sp = _seq_parallel_size(self.mesh)
        if self.spec.attention_impl == "flash":
            # blockwise Pallas kernel (O(S) memory per device); orthogonal to
            # the mesh — with a seq axis use ring/ulysses instead
            attn = flash_attention(q, k, v)
        elif self.spec.attention_impl != "local" and n_sp > 1:
            # sequence/context parallelism over the token axis; same math as
            # mha (tests/test_attention.py), collectives over ICI
            if s % n_sp != 0:
                raise ValueError(
                    f"attention_impl={self.spec.attention_impl!r} needs the "
                    f"token count ({s}) divisible by the seq mesh axis "
                    f"({n_sp}); pad features or adjust the mesh")
            sp = (ring_attention if self.spec.attention_impl == "ring"
                  else ulysses_attention)
            attn = sp(q, k, v, self.mesh)
        else:
            attn = mha(q, k, v)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, s, d)
        attn = nn.Dense(d, kernel_init=xavier_uniform, dtype=cdt,
                        param_dtype=dtype_of(self.spec.param_dtype),
                        name="proj")(attn)
        if self.spec.dropout_rate > 0:
            attn = nn.Dropout(self.spec.dropout_rate, deterministic=not train)(attn)
        x = x + attn

        # pre-LN MLP
        y = nn.LayerNorm(dtype=cdt, name="ln_mlp")(x)
        y = nn.Dense(self.spec.mlp_ratio * d, kernel_init=xavier_uniform,
                     dtype=cdt, param_dtype=dtype_of(self.spec.param_dtype),
                     name="mlp_in")(y)
        y = nn.gelu(y)
        y = nn.Dense(d, kernel_init=xavier_uniform, dtype=cdt,
                     param_dtype=dtype_of(self.spec.param_dtype),
                     name="mlp_out")(y)
        if self.spec.dropout_rate > 0:
            y = nn.Dropout(self.spec.dropout_rate, deterministic=not train)(y)
        return x + y


class FTTransformer(nn.Module):
    spec: ModelSpec
    layout: FieldLayout
    mesh: Optional[Mesh] = None  # for sequence-parallel attention_impl

    @nn.compact
    def __call__(self, features: jax.Array, *, train: bool = False) -> jax.Array:
        cdt = dtype_of(self.spec.compute_dtype)
        d = self.spec.token_dim
        numeric, ids = split_features(features, self.layout)

        tokens = []
        if self.layout.num_numeric:
            tokens.append(NumericEmbed(layout=self.layout, dim=d,
                                       param_dtype=self.spec.param_dtype,
                                       compute_dtype=self.spec.compute_dtype,
                                       name="numeric_tokenizer")(numeric))
        if self.layout.num_categorical:
            tokens.append(CategoricalEmbed(layout=self.layout, dim=d,
                                           param_dtype=self.spec.param_dtype,
                                           compute_dtype=self.spec.compute_dtype,
                                           name="cat_tokenizer")(ids))
        x = jnp.concatenate(tokens, axis=1)  # (B, F, d)

        cls = self.param("cls_token", xavier_uniform, (1, 1, d),
                         dtype_of(self.spec.param_dtype))
        cls = jnp.broadcast_to(cls.astype(cdt), (x.shape[0], 1, d))
        x = jnp.concatenate([cls, x.astype(cdt)], axis=1)

        for i in range(self.spec.num_layers):
            x = TransformerBlock(spec=self.spec, mesh=self.mesh,
                                 name=f"block_{i}")(x, train=train)

        cls_out = nn.LayerNorm(dtype=cdt, name="ln_final")(x[:, 0, :])
        return ShifuDense(features=self.spec.num_heads, activation=None,
                          xavier_bias=self.spec.xavier_bias_init,
                          param_dtype=self.spec.param_dtype,
                          compute_dtype=self.spec.compute_dtype,
                          name="shifu_output_0")(cls_out).astype(jnp.float32)
