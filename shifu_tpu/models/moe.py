"""Mixture-of-experts MLP — the true expert-parallel (EP) ladder rung.

Nothing like this exists in the reference (SURVEY.md §2.4 marks EP absent);
it extends the tabular ladder with capacity scaling: E expert MLP trunks
(the ModelConfig NumHiddenLayers/NumHiddenNodes topology each), a dense
softmax gate over the input, gate-weighted combination of expert outputs,
and the shared `shifu_output_0` scoring head.

TPU-first design notes:
- Dense (soft) gating, not top-k dispatch: every expert processes the
  batch, so the computation is static-shape einsums that tile straight onto
  the MXU — no data-dependent routing, no capacity-factor drops, and the
  model lowers exactly to the scoring artifact's op list (expert_dense /
  moe_combine in export/program.py, executed by the numpy interpreter and
  the native C++ engine).
- Expert parallelism: expert params are stacked on a leading E axis
  ('experts/*' leaves (E, ...)); with a `model` mesh axis they shard by
  expert (train/loop.init_state rule), each device computing only its own
  experts' einsum slice — XLA inserts the psum of the gate-weighted
  combine.  The EP analog of vocab-sharded embedding tables, but over
  whole sub-networks.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.nn import initializers as jinit

from ..config.schema import ModelSpec
from ..ops.activations import get_activation
from .base import ShifuDense, dtype_of


class MoEMLP(nn.Module):
    spec: ModelSpec

    @nn.compact
    def __call__(self, features: jax.Array, *, train: bool = False) -> jax.Array:
        spec = self.spec
        cdt = dtype_of(spec.compute_dtype)
        pdt = dtype_of(spec.param_dtype)
        e = spec.num_experts
        x = features.astype(cdt)

        # dense softmax gate over the raw features (B, E); float32 softmax
        gate_logits = ShifuDense(
            features=e, activation=None, xavier_bias=spec.xavier_bias_init,
            param_dtype=spec.param_dtype, compute_dtype=spec.compute_dtype,
            name="gate")(x)
        gate = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)

        # expert trunks: stacked (E, in, out) kernels, per-layer einsum —
        # one batched matmul per layer covering every expert (MXU-friendly)
        stacked_xavier = jinit.variance_scaling(
            1.0, "fan_avg", "uniform", in_axis=-2, out_axis=-1, batch_axis=(0,))
        h = jnp.broadcast_to(x[:, None, :], (x.shape[0], e, x.shape[1]))
        d_in = x.shape[1]
        for i, (n, act) in enumerate(zip(spec.hidden_nodes, spec.activations)):
            kernel = self.param(f"experts/kernel{i}", stacked_xavier,
                                (e, d_in, n), pdt)
            bias = self.param(f"experts/bias{i}", jinit.zeros, (e, n), pdt)
            h = jnp.einsum("bei,eio->beo", h, kernel.astype(cdt))
            h = h + bias.astype(cdt)[None]
            h = get_activation(act)(h)
            if spec.dropout_rate > 0:
                h = nn.Dropout(spec.dropout_rate, deterministic=not train)(h)
            d_in = n

        # gate-weighted combine (B, E, H) x (B, E) -> (B, H)
        combined = jnp.einsum("beh,be->bh", h.astype(jnp.float32),
                              gate).astype(cdt)

        return ShifuDense(
            features=spec.num_heads, activation=None,
            xavier_bias=spec.xavier_bias_init, param_dtype=spec.param_dtype,
            compute_dtype=spec.compute_dtype,
            name="shifu_output_0")(combined).astype(jnp.float32)
