"""Shared model building blocks.

`ShifuDense` reproduces the reference's `nn_layer` (resources/
ssgd_monitor.py:59-74): xavier-uniform kernel, xavier-init bias (a reference
quirk kept behind `xavier_bias`), activation applied to `x @ W + b`.  Compute
runs in `compute_dtype` (bfloat16 by default — MXU-native) with parameters
kept in `param_dtype` (float32) and master-precision loss accumulation.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import ModelSpec
from ..ops.activations import get_activation
from ..ops.initializers import bias_init, xavier_uniform
from ..ops.pallas_int8_matmul import (fused_engaged as _int8_fused_engaged,
                                      int8_matmul_dequant,
                                      xla_reference as _int8_xla_reference)


def dtype_of(name: str):
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}[name]


class _WireDense(nn.Module):
    """A Dense layer that consumes int8 wire features natively.

    Declares the same `kernel`/`bias` params (names, shapes, init order) as
    the nn.Dense that `ShifuDense` otherwise builds — checkpoints, exports,
    and sharding rules see an identical tree — but routes int8 inputs
    through `ops.pallas_int8_matmul.int8_matmul_dequant`, which applies the
    static wire grid inside the matmul's tile load instead of dispatching a
    separate dequant op.  Non-int8 inputs (the f32 init dummy, eval batches
    that arrive decoded) take the ordinary promotion math unchanged.
    """

    features: int
    wire: Tuple[Tuple[float, ...], Optional[Tuple[float, ...]]]
    xavier_bias: bool = True
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        pdt = dtype_of(self.param_dtype)
        cdt = dtype_of(self.compute_dtype)
        kernel = self.param("kernel", xavier_uniform,
                            (x.shape[-1], self.features), pdt)
        bias = self.param("bias", bias_init(self.xavier_bias),
                          (self.features,), pdt)
        if x.dtype == jnp.int8:
            scale = jnp.asarray(self.wire[0], jnp.float32)
            offset = (None if self.wire[1] is None
                      else jnp.asarray(self.wire[1], jnp.float32))
            if _int8_fused_engaged(x.shape[-1], self.features):
                return int8_matmul_dequant(x, kernel, bias, scale, offset,
                                           compute_dtype=cdt)
            return _int8_xla_reference(x, kernel, bias, scale, offset,
                                       compute_dtype=cdt)
        return x.astype(cdt) @ kernel.astype(cdt) + bias.astype(cdt)


class ShifuDense(nn.Module):
    features: int
    activation: Optional[str] = None  # None => linear
    xavier_bias: bool = True
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"
    # int8 wire grid (scale_tuple, offset_tuple_or_None) from
    # data/pipeline.wire_params; set only on the FIRST layer of models fed
    # wire-format features (train/loop.init_state) — the dense then accepts
    # int8 inputs directly with dequantization fused into the matmul
    wire: Optional[Tuple[Tuple[float, ...],
                         Optional[Tuple[float, ...]]]] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if self.wire is not None:
            # name="Dense_0" pins the auto-name nn.Dense would get, so the
            # param tree (and init RNG stream) is identical either way
            y = _WireDense(
                self.features,
                wire=self.wire,
                xavier_bias=self.xavier_bias,
                param_dtype=self.param_dtype,
                compute_dtype=self.compute_dtype,
                name="Dense_0",
            )(x)
        else:
            y = nn.Dense(
                self.features,
                kernel_init=xavier_uniform,
                bias_init=bias_init(self.xavier_bias),
                param_dtype=dtype_of(self.param_dtype),
                dtype=dtype_of(self.compute_dtype),
            )(x)
        if self.activation is not None:
            y = get_activation(self.activation)(y)
        return y


class MLPTrunk(nn.Module):
    """The hidden stack from ModelConfig (NumHiddenLayers/NumHiddenNodes/
    ActivationFunc — reference: ssgd_monitor.py:93-110).  When
    `spec.dropout_rate > 0` (ModelConfig DropoutRate) each hidden layer's
    activation is followed by dropout, active only under `train=True` —
    eval/export stay deterministic.

    `wire` (the int8 grid from data/pipeline.wire_params) attaches to layer
    0 only: that is the one layer that ever sees wire-format inputs."""

    spec: ModelSpec
    wire: Optional[Tuple[Tuple[float, ...],
                         Optional[Tuple[float, ...]]]] = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        for i, (n, act) in enumerate(zip(self.spec.hidden_nodes, self.spec.activations)):
            x = ShifuDense(
                features=n,
                activation=act,
                xavier_bias=self.spec.xavier_bias_init,
                param_dtype=self.spec.param_dtype,
                compute_dtype=self.spec.compute_dtype,
                wire=self.wire if i == 0 else None,
                name=f"hidden_layer{i}",
            )(x)
            if self.spec.dropout_rate > 0:
                x = nn.Dropout(self.spec.dropout_rate,
                               deterministic=not train)(x)
        return x


class ScoringHead(nn.Module):
    """Linear head(s) producing logits; sigmoid lives in the loss/scorer.

    The reference's head is Dense(1)+sigmoid named `shifu_output_0`
    (ssgd_monitor.py:121); returning logits keeps the loss numerically exact
    and lets XLA fuse the sigmoid where it is consumed.
    """

    spec: ModelSpec

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        y = ShifuDense(
            features=self.spec.num_heads,
            activation=None,
            xavier_bias=self.spec.xavier_bias_init,
            param_dtype=self.spec.param_dtype,
            compute_dtype=self.spec.compute_dtype,
            name="shifu_output_0",
        )(x)
        return y.astype(jnp.float32)
