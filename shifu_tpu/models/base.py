"""Shared model building blocks.

`ShifuDense` reproduces the reference's `nn_layer` (resources/
ssgd_monitor.py:59-74): xavier-uniform kernel, xavier-init bias (a reference
quirk kept behind `xavier_bias`), activation applied to `x @ W + b`.  Compute
runs in `compute_dtype` (bfloat16 by default — MXU-native) with parameters
kept in `param_dtype` (float32) and master-precision loss accumulation.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import ModelSpec
from ..ops.activations import get_activation
from ..ops.initializers import bias_init, xavier_uniform


def dtype_of(name: str):
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}[name]


class ShifuDense(nn.Module):
    features: int
    activation: Optional[str] = None  # None => linear
    xavier_bias: bool = True
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        y = nn.Dense(
            self.features,
            kernel_init=xavier_uniform,
            bias_init=bias_init(self.xavier_bias),
            param_dtype=dtype_of(self.param_dtype),
            dtype=dtype_of(self.compute_dtype),
        )(x)
        if self.activation is not None:
            y = get_activation(self.activation)(y)
        return y


class MLPTrunk(nn.Module):
    """The hidden stack from ModelConfig (NumHiddenLayers/NumHiddenNodes/
    ActivationFunc — reference: ssgd_monitor.py:93-110).  When
    `spec.dropout_rate > 0` (ModelConfig DropoutRate) each hidden layer's
    activation is followed by dropout, active only under `train=True` —
    eval/export stay deterministic."""

    spec: ModelSpec

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        for i, (n, act) in enumerate(zip(self.spec.hidden_nodes, self.spec.activations)):
            x = ShifuDense(
                features=n,
                activation=act,
                xavier_bias=self.spec.xavier_bias_init,
                param_dtype=self.spec.param_dtype,
                compute_dtype=self.spec.compute_dtype,
                name=f"hidden_layer{i}",
            )(x)
            if self.spec.dropout_rate > 0:
                x = nn.Dropout(self.spec.dropout_rate,
                               deterministic=not train)(x)
        return x


class ScoringHead(nn.Module):
    """Linear head(s) producing logits; sigmoid lives in the loss/scorer.

    The reference's head is Dense(1)+sigmoid named `shifu_output_0`
    (ssgd_monitor.py:121); returning logits keeps the loss numerically exact
    and lets XLA fuse the sigmoid where it is consumed.
    """

    spec: ModelSpec

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        y = ShifuDense(
            features=self.spec.num_heads,
            activation=None,
            xavier_bias=self.spec.xavier_bias_init,
            param_dtype=self.spec.param_dtype,
            compute_dtype=self.spec.compute_dtype,
            name="shifu_output_0",
        )(x)
        return y.astype(jnp.float32)
