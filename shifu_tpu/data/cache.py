"""Parse-once columnar cache for delimited data files.

SURVEY.md §7.3 ranks input throughput as hard part #1 and prescribes a
"columnar/pre-parsed intermediate".  This is it: the first read of a gzip
pipe-delimited file parses it (native C++ tier when available) and writes the
resulting (N, C) float32 matrix as a little-endian `.npy` next to nothing the
user owns — in an explicit cache directory.  Every later read (next epoch
restart, next trainer run, eval-over-train jobs) is a single `np.load`, which
runs at memory/disk bandwidth instead of decompress+tokenize speed — two
orders of magnitude faster than even the native parse tier.

Keying and invalidation: the cache file name is
`<sha1(abs path)[:16]>-<sha1(size, mtime_ns, delimiter, version)[:16]>.npy`.
A changed source file (size or mtime) produces a new meta hash, so stale
entries can never be served; writes atomically replace via `os.replace` and
prune superseded entries for the same source path.  A corrupt cache entry is
deleted and the source is re-parsed — the cache can always be rebuilt from
the data, so every failure path falls back to `reader.read_file`.

The reference has no analog: it re-ran its Python per-line loop on every
worker every run (resources/ssgd_monitor.py:348-454).
"""

from __future__ import annotations

import hashlib
import os
import tempfile
from typing import Optional

import numpy as np

# Bump when the parsed representation changes incompatibly.
CACHE_FORMAT_VERSION = 1

# Environment override: lets operators turn the cache on for unmodified jobs
# (e.g. the launcher CLI) without touching config files.
ENV_CACHE_DIR = "SHIFU_TPU_DATA_CACHE"


def resolve_cache_dir(cache_dir: Optional[str] = None) -> Optional[str]:
    """Explicit argument wins; else the env var; else None (cache off)."""
    if cache_dir:
        return cache_dir
    return os.environ.get(ENV_CACHE_DIR) or None


def _sha1(text: str) -> str:
    return hashlib.sha1(text.encode()).hexdigest()


def cache_entry_name(path: str, delimiter: str) -> Optional[str]:
    """Deterministic cache file name for `path`'s current state, or None when
    the file is uncacheable.

    Local paths key on os.stat; remote URIs (hdfs/gs/s3/file) key on the
    filesystem's (size, mtime) metadata — so the cache also turns remote
    ingest into a local mmap-speed read after the first fetch.  A filesystem
    that reports no size or mtime returns None: keying on a constant would
    serve stale entries after an in-place overwrite, so such files are simply
    never cached.
    """
    from . import fsio

    if fsio.is_remote(path):
        size, mtime_ns = fsio.file_info(path)
        if size is None or mtime_ns is None:
            return None
        path_part = _sha1(path)[:16]
    else:
        st = os.stat(path)
        size, mtime_ns = st.st_size, st.st_mtime_ns
        path_part = _sha1(os.path.abspath(path))[:16]
    meta_part = _sha1(
        f"{size}:{mtime_ns}:{delimiter}:{CACHE_FORMAT_VERSION}")[:16]
    return f"{path_part}-{meta_part}.npy"


def read_file_cached(
    path: str,
    delimiter: str = "|",
    cache_dir: Optional[str] = None,
    mmap: bool = False,
    parser_threads: Optional[int] = None,
) -> np.ndarray:
    """`reader.read_file` with a parse-once cache in front.

    With `mmap=True` a cache hit returns a read-only memory map — rows then
    page in on demand, so a dataset larger than RAM can stream through
    `iter_file_rows`-style consumers.
    """
    from . import reader

    cache_dir = resolve_cache_dir(cache_dir)
    if cache_dir is None:
        return reader.read_file(path, delimiter, parser_threads=parser_threads)

    name = cache_entry_name(path, delimiter)  # stats the source: IO errors propagate
    if name is None:  # no trustworthy (size, mtime) key: don't cache
        return reader.read_file(path, delimiter, parser_threads=parser_threads)
    entry = os.path.join(cache_dir, name)
    if os.path.exists(entry):
        try:
            arr = np.load(entry, mmap_mode="r" if mmap else None)
            if arr.ndim == 2 and arr.dtype == np.float32:
                return arr
        except Exception:
            pass  # corrupt entry: fall through to re-parse
        try:
            os.remove(entry)
        except OSError:
            pass

    arr = reader.read_file(path, delimiter, parser_threads=parser_threads)
    _write_entry(cache_dir, name, arr)
    if mmap:
        try:
            return np.load(os.path.join(cache_dir, name), mmap_mode="r")
        except Exception:
            return arr
    return arr


def projected_entry_name(path: str, delimiter: str, file_idx: int,
                         schema, valid_ratio: float, split_seed: int,
                         feature_dtype: str) -> Optional[str]:
    """Cache name for a PROJECTED per-file result (features/target/weight +
    train-valid mask, features already in the wire dtype).  Keyed on
    everything that shapes the result: source file state, schema column
    selection, split parameters, the file's position in the path list (row
    ids derive from it), and the feature dtype.  One load then replaces
    parse + project + split + cast on every later ingest.

    The entry is a DIRECTORY of raw per-column `.npy` files (r5): raw npy
    loads mmap (np.load(mmap_mode='r')), so a warm-page-cache ingest
    streams the big features column straight into the concat/device copy
    instead of paying the npz zip-member copy first — measured ~3x faster
    aggregate load on the bench host.  Legacy `.npz` entries from earlier
    rounds still load (read fallback below)."""
    base = cache_entry_name(path, delimiter)
    if base is None:
        return None
    sel = _sha1(str((tuple(schema.selected_indices),
                     tuple(schema.all_target_indices),
                     schema.weight_index, file_idx,
                     round(valid_ratio, 9), split_seed, feature_dtype,
                     CACHE_FORMAT_VERSION)))[:16]
    return base[:-4] + f"-p{sel}.npd"


_PROJECTED_KEYS = ("features", "target", "weight", "valid_mask")


def legacy_projected_path(entry_path: str) -> str:
    """The r4-format `.npz` path for a `.npd` directory entry path — the
    read fallback (and the hot-cache probe) accept either form."""
    return entry_path[:-4] + ".npz" if entry_path.endswith(".npd") \
        else entry_path


def _decode_projected(has, get) -> Optional[dict]:
    """Shared decode for both entry forms (directory-of-npy and legacy
    npz), given membership/load accessors: bf16 features round-trip as a
    tagged uint16 member (neither container has bf16), and a 2-D features
    matrix gates validity."""
    out = {}
    if has("features_bf16"):
        import ml_dtypes
        out["features"] = get("features_bf16").view(ml_dtypes.bfloat16)
    else:
        out["features"] = get("features")
    for k in _PROJECTED_KEYS[1:]:
        out[k] = get(k)
    return out if out["features"].ndim == 2 else None


def load_projected_entry(cache_dir: str, name: str) -> Optional[dict]:
    """Load a projected entry ({'features','target','weight','valid_mask'})
    or None on miss/corruption (corrupt entries are removed).  The big
    features column comes back memory-mapped read-only — consumers
    concatenate or device_put it, which streams pages without an extra
    materializing copy."""
    entry = os.path.join(cache_dir, name)
    if os.path.isdir(entry):
        try:
            out = _decode_projected(
                lambda k: os.path.exists(os.path.join(entry, k + ".npy")),
                lambda k: np.load(os.path.join(entry, k + ".npy"),
                                  mmap_mode=("r" if "features" in k
                                             else None)))
            if out is not None:
                return out
        except Exception:
            pass
        import shutil
        shutil.rmtree(entry, ignore_errors=True)  # corrupt: rebuildable
        return None
    legacy = legacy_projected_path(entry)
    if legacy != entry and os.path.exists(legacy):
        # r4-format npz entry: still serve it (no forced re-parse on
        # upgrade); new writes use the directory form
        try:
            with np.load(legacy) as z:
                out = _decode_projected(lambda k: k in z, lambda k: z[k])
            if out is not None:
                return out
        except Exception:
            pass
        try:
            os.remove(legacy)
        except OSError:
            pass
    return None


def write_projected_entry(cache_dir: str, name: str, arrays: dict) -> None:
    """Atomic directory-of-npy write + prune of stale-source entries; never
    raises (cache is an accelerator only).  Atomicity: columns write into
    a tmp dir, then one rename publishes the entry — a concurrent writer
    losing the rename race just discards its tmp."""
    try:
        payload = dict(arrays)
        f = payload.get("features")
        if f is not None and f.dtype.name == "bfloat16":
            payload["features_bf16"] = f.view(np.uint16)
            del payload["features"]
        os.makedirs(cache_dir, exist_ok=True)
        tmp = tempfile.mkdtemp(dir=cache_dir, suffix=".tmp")
        try:
            for k, v in payload.items():
                np.save(os.path.join(tmp, k + ".npy"),
                        np.ascontiguousarray(v))
            os.rename(tmp, os.path.join(cache_dir, name))
        finally:
            if os.path.exists(tmp):  # lost the rename race, or any error
                import shutil
                shutil.rmtree(tmp, ignore_errors=True)
        _prune_superseded(cache_dir, name)
    except Exception:  # never fail ingest for the accelerator
        pass


def _write_entry(cache_dir: str, name: str, arr: np.ndarray) -> None:
    """Atomic write + prune of superseded entries; never raises (the cache is
    an accelerator, not a correctness dependency — a read-only cache_dir just
    means every read parses)."""
    try:
        os.makedirs(cache_dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=cache_dir, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.save(f, np.ascontiguousarray(arr, dtype=np.float32))
            os.replace(tmp, os.path.join(cache_dir, name))
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
        _prune_superseded(cache_dir, name)
    except OSError:
        pass


def _prune_superseded(cache_dir: str, fresh_name: str) -> None:
    """Remove entries for the same source path (path-hash prefix) whose
    META hash differs — a rewritten/re-mtimed source supersedes BOTH its
    raw `.npy` and every projected `-p*.npz` built from it, which would
    otherwise accumulate a dataset-sized orphan per rewrite.  Entries with
    the same meta but a different projection key stay (two jobs with
    different split params legitimately share the cache dir)."""
    parts = fresh_name.rsplit(".", 1)[0].split("-")
    if len(parts) < 2:
        return
    path_part, meta_part = parts[0], parts[1]
    try:
        for existing in os.listdir(cache_dir):
            if not existing.endswith((".npy", ".npz", ".npd")):
                continue
            if existing == fresh_name:
                continue
            eparts = existing.rsplit(".", 1)[0].split("-")
            if len(eparts) < 2 or eparts[0] != path_part:
                continue
            if eparts[1] == meta_part:
                continue  # same source state: raw + projections coexist
            target = os.path.join(cache_dir, existing)
            try:
                if os.path.isdir(target):
                    import shutil
                    shutil.rmtree(target, ignore_errors=True)
                else:
                    os.remove(target)
            except OSError:
                pass
    except OSError:
        pass
