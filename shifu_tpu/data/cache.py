"""Parse-once columnar cache for delimited data files (v2: wire-format).

SURVEY.md §7.3 ranks input throughput as hard part #1 and prescribes a
"columnar/pre-parsed intermediate".  This is it, in two tiers:

- **raw tier** (`read_file_cached`): the first read of a gzip pipe-delimited
  file parses it (native C++ tier when available) and writes the resulting
  (N, C) float32 matrix as a little-endian `.npy` in an explicit cache
  directory.  Every later read is a single `np.load` at memory/disk
  bandwidth.
- **projected tier, format v2** (`write_projected_entry` /
  `load_projected_entry`): the fully projected per-file result — features
  already in the resolved WIRE format (int8 via the static `wire_params`
  grid, bf16, or f32), target compacted to uint8 when exactly representable,
  an all-ones weight column elided entirely, plus the train/valid mask — as
  a directory of raw `.npy` columns with an `entry.json` manifest.  A warm
  start mmaps the int8 features straight into the EpochFeeder's assembly
  stage with zero per-run projection/quantization and ¼ the disk bytes of a
  raw-float32 entry.  Compaction is a DISK encoding only: the loader
  reconstructs bit-exact float32 target/weight columns (uint8 -> f32 is
  exact by the write-time proof; elided weights were proven all-ones), so a
  cache hit is byte-identical to a fresh parse+project+cast — the parity
  contract tests/test_cache_v2.py pins.

Keying and invalidation: entry names embed sha1 hashes of the source path
and of (size, mtime_ns, delimiter, CACHE_FORMAT_VERSION); projected names
additionally hash the schema projection, split parameters, and the wire
format (feature_dtype encodes the int8 grid's clip).  Any change to any of
those produces a new name, so stale entries can never be served; writes
publish atomically (`os.replace` / one-directory rename) and prune
superseded same-source entries.  Legacy v1 entries (format-version 1 keys)
are transparently upgraded: read once through the old path, rewritten as
v2, and the v1 entry pruned — never orphaned on disk.

Every failure path falls back to `reader.read_file`: the cache can always
be rebuilt from the data.  A failed load of an entry that exists journals a
`cache_fallback` event (the recovery record `shifu-tpu chaos-verify`-style
audits read), and the `data.cache` chaos site covers entry read/write
(docs/ROBUSTNESS.md).

The reference has no analog: it re-ran its Python per-line loop on every
worker every run (resources/ssgd_monitor.py:348-454).
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import threading
import time
from typing import Callable, Optional

import numpy as np

# Bump when the parsed representation changes incompatibly.  v2 (this
# format): wire-format projected entries with an entry.json manifest and
# compact target/weight storage.  v1: float32 projected columns, no
# manifest — still readable (and upgraded on first touch).
CACHE_FORMAT_VERSION = 2

# Environment override: lets operators turn the cache on for unmodified jobs
# (e.g. the launcher CLI) without touching config files.
ENV_CACHE_DIR = "SHIFU_TPU_DATA_CACHE"

_MANIFEST = "entry.json"


def resolve_cache_dir(cache_dir: Optional[str] = None) -> Optional[str]:
    """Explicit argument wins; else the env var; else None (cache off)."""
    if cache_dir:
        return cache_dir
    return os.environ.get(ENV_CACHE_DIR) or None


def _sha1(text: str) -> str:
    return hashlib.sha1(text.encode()).hexdigest()


def _source_info(path: str):
    """(size, mtime_ns, path_part) for keying, or (None, None, None) when
    the filesystem reports no trustworthy metadata."""
    from . import fsio

    if fsio.is_remote(path):
        size, mtime_ns = fsio.file_info(path)
        if size is None or mtime_ns is None:
            return None, None, None
        return size, mtime_ns, _sha1(path)[:16]
    st = os.stat(path)
    return st.st_size, st.st_mtime_ns, _sha1(os.path.abspath(path))[:16]


def source_bytes(paths) -> int:
    """Total on-disk bytes of the given source files (0 for any file the
    filesystem can't stat) — the denominator of the pod data plane's
    per-host ingest accounting: with N hosts each host's
    `ingest_source_bytes_total` should approach source_bytes(all)/N."""
    total = 0
    for p in paths:
        size, _mtime, _part = _source_info(p)
        if size is not None:
            total += int(size)
    return total


def cache_entry_name(path: str, delimiter: str,
                     version: Optional[int] = None) -> Optional[str]:
    """Deterministic cache file name for `path`'s current state, or None when
    the file is uncacheable.

    Local paths key on os.stat; remote URIs (hdfs/gs/s3/file) key on the
    filesystem's (size, mtime) metadata — so the cache also turns remote
    ingest into a local mmap-speed read after the first fetch.  A filesystem
    that reports no size or mtime returns None: keying on a constant would
    serve stale entries after an in-place overwrite, so such files are simply
    never cached.  `version` pins a specific format generation (the v1
    fallback probe passes 1); None means the current CACHE_FORMAT_VERSION.
    """
    size, mtime_ns, path_part = _source_info(path)
    if path_part is None:
        return None
    if version is None:
        version = CACHE_FORMAT_VERSION
    meta_part = _sha1(f"{size}:{mtime_ns}:{delimiter}:{version}")[:16]
    return f"{path_part}-{meta_part}.npy"


def read_file_cached(
    path: str,
    delimiter: str = "|",
    cache_dir: Optional[str] = None,
    mmap: bool = False,
    parser_threads: Optional[int] = None,
    write: bool = True,
) -> np.ndarray:
    """`reader.read_file` with a parse-once cache in front.

    With `mmap=True` a cache hit returns a read-only memory map — rows then
    page in on demand, so a dataset larger than RAM can stream through
    `iter_file_rows`-style consumers.  `write=False` reads hits (current or
    legacy-v1 keys) but never writes a new raw entry on a miss — the
    projected-entry path passes it so cold ingest does not duplicate the
    matrix as raw float32 next to the ¼-size v2 entry it is about to write.
    """
    from . import reader

    cache_dir = resolve_cache_dir(cache_dir)
    if cache_dir is None:
        return reader.read_file(path, delimiter, parser_threads=parser_threads)

    # ONE stat serves the current and legacy keys plus the prune spare set
    # (remote sources pay a metadata RPC per file_info)
    size, mtime_ns, path_part = _source_info(path)  # IO errors propagate
    if path_part is None:  # no trustworthy (size, mtime) key: don't cache
        return reader.read_file(path, delimiter, parser_threads=parser_threads)

    def versioned_name(v: int) -> str:
        return (f"{path_part}-"
                f"{_sha1(f'{size}:{mtime_ns}:{delimiter}:{v}')[:16]}.npy")

    name = versioned_name(CACHE_FORMAT_VERSION)
    hit = _load_raw_entry(cache_dir, name, mmap)
    if hit is not None:
        return hit
    keep = frozenset(versioned_name(v).rsplit(".", 1)[0].split("-")[1]
                     for v in range(1, CACHE_FORMAT_VERSION + 1))
    # legacy v1 raw entry: serve it and upgrade the key (even on a
    # write=False projected-path read — the re-key is one cheap copy that
    # keeps a bumped format from stranding a dataset-sized v1 orphan the
    # cache CLI cannot identify as reclaimable)
    v1name = versioned_name(1)
    if v1name != name:
        hit = _load_raw_entry(cache_dir, v1name, mmap)
        if hit is not None:
            _write_entry(cache_dir, name, np.asarray(hit), keep)
            # remove the v1 entry only once the v2 rewrite is actually on
            # disk — _write_entry never raises (full/read-only cache dir),
            # and deleting the sole cached copy after a swallowed write
            # failure would force a full re-parse on every later run
            if os.path.exists(os.path.join(cache_dir, name)):
                try:  # POSIX: the served mmap stays valid after unlink
                    os.remove(os.path.join(cache_dir, v1name))
                except OSError:
                    pass
            if mmap:
                fresh = _load_raw_entry(cache_dir, name, True)
                if fresh is not None:
                    return fresh
            return hit

    arr = reader.read_file(path, delimiter, parser_threads=parser_threads)
    if not write:
        return arr
    _write_entry(cache_dir, name, arr, keep)
    if mmap:
        try:
            return np.load(os.path.join(cache_dir, name), mmap_mode="r")
        except Exception:
            return arr
    return arr


def _load_raw_entry(cache_dir: str, name: str,
                    mmap: bool) -> Optional[np.ndarray]:
    entry = os.path.join(cache_dir, name)
    if not os.path.exists(entry):
        return None
    try:
        arr = np.load(entry, mmap_mode="r" if mmap else None)
        if arr.ndim == 2 and arr.dtype == np.float32:
            return arr
    except Exception:
        pass  # corrupt entry: fall through to removal + re-parse
    _journal_fallback(name, "corrupt raw entry")
    try:
        os.remove(entry)
    except OSError:
        pass
    return None


def projected_entry_name(path: str, delimiter: str, file_idx: int,
                         schema, valid_ratio: float, split_seed: int,
                         feature_dtype: str,
                         version: Optional[int] = None) -> Optional[str]:
    """Cache name for a PROJECTED per-file result (features/target/weight +
    train-valid mask, features already in the wire dtype).  Keyed on
    everything that shapes the result: source file state, schema column
    selection, split parameters, the file's position in the path list (row
    ids derive from it), the feature wire format (the int8 grid's clip rides
    in the `feature_dtype` string), and the cache format version.  One load
    then replaces parse + project + split + quantize on every later ingest.

    The entry is a DIRECTORY of raw per-column `.npy` files: raw npy loads
    mmap (np.load(mmap_mode='r')), so a warm-page-cache ingest streams the
    big features column straight into the concat/device copy instead of
    paying a zip-member copy first — measured ~3x faster aggregate load on
    the bench host.  v2 adds an `entry.json` manifest (format version,
    source identity for `shifu-tpu cache`, and the compact target/weight
    recipe).  Legacy `.npz` entries from earlier rounds still load (read
    fallback below)."""
    if version is None:
        version = CACHE_FORMAT_VERSION
    base = cache_entry_name(path, delimiter, version=version)
    if base is None:
        return None
    sel = _sha1(str((tuple(schema.selected_indices),
                     tuple(schema.all_target_indices),
                     schema.weight_index, file_idx,
                     round(valid_ratio, 9), split_seed, feature_dtype,
                     version)))[:16]
    return base[:-4] + f"-p{sel}.npd"


def legacy_projected_path(entry_path: str) -> str:
    """The r4-format `.npz` path for a `.npd` directory entry path — the
    read fallback (and the hot-cache probe) accept either form."""
    return entry_path[:-4] + ".npz" if entry_path.endswith(".npd") \
        else entry_path


def _journal_fallback(name: str, reason: str) -> None:
    """Record a served-entry failure (corruption, injected read fault):
    the `cache_fallback` recovery event mirrors `checkpoint_fallback` —
    the drill-auditable proof that a damaged cache degraded to re-parse
    instead of serving garbage.  Never raises."""
    try:
        from .. import obs
        obs.counter("cache_fallback_total",
                    "cache entries that failed to serve and fell back "
                    "to re-parse").inc()
        obs.event("cache_fallback", entry=name, reason=str(reason)[:200])
    except Exception:
        pass


def _probe(op: str, path: str) -> None:
    """The `data.cache` chaos site: entry read/write attempts
    (docs/ROBUSTNESS.md).  A raise here models a failing cache device —
    reads fall back to re-parse, writes are dropped (the cache is an
    accelerator, never a correctness dependency)."""
    from .. import chaos
    chaos.maybe_fail("data.cache", op=op, path=path)


def _decode_projected(has, get, manifest: Optional[dict]) -> Optional[dict]:
    """Shared decode for every entry form (v2 manifest directory, v1
    directory, legacy npz), given membership/load accessors: bf16 features
    round-trip as a tagged uint16 member (no container has bf16), compact
    v2 target/weight reconstruct to bit-exact float32, and a 2-D features
    matrix gates validity."""
    out = {}
    if has("features_bf16"):
        import ml_dtypes
        out["features"] = get("features_bf16").view(ml_dtypes.bfloat16)
    else:
        out["features"] = get("features")
    if out["features"].ndim != 2:
        return None
    out["valid_mask"] = get("valid_mask")
    target = get("target")
    if target.dtype == np.uint8:
        # v2 compact storage: values were proven integers in [0, 255] at
        # write time, so the widening cast reconstructs the original f32
        # column bit-exactly
        target = target.astype(np.float32)
    out["target"] = target
    if has("weight"):
        out["weight"] = get("weight")
    else:
        # v2 elided weight: proven all-ones at write time
        rows = int((manifest or {}).get("rows",
                                        out["features"].shape[0]))
        out["weight"] = np.ones((rows, 1), np.float32)
    return out


def load_projected_entry(cache_dir: str, name: str) -> Optional[dict]:
    """Load a projected entry ({'features','target','weight','valid_mask'})
    or None on miss/failure.  Corrupt entries are removed (and journaled as
    `cache_fallback`); an injected `data.cache` read fault degrades to a
    miss without removal — the entry may be fine, the read path was not.
    The big features column comes back memory-mapped read-only — consumers
    concatenate or device_put it, which streams pages without an extra
    materializing copy."""
    entry = os.path.join(cache_dir, name)
    legacy = legacy_projected_path(entry)
    exists = os.path.isdir(entry) or (legacy != entry
                                      and os.path.exists(legacy))
    try:
        _probe("read", entry)
    except Exception as e:
        if exists:
            _journal_fallback(name, f"read fault: {e}")
        return None
    if os.path.isdir(entry):
        try:
            manifest = None
            mpath = os.path.join(entry, _MANIFEST)
            if os.path.exists(mpath):
                with open(mpath) as f:
                    manifest = json.load(f)
            out = _decode_projected(
                lambda k: os.path.exists(os.path.join(entry, k + ".npy")),
                lambda k: np.load(os.path.join(entry, k + ".npy"),
                                  mmap_mode=("r" if "features" in k
                                             else None)),
                manifest)
            if out is not None:
                return out
        except Exception as e:
            _journal_fallback(name, repr(e))
        else:
            _journal_fallback(name, "invalid entry layout")
        import shutil
        shutil.rmtree(entry, ignore_errors=True)  # corrupt: rebuildable
        return None
    if legacy != entry and os.path.exists(legacy):
        # r4-format npz entry: still serve it (no forced re-parse on
        # upgrade); new writes use the directory form
        try:
            with np.load(legacy) as z:
                out = _decode_projected(lambda k: k in z, lambda k: z[k],
                                        None)
            if out is not None:
                return out
        except Exception as e:
            _journal_fallback(name, repr(e))
        try:
            os.remove(legacy)
        except OSError:
            pass
    return None


def write_projected_entry(cache_dir: str, name: str, arrays: dict,
                          source: Optional[str] = None,
                          delimiter: str = "|",
                          version: Optional[int] = None,
                          supersedes: Optional[str] = None) -> None:
    """Atomic directory-of-npy write + prune of stale-source entries; never
    raises (cache is an accelerator only).  Atomicity: columns write into
    a tmp dir, then one rename publishes the entry — a concurrent writer
    losing the rename race just discards its tmp.

    At `version` >= 2 (the default) the entry stores the COMPACT disk
    encoding: target as uint8 when every value is an integer in [0, 255]
    (always true for Shifu's binary labels), an all-exactly-1.0 weight
    column elided entirely, and an `entry.json` manifest recording the
    format version, the reconstruction recipe, and the source identity
    `shifu-tpu cache` lists/prunes by.  Both encodings reconstruct
    bit-exact float32 on load.  version=1 writes the legacy column layout
    (DataConfig.cache_format=1 interop pin) — still with a manifest, so
    the cache CLI can tell a pinned job's live entries from reclaimable
    manifest-less pre-v2 leftovers.  `supersedes` names one
    specific entry this write replaces (the v1->v2 upgrade passes the
    old-key entry) — removed after publish; the generic prune spares
    same-source entries of OTHER format generations so pinned-v1 and
    default-v2 jobs can share a cache dir without mutual eviction."""
    try:
        _probe("write", os.path.join(cache_dir, name))
        if version is None:
            version = CACHE_FORMAT_VERSION
        from .pipeline import target_u8_exact, weight_all_ones
        payload = dict(arrays)
        f = payload.get("features")
        rows = int(f.shape[0]) if f is not None else 0
        if f is not None and f.dtype.name == "bfloat16":
            payload["features_bf16"] = f.view(np.uint16)
            del payload["features"]
        # ONE stat of the source feeds both the manifest identity and the
        # cross-version prune spare set (a remote source pays a metadata
        # RPC per file_info — the caller already stat'ed once for the key)
        size = mtime_ns = None
        if source is not None:
            try:
                size, mtime_ns, _pp = _source_info(source)
            except OSError:
                pass
        keep = (frozenset(
            _sha1(f"{size}:{mtime_ns}:{delimiter}:{v}")[:16]
            for v in range(1, CACHE_FORMAT_VERSION + 1))
            if size is not None else frozenset())
        # EVERY generation gets a manifest: version + source identity are
        # what lets `shifu-tpu cache` tell a pinned-v1 job's LIVE entries
        # (spared by prune) from manifest-less pre-v2 leftovers
        # (reclaimable).  Compact encoding stays v2-only — a v1 entry's
        # columns remain byte-compatible with the legacy reader, which
        # loads named `<col>.npy` members and ignores the extra file.
        manifest = {"version": version, "rows": rows,
                    "target_dtype": "float32", "weight_mode": "float32"}
        if version >= 2:
            t = payload.get("target")
            if t is not None and t.dtype != np.uint8 and target_u8_exact(t):
                payload["target"] = np.asarray(t).astype(np.uint8)
            if payload.get("target") is not None \
                    and payload["target"].dtype == np.uint8:
                manifest["target_dtype"] = "uint8"
            w = payload.get("weight")
            if w is not None and weight_all_ones(w):
                del payload["weight"]
                manifest["weight_mode"] = "elided"
        if source is not None:
            from . import fsio
            # absolute path, like the key hash (_source_info): the manifest
            # is read by `shifu-tpu cache` from an arbitrary cwd — a
            # relative path recorded verbatim would classify every live
            # entry 'orphaned' (and --prune would delete the warm cache)
            # when the CLI runs from anywhere but the job's cwd
            if not fsio.is_remote(source):
                source = os.path.abspath(source)
            manifest.update(source=source, delimiter=delimiter,
                            source_size=size, source_mtime_ns=mtime_ns)
        os.makedirs(cache_dir, exist_ok=True)
        tmp = tempfile.mkdtemp(dir=cache_dir, suffix=".tmp")
        try:
            for k, v in payload.items():
                np.save(os.path.join(tmp, k + ".npy"),
                        np.ascontiguousarray(v))
            with open(os.path.join(tmp, _MANIFEST), "w") as mf:
                json.dump(manifest, mf)
            os.rename(tmp, os.path.join(cache_dir, name))
        finally:
            if os.path.exists(tmp):  # lost the rename race, or any error
                import shutil
                shutil.rmtree(tmp, ignore_errors=True)
        if supersedes and supersedes != name:
            target = os.path.join(cache_dir, supersedes)
            try:
                if os.path.isdir(target):
                    import shutil
                    shutil.rmtree(target, ignore_errors=True)
                elif os.path.exists(target):
                    os.remove(target)
            except OSError:
                pass
        _prune_superseded(cache_dir, name, keep)
    except Exception:  # never fail ingest for the accelerator
        pass


class AsyncEntryWriter:
    """Single background thread serializing projected-entry writes so the
    cold-ingest parse pool never stalls on cache disk IO — inflate+parse of
    file k+1 overlaps the v2 write of file k (ISSUE 5 ingest pipeline).
    Bounded (`max_pending`) so a slow cache device backpressures the pool
    instead of queueing the whole dataset; `close()` drains and joins.
    Write wall-seconds are reported through each submission's `record`
    callback (the ingest_report's per-file write_s)."""

    def __init__(self, max_pending: int = 4):
        import queue
        self._q: "queue.Queue" = queue.Queue(maxsize=max(max_pending, 1))
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="shifu-cache-writer")
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            args, kwargs, record = item
            t0 = time.perf_counter()
            write_projected_entry(*args, **kwargs)  # never raises
            if record is not None:
                try:
                    record(time.perf_counter() - t0)
                except Exception:
                    pass

    def submit(self, cache_dir: str, name: str, arrays: dict,
               source: Optional[str] = None, delimiter: str = "|",
               version: Optional[int] = None,
               supersedes: Optional[str] = None,
               record: Optional[Callable[[float], None]] = None) -> None:
        self._q.put(((cache_dir, name, arrays),
                     {"source": source, "delimiter": delimiter,
                      "version": version, "supersedes": supersedes}, record))

    def close(self) -> None:
        """Drain every pending write and join the thread.  Idempotent."""
        if self._thread.is_alive():
            self._q.put(None)
            self._thread.join()


def _write_entry(cache_dir: str, name: str, arr: np.ndarray,
                 keep_metas: frozenset = frozenset()) -> None:
    """Atomic write + prune of superseded entries; never raises (the cache is
    an accelerator, not a correctness dependency — a read-only cache_dir just
    means every read parses)."""
    try:
        os.makedirs(cache_dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=cache_dir, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.save(f, np.ascontiguousarray(arr, dtype=np.float32))
            os.replace(tmp, os.path.join(cache_dir, name))
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
        _prune_superseded(cache_dir, name, keep_metas)
    except OSError:
        pass


def _prune_superseded(cache_dir: str, fresh_name: str,
                      keep_metas: frozenset = frozenset()) -> None:
    """Remove entries for the same source path (path-hash prefix) whose
    META hash differs — a rewritten/re-mtimed source supersedes BOTH its
    raw `.npy` and every projected entry built from it, which would
    otherwise accumulate a dataset-sized orphan per rewrite.  Entries with
    the same meta but a different projection key stay (two jobs with
    different split params legitimately share the cache dir), as do
    entries in `keep_metas` — the same source state keyed by a different
    format generation, so a v1-pinned job (DataConfig.cache_format=1) and
    a default-v2 job sharing one cache dir never mutually evict (and
    perpetually re-parse) each other's live entries."""
    parts = fresh_name.rsplit(".", 1)[0].split("-")
    if len(parts) < 2:
        return
    path_part, meta_part = parts[0], parts[1]
    spare = keep_metas | {meta_part}
    try:
        for existing in os.listdir(cache_dir):
            if not existing.endswith((".npy", ".npz", ".npd")):
                continue
            if existing == fresh_name:
                continue
            eparts = existing.rsplit(".", 1)[0].split("-")
            if len(eparts) < 2 or eparts[0] != path_part:
                continue
            if eparts[1] in spare:
                continue  # same source state: raw + projections coexist
            target = os.path.join(cache_dir, existing)
            try:
                if os.path.isdir(target):
                    import shutil
                    shutil.rmtree(target, ignore_errors=True)
                else:
                    os.remove(target)
            except OSError:
                pass
    except OSError:
        pass


# ---------------------------------------------------------------------------
# Cache inspection (`shifu-tpu cache <dir>` — launcher/cli.py)
# ---------------------------------------------------------------------------

# a *.tmp / .building-* entry younger than this may belong to a LIVE
# writer (cold ingest, out-of-core consolidation) — scan/prune leave it
# alone; a crashed writer's leftover ages past it and becomes reclaimable
TMP_GRACE_SECONDS = 3600.0


def _tree_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


def _classify_entry(cache_dir: str, name: str) -> Optional[dict]:
    """One scan record: {name, tier, version, bytes, source, status}.
    status: ok | legacy (pre-v2 format) | stale (source changed) |
    orphaned (source gone) | corrupt | tmp."""
    full = os.path.join(cache_dir, name)
    rec = {"name": name, "tier": None, "version": None,
           "bytes": 0, "source": None, "status": "ok"}
    # ONLY our own write-side temp names (mkdtemp/mkstemp suffix=".tmp",
    # outofcore's ".building-" prefix) classify as tmp — any other
    # dotfile/unknown name is skipped entirely: never listed, never
    # pruned (a `.nfsXXXX` placeholder or a user's `.gitignore` is not
    # ours to delete).  A tmp entry younger than the grace window is
    # skipped too: it may be a LIVE writer's in-flight dir, and pruning
    # it mid-build would fail the publish rename (or an out-of-core
    # memmap write) of a healthy concurrent job.
    if name.endswith(".tmp") or name.startswith(".building-"):
        try:
            age_s = time.time() - os.path.getmtime(full)
        except OSError:
            age_s = None
        if age_s is not None and age_s < TMP_GRACE_SECONDS:
            return None  # possibly live: neither listed nor pruned
        rec.update(tier="tmp", status="tmp",
                   bytes=_tree_bytes(full) if os.path.isdir(full)
                   else (os.path.getsize(full)
                         if os.path.exists(full) else 0))
        return rec
    if name.startswith("."):
        return None
    if name.startswith("dataset-") and os.path.isdir(full):
        rec.update(tier="dataset", bytes=_tree_bytes(full))
        try:
            with open(os.path.join(full, "meta.json")) as f:
                meta = json.load(f)
            rec["version"] = int(meta.get("version", 1))
            files = meta.get("files") or []
            # entry key = source state at build time, so a rewritten
            # source supersedes the dir: compare the recorded per-file
            # (size, mtime_ns) when present (older metas lack it)
            state = meta.get("file_state") or [None] * len(files)
            rec["source"] = files[0] if len(files) == 1 else \
                (f"{len(files)} files" if files else None)
            stale = False
            for p, fs in zip(files, state):
                if "://" in p:
                    continue
                if not os.path.exists(p):
                    rec["status"] = "orphaned"
                    break
                if fs and fs[0] is not None:
                    st = os.stat(p)
                    if (fs[0] != st.st_size
                            or fs[1] not in (None, st.st_mtime_ns)):
                        stale = True
            else:
                if rec["version"] < 2:
                    # pre-v2 consolidated entries key differently and can
                    # never be served again — reclaimable
                    rec["status"] = "legacy"
                elif stale:
                    rec["status"] = "stale"
        except (OSError, ValueError):
            rec["status"] = "corrupt"
        return rec
    if name.endswith(".npd") and os.path.isdir(full):
        rec.update(tier="projected", bytes=_tree_bytes(full))
        mpath = os.path.join(full, _MANIFEST)
        if not os.path.exists(mpath):
            rec.update(version=1, status="legacy")
            return rec
        try:
            with open(mpath) as f:
                manifest = json.load(f)
            rec["version"] = int(manifest.get("version", 2))
            src = manifest.get("source")
            rec["source"] = src
            if src and "://" not in src:
                if not os.path.exists(src):
                    rec["status"] = "orphaned"
                else:
                    st = os.stat(src)
                    if (manifest.get("source_size") not in (None, st.st_size)
                            or manifest.get("source_mtime_ns")
                            not in (None, st.st_mtime_ns)):
                        rec["status"] = "stale"
        except (OSError, ValueError):
            rec["status"] = "corrupt"
        return rec
    if name.endswith(".npz"):
        rec.update(tier="projected", version=1, status="legacy",
                   bytes=os.path.getsize(full) if os.path.exists(full)
                   else 0)
        return rec
    if name.endswith(".npy"):
        # raw entries carry no manifest; the content key in the NAME is the
        # only identity (version indistinguishable from the outside)
        rec.update(tier="raw",
                   bytes=os.path.getsize(full) if os.path.exists(full)
                   else 0)
        return rec
    return None  # not a cache artifact: never touched


def scan_cache(cache_dir: str) -> list[dict]:
    """Every cache artifact under `cache_dir`, classified — the data
    source for `shifu-tpu cache`.  Unknown files are skipped (never listed,
    never pruned)."""
    out = []
    for name in sorted(os.listdir(cache_dir)):
        rec = _classify_entry(cache_dir, name)
        if rec is not None:
            out.append(rec)
    return out


PRUNE_STATUSES = ("tmp", "legacy", "stale", "orphaned", "corrupt")


def prune_cache(cache_dir: str,
                entries: Optional[list[dict]] = None) -> list[dict]:
    """Remove superseded/orphaned artifacts (`shifu-tpu cache --prune`):
    leftover tmp dirs, legacy pre-v2 entries (their sources re-cache as v2
    on the next touch), entries whose recorded source changed or vanished,
    and corrupt entries.  Returns the records removed."""
    import shutil
    if entries is None:
        entries = scan_cache(cache_dir)
    removed = []
    for rec in entries:
        if rec["status"] not in PRUNE_STATUSES:
            continue
        full = os.path.join(cache_dir, rec["name"])
        try:
            if os.path.isdir(full):
                shutil.rmtree(full, ignore_errors=True)
            elif os.path.exists(full):
                os.remove(full)
        except OSError:
            pass
        if not os.path.exists(full):  # count only what actually left disk
            removed.append(rec)
    return removed
