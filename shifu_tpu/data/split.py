"""Deterministic train/valid row split.

The reference re-draws `random.random() >= VALID_TRAINING_DATA_RATIO` per row
per run (reference: resources/ssgd_monitor.py:395), so the partition changes
across restarts — documented as a quirk (SURVEY.md section 5.9).  Here each row
gets a stable uniform in [0,1) from an integer hash of (seed, global row id),
so resume/restart and every host agree on the partition.
"""

from __future__ import annotations

import numpy as np

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer: uint64 -> well-mixed uint64."""
    with np.errstate(over="ignore"):
        z = x + _GOLDEN
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


def row_uniform(row_ids: np.ndarray, seed: int = 0) -> np.ndarray:
    """Stable uniform [0,1) per row id."""
    ids = row_ids.astype(np.uint64)
    with np.errstate(over="ignore"):
        mixed = _splitmix64(ids ^ _splitmix64(np.full_like(ids, np.uint64(seed & (2**64 - 1)))))
    return (mixed >> np.uint64(11)).astype(np.float64) / float(1 << 53)


def train_valid_mask(
    row_ids: np.ndarray,
    valid_ratio: float,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Return (train_mask, valid_mask) boolean arrays.

    A row is validation iff its stable uniform < valid_ratio — the
    deterministic analog of the reference's `random.random() >= ratio` branch
    (ssgd_monitor.py:395).
    """
    u = row_uniform(row_ids, seed)
    valid = u < valid_ratio
    return ~valid, valid


def bagging_mask(row_ids: np.ndarray, sample_rate: float, seed: int = 1) -> np.ndarray:
    """Deterministic bagging subsample (Shifu train.baggingSampleRate)."""
    if sample_rate >= 1.0:
        return np.ones(row_ids.shape[0], dtype=bool)
    return row_uniform(row_ids, seed ^ 0x5ADB) < sample_rate
