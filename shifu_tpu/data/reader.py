"""Gzip pipe-delimited normalized-data reader.

The reference loads rows with a Python 2 per-line loop: gzip -> readline ->
str.split('|') -> float() per cell, appending to Python lists
(reference: resources/ssgd_monitor.py:348-454).  That loop is the documented
throughput anti-pattern (SURVEY.md section 7.3).  Here parsing is vectorized:
the whole (decompressed) text is parsed by numpy's C tokenizer in one call and
reshaped by the column count, giving two orders of magnitude more rows/sec.
A native C++ parser can slot in behind the same interface later.
"""

from __future__ import annotations

import gzip
import io
import os
import threading
import time
from typing import Iterable, Iterator, Optional, Sequence

import numpy as np

try:  # pandas' C csv engine is the fastest in-image parser; optional.
    import pandas as _pd
except Exception:  # pragma: no cover
    _pd = None

from ..config.schema import DataSchema

# Per-thread record of the most recent read_file call's cost split —
# {tier, inflate_s, parse_s, source_bytes}.  Thread-local so the ingest
# pool's concurrent parses never mix records; consumed by the ingest
# report (data/pipeline.py `ingest_report`, docs/OBSERVABILITY.md).  The
# native parse tier fuses inflate+parse in C++, so its whole wall lands
# in parse_s.
_io_local = threading.local()


def _note_io(tier: str, inflate_s: float, parse_s: float,
             source_bytes: int) -> None:
    _io_local.stats = {"tier": tier, "inflate_s": inflate_s,
                       "parse_s": parse_s, "source_bytes": source_bytes}


def last_io_stats() -> dict:
    """This thread's cost split for its most recent `read_file` — empty
    dict before the first read."""
    return dict(getattr(_io_local, "stats", {}))


def open_maybe_gzip(path: str) -> io.BufferedReader:
    """Open a file, transparently gunzipping by magic number (not extension)."""
    f = open(path, "rb")
    magic = f.read(2)
    f.seek(0)
    if magic == b"\x1f\x8b":
        return gzip.open(f, "rb")  # type: ignore[return-value]
    return f


def parse_rows(text: bytes | str, delimiter: str = "|") -> np.ndarray:
    """Parse delimited float rows into an (N, C) float32 array.

    Vectorized: one C-level tokenize + bulk conversion over the whole buffer.
    Non-numeric cells become NaN (the reference logged-and-skipped them,
    ssgd_monitor.py:404-408; NaN keeps row alignment and is imputed downstream).
    """
    if isinstance(text, bytes):
        text = text.decode("utf-8", errors="replace")
    text = text.strip("\n")
    if not text:
        return np.zeros((0, 0), dtype=np.float32)
    # column count from the first non-blank line (a leading whitespace-only
    # line is not a row and must not decide the width)
    first_line = ""
    for line in text.split("\n"):
        if line.strip():
            first_line = line
            break
    if not first_line:
        return np.zeros((0, 0), dtype=np.float32)
    ncols = first_line.count(delimiter) + 1
    if _pd is not None:
        try:
            df = _pd.read_csv(io.StringIO(text), sep=delimiter, header=None,
                              dtype=np.float32, engine="c")
            if df.shape[1] == ncols:
                return np.ascontiguousarray(df.to_numpy(dtype=np.float32))
        except Exception:
            pass  # ragged/non-numeric rows: fall through to tolerant paths
    # One C-level tokenize over the whole buffer: delimiter and newlines both
    # become separators; row structure is recovered by reshaping with ncols.
    # A non-numeric cell truncates this parse, so require the exact expected
    # element count (rows * ncols) — anything else falls back to the ragged
    # per-line parse, which preserves every row (bad cells become NaN).
    num_lines = text.count("\n") + 1
    flat = _fast_parse(text, delimiter)
    if flat is None or flat.size != num_lines * ncols:
        return _parse_ragged(text, delimiter, ncols)
    return flat.reshape(-1, ncols)


def _fast_parse(text: str, delimiter: str) -> Optional[np.ndarray]:
    # C-level split + bulk float conversion (np.fromstring's text mode is
    # deprecated-for-removal), processed in newline-aligned slabs so the
    # per-token str objects exist only for one slab at a time — a whole-file
    # split would transiently allocate ~6x the text size in cell objects.
    # A non-numeric cell raises and routes the caller to the ragged parse.
    slab = 1 << 24  # ~16 MB of text per slab
    out = []
    pos, n = 0, len(text)
    try:
        while pos < n:
            if n - pos <= slab:
                end = n
            else:
                end = text.rfind("\n", pos, pos + slab)
                if end <= pos:
                    end = n  # one line longer than the slab: take it whole
            chunk = text[pos:end].replace(delimiter, " ")
            out.append(np.array(chunk.split(), dtype=np.float32))
            pos = end + 1
    except (ValueError, OverflowError):
        return None  # caller falls back to the ragged parse
    if not out:
        return np.zeros((0,), dtype=np.float32)
    return out[0] if len(out) == 1 else np.concatenate(out)


def _parse_ragged(text: str, delimiter: str, ncols: int) -> np.ndarray:
    rows = []
    for line in text.split("\n"):
        if not line.strip():
            continue  # blank lines (incl. whitespace-only) are not rows
        cells = line.split(delimiter)
        vals = np.full((ncols,), np.nan, dtype=np.float32)
        for i, c in enumerate(cells[:ncols]):
            try:
                vals[i] = float(c)
            except ValueError:
                pass  # NaN, imputed downstream
        rows.append(vals)
    if not rows:
        return np.zeros((0, ncols), dtype=np.float32)
    return np.stack(rows)


def _fetch_decompressed(path: str) -> tuple[bytes, int]:
    """Remote fetch + gzip-magic decompress (the one place both live).
    Returns (decompressed bytes, fetched source bytes): the fetched length
    is the source (compressed) size ingest_source_bytes_total counts —
    captured here so remote ingest needs no second metadata RPC."""
    from . import fsio
    raw = fsio.read_bytes(path)
    fetched = len(raw)
    if raw[:2] == b"\x1f\x8b":
        raw = gzip.decompress(raw)
    return raw, fetched


def _parse_bytes(raw: bytes, delimiter: str,
                 parser_threads: Optional[int] = None) -> np.ndarray:
    """Tier selection for an in-memory buffer: native C++ parse when
    available, vectorized numpy otherwise (identical outputs, tested)."""
    from . import native_parser
    if len(delimiter.encode()) == 1 and native_parser.available():
        try:
            return native_parser.parse_buffer(raw, delimiter,
                                              threads=parser_threads)
        except RuntimeError:
            pass
    return parse_rows(raw, delimiter)


_PARQUET_EXTS = (".parquet", ".pq")


def is_parquet(path: str) -> bool:
    return path.lower().endswith(_PARQUET_EXTS)


def _parquet_source(path: str):
    """Local path, or a seekable pyarrow file for a remote URI (parquet
    readers need random access)."""
    from . import fsio
    return fsio.open_input_file(path) if fsio.is_remote(path) else path


def _read_parquet(path: str) -> np.ndarray:
    """One parquet file -> the same (N, C) float32 matrix the psv parsers
    produce.  Column positions (file order) take the place of the psv column
    indices ColumnConfig refers to, so a parquet export of the normalized
    table drops in without schema changes; lookups are positional throughout
    (duplicate field names are legal in the format).  Non-numeric columns
    are a config/data error, reported by name and position."""
    import pyarrow.parquet as pq

    table = pq.ParquetFile(_parquet_source(path)).read()
    cols = []
    for i in range(table.num_columns):
        arr = table.column(i).to_numpy(zero_copy_only=False)
        try:
            cols.append(np.asarray(arr, dtype=np.float32))
        except (ValueError, TypeError) as e:
            field = table.schema.field(i)
            raise ValueError(
                f"{path}: parquet column {i} ({field.name!r}) is not "
                f"numeric (dtype {field.type}); normalized training data "
                "must be numeric") from e
    if not cols:
        return np.zeros((0, 0), dtype=np.float32)
    return np.ascontiguousarray(np.column_stack(cols))


def read_file(path: str, delimiter: str = "|",
              parser_threads: Optional[int] = None) -> np.ndarray:
    """Read one data file into (N, C) float32: gzip/plain pipe-delimited
    text, or parquet (by .parquet/.pq extension).

    Text uses the native C++ parser (zlib + from_chars, multi-threaded —
    data/native_parser.py) when buildable; the vectorized numpy path above is
    the fallback.  Both produce identical arrays (tested).  hdfs:// gs://
    s3:// file:// URIs fetch through pyarrow.fs (data/fsio.py) and parse with
    the same tiers.  `parser_threads` caps intra-file parse threads (file-
    level threading passes 1 so parallelism stays ~cores, not cores^2).
    """
    from . import fsio, native_parser
    if is_parquet(path):
        t0 = time.perf_counter()
        arr = _read_parquet(path)
        _note_io("parquet", 0.0, time.perf_counter() - t0,
                 _local_size(path))
        return arr
    if fsio.is_remote(path):
        t0 = time.perf_counter()
        raw, fetched = _fetch_decompressed(path)
        t1 = time.perf_counter()
        arr = _parse_bytes(raw, delimiter, parser_threads)
        _note_io("remote", t1 - t0, time.perf_counter() - t1, fetched)
        return arr
    if len(delimiter.encode()) == 1 and native_parser.available():
        try:
            t0 = time.perf_counter()
            arr = native_parser.parse_file(path, delimiter,
                                           threads=parser_threads)
            _note_io("native", 0.0, time.perf_counter() - t0,
                     _local_size(path))
            return arr
        except RuntimeError:  # engine-internal failure: numpy tier serves
            pass  # (IO errors — FileNotFoundError/OSError — propagate)
    t0 = time.perf_counter()
    with open_maybe_gzip(path) as f:
        raw = f.read()
    t1 = time.perf_counter()
    arr = parse_rows(raw, delimiter)
    _note_io("numpy", t1 - t0, time.perf_counter() - t1, _local_size(path))
    return arr


def _local_size(path: str) -> int:
    try:
        return os.path.getsize(path)
    except OSError:
        return 0


def read_files(
    paths: Sequence[str],
    delimiter: str = "|",
    cache_dir: Optional[str] = None,
    num_threads: Optional[int] = None,
) -> list[np.ndarray]:
    """Read many files concurrently, preserving input order.

    The per-file work (zlib inflate + tokenize in the native parser, or
    numpy/pandas C parsing) runs outside the GIL, so file-level threading
    scales ingest with cores — the multi-host analog of the reference giving
    each worker its own file shard (yarn/appmaster/TrainingDataSet.java:65-82),
    applied *within* a host.  When file-level threading is active, each parse
    runs single-threaded internally (parallelism ~cores, not cores^2).  With
    `cache_dir`, each file goes through the parse-once columnar cache
    (data/cache.py).

    Note this returns every raw matrix at once; memory-conscious consumers
    that reduce per file (e.g. load_datasets' projection) should thread the
    reduction themselves rather than call this.
    """
    from .cache import read_file_cached

    if num_threads is None:
        num_threads = min(len(paths), os.cpu_count() or 1)
    threaded = num_threads > 1 and len(paths) > 1

    def one(p: str) -> np.ndarray:
        return read_file_cached(p, delimiter, cache_dir=cache_dir,
                                parser_threads=1 if threaded else None)

    if not threaded:
        return [one(p) for p in paths]
    from concurrent.futures import ThreadPoolExecutor
    with ThreadPoolExecutor(max_workers=num_threads) as pool:
        return list(pool.map(one, paths))


def count_rows(paths: Sequence[str]) -> int:
    """Total row count across files, gzip-aware.

    Successor of the reference's TOTAL_TRAINING_DATA_NUMBER computation
    (yarn/util/HdfsUtils.java:143-175 getFileLineCount).
    """
    from . import fsio, native_parser
    use_native = native_parser.available()
    total = 0
    for p in paths:
        if is_parquet(p):
            import pyarrow.parquet as pq
            total += pq.ParquetFile(_parquet_source(p)).metadata.num_rows
            continue
        if fsio.is_remote(p):
            total += fsio.count_data_lines(p)  # streaming, constant memory
            continue
        if use_native:
            try:
                total += native_parser.count_rows(p)
                continue
            except RuntimeError:
                pass  # engine-internal failure: stream-count in Python
        with open_maybe_gzip(p) as f:
            for line in f:
                if line.strip():  # non-blank data lines only (= parser rows)
                    total += 1
    return total


def list_data_files(root: str) -> list[str]:
    """List data files under a directory, skipping '.'/'_' prefixed names.

    Mirrors the reference's HDFS listing filter
    (yarn/appmaster/TrainingDataSet.java:69-71).  hdfs:// gs:// s3:// file://
    URIs list through pyarrow.fs with the same filter (data/fsio.py).
    """
    from . import fsio
    if fsio.is_remote(root):
        return fsio.list_files(root)
    if os.path.isfile(root):
        return [root]
    out = []
    for name in sorted(os.listdir(root)):
        if name.startswith(".") or name.startswith("_"):
            continue
        full = os.path.join(root, name)
        if os.path.isfile(full):
            out.append(full)
    return out


def shard_paths(paths: Sequence[str], shard_index: int, num_shards: int) -> list[str]:
    """Round-robin file paths across hosts.

    Successor of the reference's per-worker file split
    (yarn/appmaster/TrainingDataSet.java:65-82), minus its "#files must be >=
    #workers" failure mode (:84-86): a host with no files simply gets an empty
    list and contributes zero local rows (its global batch share is balanced by
    the pipeline's host-sharded batching instead).
    """
    return [p for i, p in enumerate(paths) if i % num_shards == shard_index]


def iter_file_rows(
    paths: Iterable[str],
    delimiter: str = "|",
    chunk_rows: int = 262144,
) -> Iterator[np.ndarray]:
    """Stream (chunk_rows, C) arrays from a list of files without holding the
    full dataset in RAM (the reference holds everything in Python lists —
    ssgd_monitor.py:354-361 — which caps it at worker memory)."""
    for path in paths:
        arr = read_file(path, delimiter)
        for start in range(0, arr.shape[0], chunk_rows):
            yield arr[start:start + chunk_rows]


def project_columns(
    rows: np.ndarray,
    schema: DataSchema,
    impute_value: float = 0.0,
) -> dict[str, np.ndarray]:
    """Project raw (N, C) rows into features/target/weight arrays.

    - features: schema.selected_indices columns, NaN-imputed with impute_value
    - target:   (N, H) — H target columns (1 for single-target, schema's
      target_indices order for Shifu multi-target mode)
    - weight:   (N, 1); 1.0 when schema.weight_index < 0, and negative weights
      clamp to 1.0 like the reference (ssgd_monitor.py:413-417).
    """
    n = rows.shape[0]
    sel = np.asarray(schema.selected_indices, dtype=np.int64)
    need = max([*schema.selected_indices, *schema.all_target_indices,
                schema.weight_index]) + 1
    if n and rows.shape[1] < need:
        raise ValueError(
            f"parsed rows have {rows.shape[1]} columns but the schema "
            f"references column index {need - 1}; the data delimiter "
            "(dataSet.dataDelimiter / DataConfig.delimiter) probably does "
            "not match the files")
    features = rows[:, sel] if n else np.zeros((0, len(sel)), np.float32)
    features = np.nan_to_num(features, nan=impute_value)
    tgt_idx = np.asarray(schema.all_target_indices, dtype=np.int64)
    target = rows[:, tgt_idx] if n else np.zeros((0, len(tgt_idx)), np.float32)
    if schema.weight_index >= 0:
        weight = rows[:, schema.weight_index:schema.weight_index + 1].copy()
        weight[~(weight >= 0.0)] = 1.0  # negatives and NaNs -> 1.0
    else:
        weight = np.ones((n, 1), dtype=np.float32)
    return {
        "features": np.ascontiguousarray(features, dtype=np.float32),
        "target": np.ascontiguousarray(target, dtype=np.float32),
        "weight": np.ascontiguousarray(weight, dtype=np.float32),
    }
