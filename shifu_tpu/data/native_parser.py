"""ctypes binding for the native C++ data parser (runtime/csrc/shifu_parser.cc).

Replaces the Python/pandas parse tier of `reader.py` with a zlib + from_chars
C++ parse (multi-threaded on newline-aligned chunks).  The reference's
equivalent was a Python 2 per-line loop (resources/ssgd_monitor.py:348-454) —
the documented throughput anti-pattern this framework's input path exists to
fix (SURVEY.md §7.3 #1).

Falls back gracefully: `available()` is False when g++ or zlib is missing, and
`reader.read_file` silently uses the numpy path then.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional, Tuple

import numpy as np

_lib = None
_lib_err: Optional[str] = None
_lock = threading.Lock()

_ENV_DISABLE = "SHIFU_TPU_NO_NATIVE_PARSER"
_ENV_THREADS = "SHIFU_TPU_PARSER_THREADS"


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_err
    with _lock:
        if _lib is not None or _lib_err is not None:
            return _lib
        if os.environ.get(_ENV_DISABLE):
            _lib_err = "disabled via " + _ENV_DISABLE
            return None
        try:
            from ..runtime.nativelib import build_library
            lib = ctypes.CDLL(build_library(
                "shifu_parser.cc", extra_flags=["-lz", "-pthread", "-ldl"]))
        except Exception as e:  # no g++/zlib: numpy path serves instead
            _lib_err = str(e)
            return None
        lib.shifu_parse_file.restype = ctypes.c_int
        lib.shifu_parse_file.argtypes = [
            ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        lib.shifu_parse_buffer.restype = ctypes.c_int
        lib.shifu_parse_buffer.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_char, ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        lib.shifu_parser_free.argtypes = [ctypes.POINTER(ctypes.c_float)]
        lib.shifu_count_rows.restype = ctypes.c_int64
        lib.shifu_count_rows.argtypes = [ctypes.c_char_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def unavailable_reason() -> Optional[str]:
    _load()
    return _lib_err


def _num_threads() -> int:
    try:
        return int(os.environ.get(_ENV_THREADS, "0"))
    except ValueError:
        return 0  # 0 = hardware_concurrency (decided in C++)


def pool_parser_threads(pool_width: int) -> int:
    """Per-file intra-parse thread budget when `pool_width` files parse
    concurrently: split the cores across the pool instead of pinning every
    file to 1 thread.  A 2-file shard on an 8-core host then still inflates
    8-wide (4 threads per file) while an 8-file pool degrades to the old
    1-thread-per-file policy — total parallelism stays ~cores, never
    cores².  SHIFU_TPU_PARSER_THREADS (when set) wins outright: an
    operator override is an override."""
    env = _num_threads()
    if env > 0:
        return env
    return max(1, (os.cpu_count() or 1) // max(int(pool_width), 1))


def _take(lib, out_pp, rows_p, cols_p) -> np.ndarray:
    rows, cols = rows_p.value, cols_p.value
    if rows == 0 or cols == 0:
        return np.zeros((0, max(cols, 0)), dtype=np.float32)
    # copy out of the malloc'd buffer into numpy-owned memory, then free
    arr = np.ctypeslib.as_array(out_pp, shape=(rows, cols)).copy()
    lib.shifu_parser_free(out_pp)
    return arr


def _delim_byte(delimiter: str) -> bytes:
    b = delimiter.encode()
    if len(b) != 1:
        raise ValueError(
            f"native parser supports single-byte delimiters only, got "
            f"{delimiter!r} — use the numpy reader tier")
    return b


def parse_file(path: str, delimiter: str = "|",
               threads: Optional[int] = None) -> np.ndarray:
    """Parse a (possibly gzipped) delimited file into (N, C) float32.

    `threads` overrides the intra-file parse parallelism (None = env var /
    hardware_concurrency; callers doing file-level threading pass 1 to avoid
    cores^2 oversubscription).  Raises FileNotFoundError/OSError for IO
    problems (matching the Python tier), ValueError for multi-byte
    delimiters, RuntimeError otherwise.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native parser unavailable: {_lib_err}")
    delim = _delim_byte(delimiter)
    out_pp = ctypes.POINTER(ctypes.c_float)()
    rows_p = ctypes.c_int64(0)
    cols_p = ctypes.c_int64(0)
    rc = lib.shifu_parse_file(
        path.encode(), delim,
        _num_threads() if threads is None else int(threads),
        ctypes.byref(out_pp), ctypes.byref(rows_p), ctypes.byref(cols_p))
    if rc == 4:
        if not os.path.exists(path):
            raise FileNotFoundError(f"no such data file: {path}")
        raise OSError(f"unreadable data file: {path}")
    if rc == 5:
        raise OSError(f"corrupt or truncated gzip stream: {path}")
    if rc != 0:
        raise RuntimeError(f"shifu_parse_file({path!r}) failed rc={rc}")
    return _take(lib, out_pp, rows_p, cols_p)


def parse_buffer(text: bytes, delimiter: str = "|",
                 threads: Optional[int] = None) -> np.ndarray:
    """Parse an in-memory delimited text buffer into (N, C) float32."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native parser unavailable: {_lib_err}")
    delim = _delim_byte(delimiter)
    out_pp = ctypes.POINTER(ctypes.c_float)()
    rows_p = ctypes.c_int64(0)
    cols_p = ctypes.c_int64(0)
    rc = lib.shifu_parse_buffer(
        text, len(text), delim,
        _num_threads() if threads is None else int(threads),
        ctypes.byref(out_pp), ctypes.byref(rows_p), ctypes.byref(cols_p))
    if rc != 0:
        raise RuntimeError(f"shifu_parse_buffer failed rc={rc}")
    return _take(lib, out_pp, rows_p, cols_p)


def count_rows(path: str) -> int:
    """Count non-blank data lines (gzip-aware, streaming); native
    getFileLineCount.  Raises FileNotFoundError for a missing path (same
    contract as the Python tier), RuntimeError for engine failures."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native parser unavailable: {_lib_err}")
    n = lib.shifu_count_rows(path.encode())
    if n < 0:
        if not os.path.exists(path):
            raise FileNotFoundError(f"no such data file: {path}")
        raise RuntimeError(f"shifu_count_rows({path!r}) failed")
    return int(n)
