from .cache import read_file_cached, resolve_cache_dir
from .pipeline import TabularDataset, batch_iterator, load_datasets, num_batches, pad_to_batch
from .reader import (
    count_rows,
    list_data_files,
    open_maybe_gzip,
    parse_rows,
    project_columns,
    read_file,
    read_files,
    shard_paths,
)
from .split import bagging_mask, row_uniform, train_valid_mask

__all__ = [
    "TabularDataset",
    "batch_iterator",
    "load_datasets",
    "num_batches",
    "pad_to_batch",
    "count_rows",
    "list_data_files",
    "open_maybe_gzip",
    "parse_rows",
    "project_columns",
    "read_file",
    "read_files",
    "read_file_cached",
    "resolve_cache_dir",
    "shard_paths",
    "bagging_mask",
    "row_uniform",
    "train_valid_mask",
]
