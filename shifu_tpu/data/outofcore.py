"""Out-of-core datasets: train host shards bigger than RAM.

The reference loads every row into Python lists, capping the dataset at
worker memory (resources/ssgd_monitor.py:354-361, 10 GB default containers).
Here a host shard that exceeds RAM is consolidated ONCE into on-disk
projected arrays (features/target/weight, train and valid pre-split) and
memory-mapped thereafter: `TabularDataset` holds read-only `np.memmap`s, the
staged-blocks tier gathers whole batches from them (sequential page-ins), and
the prefetch thread overlaps that disk IO with device compute.  Steady-state
epochs therefore stream from local disk at page-cache speed with no parse,
no decompress, and no RAM-resident copy of the dataset.

Layout per consolidated entry (directory named by a content key):
    meta.json              row counts + the build inputs (debuggability)
    train_features.npy     (Ntr, F) float32   written via open_memmap
    train_target.npy       (Ntr, H)
    train_weight.npy       (Ntr, 1)
    valid_features.npy     (Nva, F)
    valid_target.npy       (Nva, H)
    valid_weight.npy       (Nva, 1)

The content key covers each source file's per-file cache identity
(path+size+mtime, data/cache.py), the column projection, split config, write
permutation seed, and host shard — any change rebuilds.  Builds are atomic
(tmp dir + os.replace), so a killed build never leaves a servable half-entry.

Row-order note: the in-RAM loader applies a one-time global row permutation
to the training partition; scattering rows across a disk file would be random
IO, so here the write permutes at *chunk* granularity across files (plus
within-chunk row shuffles), and the per-epoch batch-order shuffle of the
staged tier sits on top — the standard out-of-core approximation to global
shuffling.  Validation rows are written in file order, matching the in-RAM
loader exactly.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
from typing import Optional

import numpy as np

from ..config.schema import DataConfig, DataSchema
from . import cache as cache_mod
from . import reader, split

OUT_OF_CORE_VERSION = 1

# rows per write chunk: big enough for near-sequential IO, small enough that
# a chunk is a trivial RAM footprint (256k rows x 1000 cols x 4B = 1 GB max;
# typical tabular widths are far less)
_CHUNK_ROWS = 262_144


def _entry_key(schema: DataSchema, data: DataConfig, my_files: list[tuple[int, str]]) -> str:
    h = hashlib.sha1()
    h.update(f"v{OUT_OF_CORE_VERSION}".encode())
    for file_idx, path in my_files:
        # per-file cache identity = content identity (size+mtime+delimiter)
        name = cache_mod.cache_entry_name(path, data.delimiter)
        if name is None:  # no trustworthy metadata: consolidation unsafe
            raise ValueError(
                f"cannot build out-of-core dataset: {path} has no (size, "
                f"mtime) metadata to key the consolidated cache on")
        h.update(f"{file_idx}:{name};".encode())
    h.update(json.dumps({
        "sel": list(schema.selected_indices),
        "tgt": list(schema.all_target_indices),
        "wgt": schema.weight_index,
        "valid_ratio": data.valid_ratio,
        "split_seed": data.split_seed,
        "shuffle_seed": data.shuffle_seed,
    }, sort_keys=True).encode())
    return h.hexdigest()[:24]


_PARTS = ("features", "target", "weight")


def _open_split(entry_dir: str, prefix: str):
    return tuple(
        np.load(os.path.join(entry_dir, f"{prefix}_{part}.npy"), mmap_mode="r")
        for part in _PARTS)


def load_datasets_out_of_core(
    schema: DataSchema,
    data: DataConfig,
    host_index: int = 0,
    num_hosts: int = 1,
):
    """(train, valid) TabularDatasets backed by read-only memmaps.

    Requires a cache directory (DataConfig.cache_dir or SHIFU_TPU_DATA_CACHE)
    — the consolidated arrays have to live somewhere durable.
    """
    from .pipeline import TabularDataset  # avoid import cycle

    cache_dir = cache_mod.resolve_cache_dir(data.cache_dir)
    if cache_dir is None:
        raise ValueError(
            "out-of-core datasets need a cache directory: set "
            "DataConfig.cache_dir or SHIFU_TPU_DATA_CACHE")

    paths: list[str] = []
    for p in data.paths:
        paths.extend(reader.list_data_files(p))
    mine = [(i, p) for i, p in enumerate(paths) if i % num_hosts == host_index]

    key = _entry_key(schema, data, mine)
    entry_dir = os.path.join(
        cache_dir, f"dataset-{key}-h{host_index}of{num_hosts}")
    if not os.path.exists(os.path.join(entry_dir, "meta.json")):
        _build_entry(entry_dir, schema, data, mine, host_index, num_hosts)

    train = TabularDataset(*_open_split(entry_dir, "train"))
    valid = TabularDataset(*_open_split(entry_dir, "valid"))
    return train, valid


def _file_masks(mine, data: DataConfig):
    """Pass 1: per-file (row_count, valid_mask, valid-prefix-sum table)
    without keeping any rows.

    Raises when a per-file cache entry could not be written (non-memmap
    return): pass 2 reads each file once per chunk, which is only sane when
    those reads are mmap hits — degrading to a full re-parse per chunk would
    multiply parse cost by the chunk count with no warning.
    """
    counts, masks, prefixes = [], [], []
    for file_idx, path in mine:
        # the raw matrix is mmap-served on the second touch (pass 2)
        rows = cache_mod.read_file_cached(path, data.delimiter,
                                          cache_dir=data.cache_dir, mmap=True)
        if not isinstance(rows, np.memmap):
            raise OSError(
                f"out-of-core build needs a writable cache with space for "
                f"the parsed copy of every source file, but caching "
                f"{path!r} failed (cache_dir full or unwritable?)")
        n = rows.shape[0]
        row_ids = (np.uint64(file_idx) << np.uint64(40)) + np.arange(n, dtype=np.uint64)
        _, valid_mask = split.train_valid_mask(row_ids, data.valid_ratio, data.split_seed)
        counts.append(n)
        masks.append(valid_mask)
        # exclusive prefix: prefixes[i][r] = valid rows before row r — lets
        # pass 2 find a chunk's valid write offset in O(1) instead of
        # re-summing a boolean prefix per chunk (quadratic at 1e9-row scale)
        prefixes.append(np.concatenate(
            [[0], np.cumsum(valid_mask, dtype=np.int64)]))
        del rows
    return counts, masks, prefixes


def _build_entry(entry_dir, schema: DataSchema, data: DataConfig, mine,
                 host_index: int, num_hosts: int) -> None:
    counts, masks, prefixes = _file_masks(mine, data)
    n_valid = int(sum(int(m.sum()) for m in masks))
    n_train = int(sum(counts)) - n_valid
    f_dim = len(schema.selected_indices)
    t_dim = len(schema.all_target_indices)

    # chunk write plan: (file pos, row start, row stop) per chunk, order
    # permuted across the whole shard for train decorrelation
    chunks = []
    for pos, n in enumerate(counts):
        for start in range(0, n, _CHUNK_ROWS):
            chunks.append((pos, start, min(start + _CHUNK_ROWS, n)))
    rng = np.random.default_rng(np.random.PCG64(data.shuffle_seed ^ 0xD15C))
    chunk_order = rng.permutation(len(chunks))

    parent = os.path.dirname(entry_dir) or "."
    os.makedirs(parent, exist_ok=True)
    tmp_dir = tempfile.mkdtemp(dir=parent, prefix=".building-")
    try:
        def alloc(prefix, n_rows, dim):
            return np.lib.format.open_memmap(
                os.path.join(tmp_dir, prefix), mode="w+",
                dtype=np.float32, shape=(n_rows, dim))

        out = {
            "train": (alloc("train_features.npy", n_train, f_dim),
                      alloc("train_target.npy", n_train, t_dim),
                      alloc("train_weight.npy", n_train, 1)),
            "valid": (alloc("valid_features.npy", n_valid, f_dim),
                      alloc("valid_target.npy", n_valid, t_dim),
                      alloc("valid_weight.npy", n_valid, 1)),
        }
        # valid rows keep file order (== in-RAM loader); compute each file's
        # valid write offset up front
        valid_offsets = np.concatenate(
            [[0], np.cumsum([int(m.sum()) for m in masks])])
        train_cursor = 0
        for ci in chunk_order:
            pos, start, stop = chunks[ci]
            _, path = mine[pos]
            rows = cache_mod.read_file_cached(path, data.delimiter,
                                              cache_dir=data.cache_dir, mmap=True)
            if not isinstance(rows, np.memmap):  # same guard as pass 1: a
                # cache entry evicted mid-build must not degrade to a full
                # re-parse per chunk
                raise OSError(
                    f"out-of-core build lost the cache entry for {path!r} "
                    f"mid-build (cache_dir pruned or full?)")
            cols = reader.project_columns(np.asarray(rows[start:stop]), schema)
            del rows
            vmask = masks[pos][start:stop]
            tmask = ~vmask
            n_tr = int(tmask.sum())
            if n_tr:
                order = rng.permutation(n_tr)  # within-chunk row shuffle
                sl = slice(train_cursor, train_cursor + n_tr)
                out["train"][0][sl] = cols["features"][tmask][order]
                out["train"][1][sl] = cols["target"][tmask][order]
                out["train"][2][sl] = cols["weight"][tmask][order]
                train_cursor += n_tr
            n_va = int(vmask.sum())
            if n_va:
                # file-ordered position: offset of this file + valid rows
                # before `start` within it (O(1) via the prefix table)
                before = int(prefixes[pos][start])
                sl = slice(valid_offsets[pos] + before,
                           valid_offsets[pos] + before + n_va)
                out["valid"][0][sl] = cols["features"][vmask]
                out["valid"][1][sl] = cols["target"][vmask]
                out["valid"][2][sl] = cols["weight"][vmask]
        for arrs in out.values():
            for a in arrs:
                a.flush()
        del out
        meta = {
            "version": OUT_OF_CORE_VERSION,
            "n_train": n_train, "n_valid": n_valid,
            "feature_dim": f_dim, "target_dim": t_dim,
            "host_index": host_index, "num_hosts": num_hosts,
            "files": [p for _, p in mine],
        }
        with open(os.path.join(tmp_dir, "meta.json"), "w") as f:
            json.dump(meta, f, indent=1)
        try:
            os.rename(tmp_dir, entry_dir)  # atomic publish
        except OSError:
            # either a concurrent builder published first (theirs is
            # equivalent) or the rename genuinely failed — only swallow if a
            # servable entry actually exists
            if not os.path.exists(os.path.join(entry_dir, "meta.json")):
                raise
            shutil.rmtree(tmp_dir, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp_dir, ignore_errors=True)
        raise
