"""Out-of-core datasets: train host shards bigger than RAM.

The reference loads every row into Python lists, capping the dataset at
worker memory (resources/ssgd_monitor.py:354-361, 10 GB default containers).
Here a host shard that exceeds RAM is consolidated ONCE into on-disk
projected arrays (features/target/weight, train and valid pre-split) and
memory-mapped thereafter: `TabularDataset` holds read-only `np.memmap`s, the
staged-blocks tier gathers whole batches from them (sequential page-ins), and
the prefetch thread overlaps that disk IO with device compute.  Steady-state
epochs therefore stream from local disk at page-cache speed with no parse,
no decompress, and no RAM-resident copy of the dataset.

v2 (cache v2, ISSUE 5): the tier rides the SAME per-file projected entries
the in-RAM loader caches (data/cache.py) instead of duplicating every source
into its own raw-float32 `.npy` first — the build ensures each file's v2
entry exists (parallel cold ingest through the pipeline's pool), then copies
mmap-backed slices of the already-projected, already-wire-format columns
into the consolidated arrays.  Features consolidate in the WIRE dtype (int8
= ¼ the bytes of the old float32 layout, bf16 stored as its uint16 bits),
so the staged tier's per-block cast is a pass-through and a warm start never
re-quantizes.  Compact entry columns reconstruct on the fly: a uint8 target
slice widens into the float32 consolidated column bit-exactly, an elided
weight column broadcasts 1.0.

Layout per consolidated entry (directory named by a content key):
    meta.json              row counts + dtypes + the build inputs
    train_features.npy     (Ntr, F) wire dtype   written via open_memmap
    train_target.npy       (Ntr, H) float32
    train_weight.npy       (Ntr, 1) float32
    valid_features.npy     (Nva, F)
    valid_target.npy       (Nva, H)
    valid_weight.npy       (Nva, 1)

The content key covers each source file's per-file cache identity
(path+size+mtime, data/cache.py), the column projection, split config, write
permutation seed, host shard, the wire format, and OUT_OF_CORE_VERSION —
any change rebuilds.  Builds are atomic (tmp dir + rename), so a killed
build never leaves a servable half-entry.

Row-order note: the in-RAM loader applies a one-time global row permutation
to the training partition; scattering rows across a disk file would be random
IO, so here the write permutes at *chunk* granularity across files (plus
within-chunk row shuffles), and the per-epoch batch-order shuffle of the
staged tier sits on top — the standard out-of-core approximation to global
shuffling.  Validation rows are written in file order, matching the in-RAM
loader exactly.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
import time
from typing import Optional

import numpy as np

from ..config.schema import DataConfig, DataSchema
from . import cache as cache_mod
from . import reader

# v2: consolidated arrays ride the cache-v2 projected entries and store
# features in the wire dtype (see module docstring)
OUT_OF_CORE_VERSION = 2

# rows per write chunk: big enough for near-sequential IO, small enough that
# a chunk is a trivial RAM footprint (256k rows x 1000 cols x 4B = 1 GB max;
# typical tabular widths are far less)
_CHUNK_ROWS = 262_144


def _entry_key(schema: DataSchema, data: DataConfig,
               my_files: list[tuple[int, str]], feature_dtype: str) -> str:
    h = hashlib.sha1()
    h.update(f"v{OUT_OF_CORE_VERSION}".encode())
    for file_idx, path in my_files:
        # per-file cache identity = content identity (size+mtime+delimiter)
        name = cache_mod.cache_entry_name(path, data.delimiter)
        if name is None:  # no trustworthy metadata: consolidation unsafe
            raise ValueError(
                f"cannot build out-of-core dataset: {path} has no (size, "
                f"mtime) metadata to key the consolidated cache on")
        h.update(f"{file_idx}:{name};".encode())
    h.update(json.dumps({
        "sel": list(schema.selected_indices),
        "tgt": list(schema.all_target_indices),
        "wgt": schema.weight_index,
        "valid_ratio": data.valid_ratio,
        "split_seed": data.split_seed,
        "shuffle_seed": data.shuffle_seed,
        "feature_dtype": feature_dtype,
    }, sort_keys=True).encode())
    return h.hexdigest()[:24]


_PARTS = ("features", "target", "weight")


def _open_split(entry_dir: str, prefix: str, meta: dict):
    arrs = []
    for part in _PARTS:
        a = np.load(os.path.join(entry_dir, f"{prefix}_{part}.npy"),
                    mmap_mode="r")
        if part == "features" and meta.get("features_dtype") == "bfloat16":
            import ml_dtypes
            a = a.view(ml_dtypes.bfloat16)  # stored as its uint16 bits
        arrs.append(a)
    return tuple(arrs)


def load_datasets_out_of_core(
    schema: DataSchema,
    data: DataConfig,
    host_index: int = 0,
    num_hosts: int = 1,
    feature_dtype: str = "float32",
):
    """(train, valid) TabularDatasets backed by read-only memmaps, features
    already in the wire dtype.

    Requires a cache directory (DataConfig.cache_dir or SHIFU_TPU_DATA_CACHE)
    — the consolidated arrays have to live somewhere durable.
    """
    from .pipeline import TabularDataset  # avoid import cycle

    cache_dir = cache_mod.resolve_cache_dir(data.cache_dir)
    if cache_dir is None:
        raise ValueError(
            "out-of-core datasets need a cache directory: set "
            "DataConfig.cache_dir or SHIFU_TPU_DATA_CACHE")

    from .pipeline import host_shard_assignment  # shared pod shard formula
    paths: list[str] = []
    for p in data.paths:
        paths.extend(reader.list_data_files(p))
    own = set(host_shard_assignment(
        len(paths), host_index, num_hosts,
        seed=data.shuffle_seed, epoch=0,
        mode=getattr(data, "host_shard", "auto")))
    mine = [(i, p) for i, p in enumerate(paths) if i in own]

    key = _entry_key(schema, data, mine, feature_dtype)
    entry_dir = os.path.join(
        cache_dir, f"dataset-{key}-h{host_index}of{num_hosts}")
    if not os.path.exists(os.path.join(entry_dir, "meta.json")):
        _build_entry(entry_dir, schema, data, mine, host_index, num_hosts,
                     feature_dtype, cache_dir)

    with open(os.path.join(entry_dir, "meta.json")) as f:
        meta = json.load(f)
    train = TabularDataset(*_open_split(entry_dir, "train", meta))
    valid = TabularDataset(*_open_split(entry_dir, "valid", meta))
    return train, valid


class _EntryColumns:
    """Read-only mmap handles over one projected v2 entry's columns plus
    its reconstruction recipe — the build's zero-copy source.  Features
    come back in their STORAGE dtype (bf16 as uint16 bits: the consolidated
    file stores the same bits, so copies are native-speed u16 moves);
    `weight` is None when the entry elided an all-ones column."""

    def __init__(self, entry_dir: str):
        feat = os.path.join(entry_dir, "features.npy")
        if not os.path.exists(feat):
            feat = os.path.join(entry_dir, "features_bf16.npy")
        self.features = np.load(feat, mmap_mode="r")
        self.target = np.load(os.path.join(entry_dir, "target.npy"),
                              mmap_mode="r")
        wpath = os.path.join(entry_dir, "weight.npy")
        self.weight = np.load(wpath, mmap_mode="r") \
            if os.path.exists(wpath) else None
        self.valid_mask = np.asarray(
            np.load(os.path.join(entry_dir, "valid_mask.npy")))
        self.rows = int(self.features.shape[0])


def _ensure_entries(mine, schema: DataSchema, data: DataConfig,
                    feature_dtype: str, cache_dir: str) -> list[_EntryColumns]:
    """Make sure every source file has a projected v2 entry on disk and
    return mmap handles over them, parsing missing files through the
    bounded ingest pool (parallel cold ingest; parsed arrays are dropped
    immediately — only the on-disk entry and its mmap survive, so the
    build's peak RAM is pool_width in-flight files, never the shard).

    Raises when an entry could not be written: the chunked copy reads each
    entry once per chunk, which is only sane when those reads are mmap hits
    — degrading to a full re-parse per chunk would multiply parse cost by
    the chunk count with no warning.
    """
    from . import pipeline as pipe_mod

    version = pipe_mod.resolved_cache_format(data)

    def entry_path(file_idx: int, path: str) -> str:
        name = cache_mod.projected_entry_name(
            path, data.delimiter, file_idx, schema, data.valid_ratio,
            data.split_seed, feature_dtype, version=version)
        if name is None:
            raise ValueError(
                f"cannot build out-of-core dataset: {path} has no (size, "
                f"mtime) metadata to key the per-file cache on")
        return os.path.join(cache_dir, name)

    missing = [(pos, item) for pos, item in enumerate(mine)
               if not os.path.isdir(entry_path(*item))]
    if missing:
        # default pool of 2 (not cpu_count): the out-of-core regime is
        # exactly where width x file-size transients threaten host RAM;
        # ingest_workers (or the legacy read_threads spelling, same
        # fallback chain as pipeline.ingest_pool_width) raises it
        # explicitly
        width = data.ingest_workers or data.read_threads \
            or min(2, len(missing))
        width = max(1, min(width, len(missing)))
        threaded = width > 1
        from . import native_parser
        pt = native_parser.pool_parser_threads(width) if threaded else None
        stats: list = []
        t0 = time.perf_counter()

        def build_one(item):
            # writes the v2 entry synchronously (writer=None); the parsed
            # arrays are discarded — the mmap below is the real product
            pipe_mod._load_one_projected(item, schema, data, feature_dtype,
                                         threaded, parser_threads=pt,
                                         stats=stats)

        if threaded:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(max_workers=width) as pool:
                list(pool.map(lambda pi: build_one(pi[1]), missing))
        else:
            for _pos, item in missing:
                build_one(item)
        pipe_mod._emit_ingest_report(stats, width,
                                     time.perf_counter() - t0,
                                     mode="outofcore")
    def materialize_dir(entry: str, path: str) -> None:
        """A served-but-not-directory entry (a legacy `.npz` under a
        pinned cache_format=1 — _load_one_projected serves it without a
        rewrite) is re-published in the directory form the chunk copy
        mmaps; a no-op when nothing loads."""
        name = os.path.basename(entry)
        hit = cache_mod.load_projected_entry(cache_dir, name)
        if hit is not None and not os.path.isdir(entry):
            cache_mod.write_projected_entry(
                cache_dir, name, hit, source=path,
                delimiter=data.delimiter, version=version)

    def open_or_rebuild(file_idx: int, path: str) -> _EntryColumns:
        entry = entry_path(file_idx, path)
        for attempt in (0, 1):
            if not os.path.isdir(entry):
                materialize_dir(entry, path)
            if os.path.isdir(entry):
                try:
                    return _EntryColumns(entry)
                except Exception:
                    # damaged columns (truncated npy, concurrent prune):
                    # the module contract is that every failure path falls
                    # back to re-parse — drop the entry and rebuild once
                    if attempt:
                        raise
                    shutil.rmtree(entry, ignore_errors=True)
            if attempt:
                break
            pipe_mod._load_one_projected((file_idx, path), schema, data,
                                         feature_dtype, False)
        raise OSError(
            f"out-of-core build needs a writable cache with space for "
            f"the projected copy of every source file, but caching "
            f"{path!r} failed (cache_dir full or unwritable?)")

    return [open_or_rebuild(file_idx, path) for file_idx, path in mine]


def _build_entry(entry_dir, schema: DataSchema, data: DataConfig, mine,
                 host_index: int, num_hosts: int, feature_dtype: str,
                 cache_dir: str) -> None:
    entries = _ensure_entries(mine, schema, data, feature_dtype, cache_dir)
    counts = [e.rows for e in entries]
    masks = [e.valid_mask for e in entries]
    # exclusive prefix: prefixes[i][r] = valid rows before row r — lets the
    # chunk copy find a chunk's valid write offset in O(1) instead of
    # re-summing a boolean prefix per chunk (quadratic at 1e9-row scale)
    prefixes = [np.concatenate([[0], np.cumsum(m, dtype=np.int64)])
                for m in masks]
    n_valid = int(sum(int(m.sum()) for m in masks))
    n_train = int(sum(counts)) - n_valid
    f_dim = len(schema.selected_indices)
    t_dim = len(schema.all_target_indices)
    feat_store = entries[0].features.dtype if entries else np.dtype(np.float32)
    feat_logical = ("bfloat16" if feature_dtype == "bfloat16"
                    else str(feat_store))

    # chunk write plan: (file pos, row start, row stop) per chunk, order
    # permuted across the whole shard for train decorrelation
    chunks = []
    for pos, n in enumerate(counts):
        for start in range(0, n, _CHUNK_ROWS):
            chunks.append((pos, start, min(start + _CHUNK_ROWS, n)))
    rng = np.random.default_rng(np.random.PCG64(data.shuffle_seed ^ 0xD15C))
    chunk_order = rng.permutation(len(chunks))

    parent = os.path.dirname(entry_dir) or "."
    os.makedirs(parent, exist_ok=True)
    tmp_dir = tempfile.mkdtemp(dir=parent, prefix=".building-")
    try:
        def alloc(prefix, n_rows, dim, dtype=np.float32):
            return np.lib.format.open_memmap(
                os.path.join(tmp_dir, prefix), mode="w+",
                dtype=dtype, shape=(n_rows, dim))

        out = {
            "train": (alloc("train_features.npy", n_train, f_dim, feat_store),
                      alloc("train_target.npy", n_train, t_dim),
                      alloc("train_weight.npy", n_train, 1)),
            "valid": (alloc("valid_features.npy", n_valid, f_dim, feat_store),
                      alloc("valid_target.npy", n_valid, t_dim),
                      alloc("valid_weight.npy", n_valid, 1)),
        }
        # valid rows keep file order (== in-RAM loader); compute each file's
        # valid write offset up front
        valid_offsets = np.concatenate(
            [[0], np.cumsum([int(m.sum()) for m in masks])])
        train_cursor = 0
        last_touch = time.monotonic()
        for ci in chunk_order:
            # a TB-scale copy can outlive the prune grace window
            # (cache.TMP_GRACE_SECONDS keys liveness off the dir mtime,
            # which open_memmap set at alloc time): re-touch the building
            # dir periodically so a concurrent `shifu-tpu cache --prune`
            # never reclaims a LIVE build mid-copy
            if time.monotonic() - last_touch > 300:
                try:
                    os.utime(tmp_dir)
                except OSError:
                    pass
                last_touch = time.monotonic()
            pos, start, stop = chunks[ci]
            e = entries[pos]
            # slices of the already-projected, already-wire-format entry —
            # a uint8 compact target widens into the f32 column bit-exactly
            # on assignment; an elided weight broadcasts 1.0
            feats = e.features[start:stop]
            tgt = e.target[start:stop]
            wgt = e.weight[start:stop] if e.weight is not None else None
            vmask = masks[pos][start:stop]
            tmask = ~vmask
            n_tr = int(tmask.sum())
            if n_tr:
                order = rng.permutation(n_tr)  # within-chunk row shuffle
                sl = slice(train_cursor, train_cursor + n_tr)
                out["train"][0][sl] = feats[tmask][order]
                out["train"][1][sl] = tgt[tmask][order]
                if wgt is not None:
                    out["train"][2][sl] = wgt[tmask][order]
                else:
                    out["train"][2][sl] = 1.0
                train_cursor += n_tr
            n_va = int(vmask.sum())
            if n_va:
                # file-ordered position: offset of this file + valid rows
                # before `start` within it (O(1) via the prefix table)
                before = int(prefixes[pos][start])
                sl = slice(valid_offsets[pos] + before,
                           valid_offsets[pos] + before + n_va)
                out["valid"][0][sl] = feats[vmask]
                out["valid"][1][sl] = tgt[vmask]
                if wgt is not None:
                    out["valid"][2][sl] = wgt[vmask]
                else:
                    out["valid"][2][sl] = 1.0
        for arrs in out.values():
            for a in arrs:
                a.flush()
        del out
        # absolute paths + per-file (size, mtime_ns) at build time: the
        # consolidated dir is keyed on source state, so a rewritten source
        # orphans it — without the recorded state `shifu-tpu cache` could
        # never tell a superseded dataset dir (stale, reclaimable) from a
        # live one, leaking a dataset-sized dir per source rewrite
        file_state = []
        file_paths = []
        for _idx, p in mine:
            try:
                fsize, fmtime, _pp = cache_mod._source_info(p)
            except OSError:
                fsize = fmtime = None
            file_paths.append(p if "://" in p else os.path.abspath(p))
            file_state.append([fsize, fmtime])
        meta = {
            "version": OUT_OF_CORE_VERSION,
            "n_train": n_train, "n_valid": n_valid,
            "feature_dim": f_dim, "target_dim": t_dim,
            # logical vs storage dtype: bf16 consolidates as its uint16
            # bits (npy has no bf16) and is viewed back at open time
            "features_dtype": feat_logical,
            "wire_feature_dtype": feature_dtype,
            "host_index": host_index, "num_hosts": num_hosts,
            "files": file_paths,
            "file_state": file_state,
        }
        with open(os.path.join(tmp_dir, "meta.json"), "w") as f:
            json.dump(meta, f, indent=1)
        try:
            os.rename(tmp_dir, entry_dir)  # atomic publish
        except OSError:
            # either a concurrent builder published first (theirs is
            # equivalent) or the rename genuinely failed — only swallow if a
            # servable entry actually exists
            if not os.path.exists(os.path.join(entry_dir, "meta.json")):
                raise
            shutil.rmtree(tmp_dir, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp_dir, ignore_errors=True)
        raise
