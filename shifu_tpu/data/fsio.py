"""Filesystem abstraction: local paths plus hdfs:// gs:// s3:// file:// URIs.

The reference reads training data straight from HDFS — the Java side lists
and splits HDFS files (yarn/appmaster/TrainingDataSet.java:55-86, counts rows
via yarn/util/HdfsUtils.java:143-175) and the Python trainer reads them
through TF's gfile+libhdfs bridge (resources/pytrain-bk.sh:13-16 exports the
Hadoop classpath for exactly this).  Here the equivalent capability rides
pyarrow.fs, which dispatches URI schemes to its C++ filesystem
implementations (HadoopFileSystem over libhdfs, GcsFileSystem, S3FileSystem).

Everything is gated: plain paths never touch pyarrow, and a missing
pyarrow / libhdfs yields a clear error only when a remote URI is actually
used.  Remote bytes are fetched whole (data files are modest shards by
construction — the reference round-robins files across workers) and parsed
by the same native/numpy tiers as local files; the parse-once columnar cache
(data/cache.py) keys remote URIs by (size, mtime) from the filesystem's
metadata, so steady-state ingest of remote data is a local mmap-speed read.
"""

from __future__ import annotations

import os
import re
from typing import Optional, Tuple

_SCHEME_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9+.-]*://")

# schemes handled by pyarrow.fs.FileSystem.from_uri
_KNOWN_SCHEMES = ("hdfs", "viewfs", "gs", "gcs", "s3", "file", "mock")


def is_remote(path: str) -> bool:
    """True for scheme:// URIs that should route through pyarrow.fs."""
    m = _SCHEME_RE.match(path)
    return bool(m) and path.split("://", 1)[0].lower() in _KNOWN_SCHEMES


# (scheme, authority) -> FileSystem: one libhdfs/GCS/S3 connection per
# endpoint instead of one per call (for hdfs a from_uri is a fresh libhdfs
# connect, so 1000 shards would otherwise mean 1000 namenode handshakes)
import threading as _threading

_fs_cache: dict = {}
_fs_lock = _threading.Lock()

# bucket-style filesystems keep the first URI segment (the bucket) in the
# in-filesystem path; authority-style ones (namenode, empty file:// host)
# strip it
_BUCKET_SCHEMES = ("gs", "gcs", "s3", "mock")


def _derive_fs_path(scheme: str, rest: str) -> str:
    # from_uri percent-decodes the path component; match it so a cache hit
    # yields exactly the path from_uri would have produced
    from urllib.parse import unquote
    if scheme in _BUCKET_SCHEMES:
        return unquote(rest)
    slash = rest.find("/")
    return unquote(rest[slash:]) if slash >= 0 else "/"


def _filesystem(path: str) -> Tuple["object", str]:
    """(pyarrow FileSystem, in-filesystem path) for a URI; the filesystem is
    memoized per scheme://authority endpoint.  The in-filesystem path is
    derived structurally and validated against from_uri's answer on the first
    call per endpoint — the endpoint is only cached when they agree, so a
    cache hit can never produce a path from_uri would not have."""
    try:
        from pyarrow import fs as pafs
    except Exception as e:  # pragma: no cover - pyarrow is in the image
        raise RuntimeError(
            f"remote data path {path!r} needs pyarrow, which failed to "
            f"import: {e}") from e
    scheme, rest = path.split("://", 1)
    scheme = scheme.lower()
    endpoint = (scheme, "" if scheme in _BUCKET_SCHEMES else rest.split("/", 1)[0])
    derived = _derive_fs_path(scheme, rest)
    with _fs_lock:
        cached = _fs_cache.get(endpoint)
    if cached is not None:
        return cached, derived
    try:
        filesystem, fs_path = pafs.FileSystem.from_uri(path)
    except Exception as e:
        raise OSError(f"cannot open filesystem for {path!r}: {e}") from e
    if fs_path == derived:
        with _fs_lock:
            _fs_cache.setdefault(endpoint, filesystem)
    return filesystem, fs_path


def file_info(path: str) -> Tuple[Optional[int], Optional[int]]:
    """(size_bytes, mtime_ns) for a remote file; raises FileNotFoundError.

    Either element is None when the filesystem does not report it — callers
    that key caches on this metadata must treat None as "uncacheable", never
    substitute a constant (a constant key would serve stale data after an
    in-place overwrite).
    """
    filesystem, fs_path = _filesystem(path)  # guards the pyarrow import
    from pyarrow import fs as pafs
    info = filesystem.get_file_info(fs_path)
    if info.type == pafs.FileType.NotFound:
        raise FileNotFoundError(f"no such data file: {path}")
    size = None if info.size is None else int(info.size)
    mtime_ns = None if info.mtime_ns is None else int(info.mtime_ns)
    return size, mtime_ns


def exists(path: str) -> bool:
    """Does a file/object exist at `path`?  Local paths stat; remote URIs
    ask the filesystem.  Unreachable filesystems read as absent — callers
    at this level (lease reads, staleness probes) treat "can't tell" and
    "not there" the same way."""
    if not is_remote(path):
        return os.path.exists(path)
    try:
        filesystem, fs_path = _filesystem(path)
        from pyarrow import fs as pafs
        return filesystem.get_file_info(fs_path).type \
            != pafs.FileType.NotFound
    except Exception:
        return False


def write_bytes_atomic(path: str, data: bytes) -> None:
    """Publish `data` at `path` so no reader ever observes a torn write.

    Local: tmp file + os.replace (POSIX rename atomicity).  Remote: a
    single open_output_stream/close — object stores publish the object
    only when the stream closes, which is the same no-torn-reads
    guarantee; hdfs-style filesystems expose the file at create, so a
    tmp + move lands the rename-atomicity there too.  The membership
    lease and sync-manifest writers sit on this."""
    if not is_remote(path):
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        return
    filesystem, fs_path = _filesystem(path)
    tmp_fs_path = f"{fs_path}.tmp.{os.getpid()}"

    def op() -> None:
        from .. import chaos
        chaos.maybe_fail("fsio.write_bytes", path=path)
        parent = fs_path.rsplit("/", 1)[0]
        if parent and parent != fs_path:
            try:
                filesystem.create_dir(parent, recursive=True)
            except Exception:
                pass  # object stores have no dirs; write decides
        with filesystem.open_output_stream(tmp_fs_path) as f:
            f.write(data)
        try:
            filesystem.move(tmp_fs_path, fs_path)
        except Exception:
            # no rename on this store: the close above already published
            # the tmp object whole — fall back to a direct whole-object
            # write (still never torn) and drop the tmp
            with filesystem.open_output_stream(fs_path) as f:
                f.write(data)
            try:
                filesystem.delete_file(tmp_fs_path)
            except Exception:
                pass
    _retry_transient(op, _classifier(filesystem, fs_path, path),
                     op_name="write_bytes_atomic")


def open_input_file(path: str):
    """A seekable pyarrow input file for a remote URI (parquet readers need
    random access, unlike the streaming read_bytes path)."""
    filesystem, fs_path = _filesystem(path)
    return _retry_transient(lambda: filesystem.open_input_file(fs_path),
                            _classifier(filesystem, fs_path, path),
                            op_name="open_input_file")


def _retry_attempts() -> int:
    """Total tries for a transient remote failure: 1 + retries.
    SHIFU_TPU_FS_RETRIES tunes it (0 disables).  The reference leaned on the
    HDFS client's own retry policy; pyarrow surfaces transient datanode /
    network errors to the caller, so the equivalent lives here."""
    import os
    try:
        return max(0, int(os.environ.get("SHIFU_TPU_FS_RETRIES", "2"))) + 1
    except ValueError:
        return 3


# error-message markers that make a remote failure NOT worth retrying —
# auth/permission problems fail the same way on every attempt, and across a
# 1000-shard dataset pointless retries turn a clear error into minutes of
# backoff.  Best-effort string match: pyarrow raises plain OSError for most
# filesystem failures, so the type alone cannot classify.
_TERMINAL_MARKERS = ("permission denied", "access denied", "accessdenied",
                     "forbidden", "unauthorized", "authentication",
                     "kerberos", "credential", "token expired")


def _count_terminal(op_name: str, reason: str) -> None:
    """fsio_terminal_total: remote failures that gave up (no more retries) —
    best-effort telemetry, never allowed to mask the real error."""
    try:
        from .. import obs
        obs.counter("fsio_terminal_total",
                    "remote fs failures not retried / exhausted").inc(
            op=op_name or "op", reason=reason)
    except Exception:
        pass


# retry backoff bounds: decorrelated jitter between _RETRY_BASE_S and 3x the
# previous sleep, capped — a gang of hosts hitting the same flaky namenode
# must NOT re-arrive in lockstep (synchronized exponential backoff turns one
# hiccup into N coordinated thundering herds, re-triggering the overload)
_RETRY_BASE_S = 0.1
_RETRY_CAP_S = 5.0


def _retry_deadline_s() -> float:
    """Total wall-clock budget for ONE remote call's whole retry ladder
    (attempt time + backoff sleeps).  A persistent fault under a raised
    SHIFU_TPU_FS_RETRIES is otherwise unbounded per call — N shards x an
    unbounded ladder wedges job startup for hours.  0 disables the cap."""
    import os
    try:
        return max(0.0, float(os.environ.get(
            "SHIFU_TPU_FS_RETRY_DEADLINE_S", "60")))
    except ValueError:
        return 60.0


def _journal_exhausted(op_name: str, elapsed_s: float, attempts: int,
                       deadline_s: float, reason: str) -> None:
    """`fsio_retry_exhausted` journal record: which op gave up, after how
    long and how many tries — the forensic line that separates "the fault
    outlived the budget" from "the budget was too small"."""
    try:
        from .. import obs
        obs.event("fsio_retry_exhausted", op=op_name or "op",
                  elapsed_s=round(elapsed_s, 3), attempts=attempts,
                  deadline_s=round(deadline_s, 3), reason=reason)
    except Exception:
        pass


def _retry_transient(op, classify=None, op_name: str = ""):
    """Run `op()` retrying transient remote errors with decorrelated-jitter
    backoff (sleep ~ U[base, 3*prev], capped — AWS architecture blog's
    "decorrelated jitter": retries desynchronize across a gang instead of
    hammering the endpoint in waves).

    `classify(exc)` may raise a terminal error (FileNotFoundError /
    IsADirectoryError) instead of letting the retry proceed; auth-shaped
    errors (see _TERMINAL_MARKERS) never retry.  Every remote operation —
    read, streaming count, listing, parquet open — goes through here, so a
    transient namenode/datanode hiccup can't kill job startup.  Retries and
    terminal failures export as `fsio_retry_total` / `fsio_terminal_total`
    (labels: op, and reason for terminal ones)."""
    import random
    import time

    attempts = _retry_attempts()
    deadline_s = _retry_deadline_s()
    t0 = time.monotonic()
    sleep_s = _RETRY_BASE_S
    for attempt in range(attempts):
        try:
            return op()
        except (FileNotFoundError, IsADirectoryError):
            _count_terminal(op_name, "not_found")
            raise
        except Exception as e:
            if classify is not None:
                try:
                    classify(e)  # may raise the terminal classification
                except (FileNotFoundError, IsADirectoryError):
                    _count_terminal(op_name, "not_found")
                    raise
            msg = str(e).lower()
            if any(m in msg for m in _TERMINAL_MARKERS):
                _count_terminal(op_name, "auth")
                raise
            if attempt == attempts - 1:
                _count_terminal(op_name, "exhausted")
                _journal_exhausted(op_name, time.monotonic() - t0,
                                   attempt + 1, deadline_s, "attempts")
                raise
            sleep_s = min(_RETRY_CAP_S,
                          random.uniform(_RETRY_BASE_S, sleep_s * 3))
            # total-deadline cap on the ladder: if the next sleep would
            # overrun the per-call budget, surface the real error NOW —
            # retrying past the deadline only delays the same failure
            elapsed = time.monotonic() - t0
            if deadline_s > 0 and elapsed + sleep_s > deadline_s:
                _count_terminal(op_name, "deadline")
                _journal_exhausted(op_name, elapsed, attempt + 1,
                                   deadline_s, "deadline")
                raise
            try:
                from .. import obs
                obs.counter("fsio_retry_total",
                            "remote fs transient-error retries").inc(
                    op=op_name or "op")
            except Exception:
                pass
            time.sleep(sleep_s)
    raise AssertionError("unreachable")


def _classifier(filesystem, fs_path: str, path: str):
    """classify-after-the-fact for _retry_transient: one stat on the failure
    path turns missing-file/directory errors terminal."""
    from pyarrow import fs as pafs

    def classify(e: Exception) -> None:
        try:
            info = filesystem.get_file_info(fs_path)
        except Exception:
            return  # stat itself flaky: let the retry decide
        if info.type == pafs.FileType.NotFound:
            raise FileNotFoundError(f"no such data file: {path}") from e
        if info.type == pafs.FileType.Directory:
            raise IsADirectoryError(
                f"expected a file, got a directory: {path}") from e

    return classify


def join(base: str, *names: str) -> str:
    """Path join that preserves remote URI schemes (os.path.join would
    mangle 'gs://bucket' + 'x' fine but keep one definition for both)."""
    if is_remote(base):
        return "/".join([base.rstrip("/"), *names])
    return os.path.join(base, *names)


def write_bytes(path: str, data: bytes) -> None:
    """Write a whole object/file at `path` (remote URIs via pyarrow.fs;
    parent 'directories' are implicit on object stores, created on
    hdfs-style filesystems)."""
    filesystem, fs_path = _filesystem(path)

    def op() -> None:
        from .. import chaos
        chaos.maybe_fail("fsio.write_bytes", path=path)
        parent = fs_path.rsplit("/", 1)[0]
        if parent and parent != fs_path:
            try:
                filesystem.create_dir(parent, recursive=True)
            except Exception:
                pass  # object stores have no dirs; write decides
        with filesystem.open_output_stream(fs_path) as f:
            f.write(data)

    _retry_transient(op, _classifier(filesystem, fs_path, path),
                     op_name="write_bytes")


def upload_dir(local_dir: str, remote_dir: str,
               chunk_bytes: int = 8 << 20) -> list[str]:
    """Upload every file under local_dir to remote_dir (recursive, relative
    layout preserved); returns the remote paths written.  Streams in
    fixed-size chunks — a multi-GB weights file must not be materialized
    in host RAM.  Used to ship locally-built artifacts (export dir, native
    pack) to a remote job dir."""
    out: list[str] = []
    base = remote_dir.rstrip("/")
    for root, _dirs, files in os.walk(local_dir):
        rel_root = os.path.relpath(root, local_dir)
        for name in sorted(files):
            rel = name if rel_root == "." else f"{rel_root}/{name}"
            target = f"{base}/{rel}"
            filesystem, fs_path = _filesystem(target)

            def op() -> None:
                parent = fs_path.rsplit("/", 1)[0]
                if parent and parent != fs_path:
                    try:  # object stores have no dirs; hdfs-style need them
                        filesystem.create_dir(parent, recursive=True)
                    except Exception:
                        pass
                with open(os.path.join(root, name), "rb") as src, \
                        filesystem.open_output_stream(fs_path) as dst:
                    while True:
                        chunk = src.read(chunk_bytes)
                        if not chunk:
                            break
                        dst.write(chunk)

            _retry_transient(op, _classifier(filesystem, fs_path, target),
                             op_name="upload_dir")
            out.append(target)
    return out


def read_bytes(path: str) -> bytes:
    """Fetch a file's raw bytes (gzip detection happens downstream).
    Local paths read directly; remote URIs stream through pyarrow.fs with
    transient errors retried with backoff — NotFound/Directory and auth
    failures classify immediately and never retry."""
    if not is_remote(path):
        with open(path, "rb") as f:
            return f.read()
    filesystem, fs_path = _filesystem(path)  # guards the pyarrow import

    def op() -> bytes:
        from .. import chaos
        chaos.maybe_fail("fsio.read_bytes", path=path)
        with filesystem.open_input_stream(fs_path) as stream:
            return stream.read()

    return _retry_transient(op, _classifier(filesystem, fs_path, path),
                            op_name="read_bytes")


def count_data_lines(path: str, chunk_bytes: int = 1 << 20) -> int:
    """Count non-blank lines of a (possibly gzipped) remote file, streaming —
    constant memory regardless of file size (the local analog streams too,
    reader.count_rows).  A transient mid-stream error restarts the whole
    count (the state is per-attempt, so a retry can't double-count)."""
    import zlib

    filesystem, fs_path = _filesystem(path)

    def op() -> int:
        count = 0
        line_has_content = False

        def feed(data: bytes) -> None:
            # count newline-terminated non-blank lines; carry blank/content
            # state across chunk borders
            nonlocal count, line_has_content
            parts = data.split(b"\n")
            for piece in parts[:-1]:
                if line_has_content or piece.strip():
                    count += 1
                line_has_content = False
            if parts[-1].strip():
                line_has_content = True

        decomp = None
        first = True
        with filesystem.open_input_stream(fs_path) as stream:
            while True:
                chunk = stream.read(chunk_bytes)
                if not chunk:
                    break
                chunk = bytes(chunk)
                if first:
                    first = False
                    if chunk[:2] == b"\x1f\x8b":
                        decomp = zlib.decompressobj(wbits=31)  # gzip wrapper
                if decomp is None:
                    feed(chunk)
                    continue
                # multi-member (concatenated) gzip: each member ends the
                # decompressobj with the remainder in unused_data — restart a
                # fresh decompressor per member (gzip.decompress semantics)
                data = chunk
                while data:
                    feed(decomp.decompress(data))
                    if not decomp.eof:
                        break
                    data = decomp.unused_data
                    decomp = zlib.decompressobj(wbits=31)
        if decomp:
            feed(decomp.flush())
        if line_has_content:
            count += 1  # final unterminated line
        return count

    return _retry_transient(op, _classifier(filesystem, fs_path, path),
                            op_name="count_data_lines")


def walk_files(root: str) -> list[tuple[str, int]]:
    """Every FILE under `root`, recursively, as (path-or-URI, size) sorted
    by path — ONE definition of the local-os.walk / remote-FileSelector
    walk (and of the URI scheme/authority rebuild) shared by checkpoint
    manifests, retention sizing, and the chaos corrupt action.  A file
    `root` yields itself; a missing root yields []."""
    if not is_remote(root):
        if os.path.isfile(root):
            try:
                return [(root, os.path.getsize(root))]
            except OSError:
                return []
        out = []
        for dirpath, _dirs, names in os.walk(root):
            for name in names:
                full = os.path.join(dirpath, name)
                try:
                    out.append((full, os.path.getsize(full)))
                except OSError:
                    continue
        return sorted(out)
    from pyarrow import fs as pafs
    filesystem, fs_path = _filesystem(root)
    base = fs_path.rstrip("/")
    scheme, rest = root.split("://", 1)
    # hdfs-style paths start with "/" and need the authority restored;
    # bucket-style keep the bucket as the first path segment (same rebuild
    # as list_files)
    authority = rest.split("/", 1)[0] if fs_path.startswith("/") else ""

    def rebuild(p: str) -> str:
        return (f"{scheme}://{authority}{p}" if p.startswith("/")
                else f"{scheme}://{p}")

    info = _retry_transient(lambda: filesystem.get_file_info(base),
                            op_name="walk_files")
    if info.type == pafs.FileType.File:
        return [(root, int(info.size or 0))]
    if info.type == pafs.FileType.NotFound:
        return []
    infos = _retry_transient(
        lambda: filesystem.get_file_info(
            pafs.FileSelector(base, recursive=True, allow_not_found=True)),
        op_name="walk_files")
    return sorted((rebuild(i.path), int(i.size or 0)) for i in infos
                  if i.type == pafs.FileType.File)


def list_files(root: str) -> list[str]:
    """List data files under a remote directory (or [root] for a file),
    skipping '.'/'_' prefixed names — the same filter as the local lister and
    the reference's HDFS listing (yarn/appmaster/TrainingDataSet.java:69-71).
    Returned paths keep the original scheme so downstream reads route back
    through pyarrow."""
    filesystem, fs_path = _filesystem(root)  # guards the pyarrow import
    from pyarrow import fs as pafs
    from .. import chaos

    def stat_op():
        chaos.maybe_fail("fsio.list_files", path=root)
        return filesystem.get_file_info(fs_path)

    info = _retry_transient(stat_op, op_name="list_files")
    if info.type == pafs.FileType.NotFound:
        raise FileNotFoundError(f"no such data path: {root}")
    scheme, rest = root.split("://", 1)
    # hdfs-style filesystems carry an authority (namenode[:port]) in the URI
    # that from_uri strips from fs_path; bucket filesystems (gs/s3) keep the
    # bucket as the first fs_path segment.  Rebuild accordingly so returned
    # URIs resolve back to the same filesystem.
    authority = rest.split("/", 1)[0] if fs_path.startswith("/") else ""

    def rebuild(p: str) -> str:
        if _SCHEME_RE.match(p):
            return p
        if p.startswith("/"):
            return f"{scheme}://{authority}{p}"
        return f"{scheme}://{p}"

    if info.type == pafs.FileType.File:
        return [root]
    selector = pafs.FileSelector(fs_path, recursive=False)
    out = []
    children = _retry_transient(lambda: filesystem.get_file_info(selector),
                                op_name="list_files")
    for child in sorted(children, key=lambda i: i.path):
        if child.type != pafs.FileType.File:
            continue
        base = child.base_name
        if base.startswith(".") or base.startswith("_"):
            continue
        out.append(rebuild(child.path))
    return out
