"""In-memory dataset + batch pipeline feeding the SPMD train step.

The reference's pipeline is: per-worker file shard -> full in-RAM Python lists
-> feed_dict minibatches (reference: resources/ssgd_monitor.py:348-454,268-276).
Here: per-host file shard -> vectorized parse -> contiguous numpy arrays ->
static-shape batches (drop-remainder) handed to jax.device_put with a
data-axis NamedSharding.  Epoch shuffles are deterministic in (seed, epoch),
so a restart resumes with identical batch order.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Iterator, Optional, Sequence

import numpy as np

from ..config.schema import DataConfig, DataSchema
from . import reader, split


@dataclasses.dataclass
class TabularDataset:
    """Feature/target/weight arrays for one partition (train or valid)."""

    features: np.ndarray  # (N, F) float32
    target: np.ndarray    # (N, 1) float32
    weight: np.ndarray    # (N, 1) float32

    @property
    def num_rows(self) -> int:
        return int(self.features.shape[0])

    @property
    def num_features(self) -> int:
        return int(self.features.shape[1])

    def take(self, idx: np.ndarray) -> "TabularDataset":
        return TabularDataset(self.features[idx], self.target[idx], self.weight[idx])


def load_datasets(
    schema: DataSchema,
    data: DataConfig,
    host_index: int = 0,
    num_hosts: int = 1,
) -> tuple[TabularDataset, TabularDataset]:
    """Load (train, valid) datasets for this host.

    Files are round-robined across hosts (successor of
    yarn/appmaster/TrainingDataSet.java:65-82); rows are split train/valid by
    the deterministic hash in `split` (fixes the re-drawn random split quirk,
    ssgd_monitor.py:395).
    """
    if data.out_of_core:
        from .outofcore import load_datasets_out_of_core
        return load_datasets_out_of_core(schema, data, host_index, num_hosts)

    paths: list[str] = []
    for p in data.paths:
        paths.extend(reader.list_data_files(p))

    # global row ids must be stable across hosts: derive from (file idx, row idx);
    # shard by index so duplicate path strings still get distinct ids
    mine = [(i, p) for i, p in enumerate(paths) if i % num_hosts == host_index]
    num_threads = data.read_threads or min(len(mine), os.cpu_count() or 1)
    threaded = num_threads > 1 and len(mine) > 1

    def load_one(item: tuple[int, str]):
        """Parse + project + split ONE file; the raw (N, C) matrix dies here,
        so peak memory is (in-flight raw files) + (projected columns), never
        all raw matrices at once."""
        from .cache import read_file_cached
        file_idx, path = item
        rows = read_file_cached(
            path, data.delimiter, cache_dir=data.cache_dir,
            parser_threads=1 if threaded else None)
        cols = reader.project_columns(rows, schema)
        n = cols["features"].shape[0]
        row_ids = (np.uint64(file_idx) << np.uint64(40)) + np.arange(n, dtype=np.uint64)
        _, valid_mask = split.train_valid_mask(row_ids, data.valid_ratio, data.split_seed)
        return cols, valid_mask

    if threaded:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=num_threads) as pool:
            results = list(pool.map(load_one, mine))  # map preserves file order
    else:
        results = [load_one(m) for m in mine]

    feats, targs, weights, masks_v = [], [], [], []
    for cols, valid_mask in results:
        feats.append(cols["features"])
        targs.append(cols["target"])
        weights.append(cols["weight"])
        masks_v.append(valid_mask)

    if feats:
        features = np.concatenate(feats)
        target = np.concatenate(targs)
        weight = np.concatenate(weights)
        valid_mask = np.concatenate(masks_v)
    else:
        features = np.zeros((0, schema.feature_count), np.float32)
        target = np.zeros((0, 1), np.float32)
        weight = np.zeros((0, 1), np.float32)
        valid_mask = np.zeros((0,), bool)

    full = TabularDataset(features, target, weight)
    train = full.take(~valid_mask)
    valid = full.take(valid_mask)
    # one-time global row shuffle of the training partition: staged epochs
    # then only permute batch order per epoch (staged_epoch_blocks), which
    # together approximates row-level shuffling at a fraction of the host cost
    if train.num_rows > 1:
        perm = np.random.default_rng(
            np.random.PCG64(data.split_seed ^ 0xC0FFEE)).permutation(train.num_rows)
        train = train.take(perm)
    return train, valid


def batch_iterator(
    ds: TabularDataset,
    batch_size: int,
    *,
    shuffle: bool = True,
    seed: int = 0,
    epoch: int = 0,
    drop_remainder: bool = True,
) -> Iterator[dict[str, np.ndarray]]:
    """Yield {'features','target','weight'} batches with static shapes.

    Shuffle order is a pure function of (seed, epoch) so every host and every
    restart agrees.  drop_remainder keeps shapes static for XLA; the dropped
    tail rotates across epochs because the permutation changes per epoch.
    """
    n = ds.num_rows
    if n == 0:
        return
    if shuffle:
        rng = np.random.default_rng(np.random.PCG64(seed * 1_000_003 + epoch))
        order = rng.permutation(n)
    else:
        order = np.arange(n)
    num_full = n // batch_size
    end = num_full * batch_size if drop_remainder else n
    for start in range(0, end, batch_size):
        idx = order[start:start + batch_size]
        yield {
            "features": ds.features[idx],
            "target": ds.target[idx],
            "weight": ds.weight[idx],
        }


def prefetch_to_device(batches: Iterator[dict[str, np.ndarray]],
                       mesh=None, size: int = 2, put_fn=None) -> Iterator[dict]:
    """Background-thread device feed: host batches are device_put (with
    data-axis sharding when a mesh is given) ahead of consumption, so host
    parse/shuffle overlaps device compute — the double-buffering the
    reference's feed_dict loop could never do (ssgd_monitor.py:271-276
    blocked the worker on every batch).

    `put_fn` overrides the host->device placement (used by the staged-epoch
    path, whose arrays shard on their second axis).
    """
    import queue
    import threading

    import jax

    from ..parallel import sharding as shard_lib

    if put_fn is None:
        def put_fn(b):
            if mesh is not None:
                return shard_lib.shard_batch(b, mesh)
            return {k: jax.device_put(v) for k, v in b.items()}

    if size <= 0:
        for b in batches:
            yield put_fn(b)
        return

    q: "queue.Queue" = queue.Queue(maxsize=size)
    _END = object()

    def producer() -> None:
        try:
            for b in batches:
                q.put(put_fn(b))
        except BaseException as e:  # surface errors to the consumer
            q.put(e)
            return
        q.put(_END)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is _END:
            return
        if isinstance(item, BaseException):
            raise item
        yield item


def staged_epoch_blocks(
    ds: TabularDataset,
    batch_size: int,
    *,
    shuffle: bool = True,
    seed: int = 0,
    epoch: int = 0,
    block_batches: int = 32,
) -> Iterator[dict[str, np.ndarray]]:
    """Yield {'features': (nb, B, F), ...} stacked blocks for the staged
    (scan-on-device) epoch path.

    Host cost per block is a gather of whole contiguous batches (large
    memcpys), not per-row fancy indexing: the dataset is viewed as
    (num_batches, B, ...) and only the *batch order* is permuted per epoch,
    with a cheap row-offset rotation so batch composition drifts across
    epochs.  Row-level shuffling happens once at load time (load_datasets
    applies a global permutation), which together with batch-order shuffling
    is the standard approximation for large-scale SGD.
    """
    n = ds.num_rows
    nb_total = n // batch_size
    if nb_total == 0:
        return
    slack = n - nb_total * batch_size
    offset = (epoch * 997) % (slack + 1) if (shuffle and slack > 0) else 0

    def as_blocks(arr: np.ndarray) -> np.ndarray:
        return arr[offset:offset + nb_total * batch_size].reshape(
            nb_total, batch_size, *arr.shape[1:])

    feats = as_blocks(ds.features)
    targ = as_blocks(ds.target)
    wgt = as_blocks(ds.weight)

    if shuffle:
        rng = np.random.default_rng(np.random.PCG64(seed * 1_000_003 + epoch))
        order = rng.permutation(nb_total)
    else:
        order = np.arange(nb_total)

    for start in range(0, nb_total, block_batches):
        idx = order[start:start + block_batches]
        yield {
            "features": feats[idx],
            "target": targ[idx],
            "weight": wgt[idx],
        }


def num_batches(ds: TabularDataset, batch_size: int, drop_remainder: bool = True) -> int:
    if drop_remainder:
        return ds.num_rows // batch_size
    return -(-ds.num_rows // batch_size)


def pad_to_batch(batch: dict[str, np.ndarray], batch_size: int) -> tuple[dict[str, np.ndarray], np.ndarray]:
    """Pad a short batch up to batch_size; returns (padded, validity mask).

    Padding rows get weight 0 so they contribute nothing to weighted losses or
    metrics — used by full-dataset eval so no validation row is dropped (the
    reference evaluates the full valid set each epoch, ssgd_monitor.py:281-284).
    """
    n = batch["features"].shape[0]
    if n == batch_size:
        return batch, np.ones((batch_size,), bool)
    pad = batch_size - n
    out = {}
    for k, v in batch.items():
        out[k] = np.concatenate([v, np.zeros((pad,) + v.shape[1:], v.dtype)])
    out["weight"][n:] = 0.0
    mask = np.zeros((batch_size,), bool)
    mask[:n] = True
    return out, mask
