"""In-memory dataset + batch pipeline feeding the SPMD train step.

The reference's pipeline is: per-worker file shard -> full in-RAM Python lists
-> feed_dict minibatches (reference: resources/ssgd_monitor.py:348-454,268-276).
Here: per-host file shard -> vectorized parse -> contiguous numpy arrays ->
static-shape batches (drop-remainder) handed to jax.device_put with a
data-axis NamedSharding.  Epoch shuffles are deterministic in (seed, epoch),
so a restart resumes with identical batch order.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Iterator, Optional, Sequence

import numpy as np

from .. import obs
from ..config.schema import DataConfig, DataSchema
from . import reader, split


def fast_take(a: np.ndarray, idx) -> np.ndarray:
    """Fancy-index `a[idx]` at native speed for non-native dtypes.

    numpy routes ml_dtypes.bfloat16 gathers through a per-element fallback
    (~84 MB/s measured on the bench host vs ~700 MB/s for int8) — an order
    of magnitude off memcpy, which made the staged bf16 tier's host block
    assembly its hidden bottleneck at high H2D bandwidth.  Gathering a
    same-itemsize integer VIEW takes numpy's native path and views back,
    bit-identical."""
    if a.dtype.name == "bfloat16":
        return a.view(np.uint16)[idx].view(a.dtype)
    return a[idx]


@dataclasses.dataclass
class TabularDataset:
    """Feature/target/weight arrays for one partition (train or valid)."""

    features: np.ndarray  # (N, F) float32
    target: np.ndarray    # (N, 1) float32
    weight: np.ndarray    # (N, 1) float32

    @property
    def num_rows(self) -> int:
        return int(self.features.shape[0])

    @property
    def num_features(self) -> int:
        return int(self.features.shape[1])

    def take(self, idx: np.ndarray) -> "TabularDataset":
        return TabularDataset(fast_take(self.features, idx),
                              self.target[idx], self.weight[idx])


def resolved_cache_format(data: DataConfig) -> int:
    """The cache entry format generation this job writes/keys by:
    DataConfig.cache_format, 0 meaning the current CACHE_FORMAT_VERSION."""
    from . import cache as cache_lib
    return int(getattr(data, "cache_format", 0)) \
        or cache_lib.CACHE_FORMAT_VERSION


def ingest_pool_width(data: DataConfig, n_files: int) -> int:
    """Width of the cold-ingest parse pool (how many part-files
    inflate+parse concurrently): DataConfig.ingest_workers, falling back to
    the legacy read_threads spelling, else one worker per file capped at
    cpu_count.  Intra-file parser threads scale inversely
    (native_parser.pool_parser_threads) so total parallelism stays ~cores.
    """
    if n_files <= 0:
        return 1
    width = data.ingest_workers or data.read_threads \
        or min(n_files, os.cpu_count() or 1)
    return max(1, min(int(width), n_files))


def _write_projected(writer, cache_dir: str, name: str, arrays: dict,
                     source: str, delimiter: str, version: int,
                     rec: Optional[dict],
                     supersedes: Optional[str] = None) -> None:
    """Route one v2 entry write through the async writer (cold ingest:
    inflate+parse of the next file overlaps this write) or do it inline;
    either way the wall lands in the ingest_report's per-file write_s and
    the `write` phase counter."""
    from . import cache as cache_lib
    wsec = obs.counter("ingest_seconds_total",
                       "cold-ingest wall seconds by phase "
                       "(docs/OBSERVABILITY.md ingest_report)")

    def record(dt: float) -> None:
        wsec.inc(dt, phase="write")
        if rec is not None:
            rec["write_s"] = rec.get("write_s", 0.0) + dt

    if writer is not None:
        writer.submit(cache_dir, name, arrays, source=source,
                      delimiter=delimiter, version=version,
                      supersedes=supersedes, record=record)
        return
    t0 = time.perf_counter()
    cache_lib.write_projected_entry(cache_dir, name, arrays, source=source,
                                    delimiter=delimiter, version=version,
                                    supersedes=supersedes)
    record(time.perf_counter() - t0)


def _load_one_projected(item: tuple[int, str], schema: DataSchema,
                        data: DataConfig, feature_dtype: str,
                        threaded: bool, parser_threads: Optional[int] = None,
                        stats: Optional[list] = None, writer=None):
    """Parse + project + split + wire-cast ONE file; the raw (N, C) matrix
    dies here, so peak memory is (in-flight raw files) + (projected
    columns), never all raw matrices at once.  With a cache_dir the fully
    PROJECTED result is cached (data/cache.py v2 entries: wire-format
    features, compact target/weight): a hit replaces
    parse + project + split + quantize with one mmap-backed load.  A v1
    entry under the old key serves once and is rewritten as v2 (the
    transparent upgrade; the v1 entry is pruned by the write).  `stats`
    collects the per-file ingest_report record; `writer` (an
    AsyncEntryWriter) overlaps entry writes with the pool's parses."""
    from . import cache as cache_lib
    file_idx, path = item
    cache_dir = cache_lib.resolve_cache_dir(data.cache_dir)
    version = resolved_cache_format(data)
    rec = {"file": os.path.basename(path), "tier": "parse", "rows": 0,
           "inflate_s": 0.0, "parse_s": 0.0, "write_s": 0.0}
    if stats is not None:
        stats.append(rec)
    isec = obs.counter("ingest_seconds_total",
                       "cold-ingest wall seconds by phase "
                       "(docs/OBSERVABILITY.md ingest_report)")
    name = None
    if cache_dir is not None:
        name = cache_lib.projected_entry_name(
            path, data.delimiter, file_idx, schema, data.valid_ratio,
            data.split_seed, feature_dtype, version=version)
        if name is not None:
            t_load = time.perf_counter()
            hit = cache_lib.load_projected_entry(cache_dir, name)
            upgraded = False
            if hit is None and version >= 2:
                # transparent v1 upgrade: serve the legacy-keyed entry once,
                # republish it as v2 (which prunes the v1 bytes)
                v1name = cache_lib.projected_entry_name(
                    path, data.delimiter, file_idx, schema, data.valid_ratio,
                    data.split_seed, feature_dtype, version=1)
                if v1name is not None:
                    hit = cache_lib.load_projected_entry(cache_dir, v1name)
                    upgraded = hit is not None
            if hit is not None:
                isec.inc(time.perf_counter() - t_load, phase="cache_load")
                mask = hit.pop("valid_mask")
                rec.update(tier="cache_v1" if upgraded else "cache",
                           rows=int(hit["features"].shape[0]))
                obs.counter("data_cache_hits_total",
                            "projected-cache hits (one entry load "
                            "replaced parse+project+split+cast)").inc()
                obs.counter("data_rows_read_total",
                            "rows ingested into datasets").inc(
                    int(hit["features"].shape[0]), source="cache")
                if upgraded:
                    obs.counter("data_cache_upgraded_total",
                                "legacy v1 projected entries rewritten "
                                "as v2").inc()
                    # supersedes=v1name: the upgrade removes exactly the
                    # old-key entry it replaced — the generic prune spares
                    # other format generations (v1-pinned jobs may share
                    # the dir)
                    _write_projected(writer, cache_dir, name,
                                     {**hit, "valid_mask": mask}, path,
                                     data.delimiter, version, rec,
                                     supersedes=v1name)
                return hit, mask
        obs.counter("data_cache_misses_total",
                    "projected-cache misses (full parse path taken)").inc()
    t_parse = time.perf_counter()
    if parser_threads is None and threaded:
        parser_threads = 1  # legacy callers: file-level pool, 1 thread each
    reader._note_io("raw_cache", 0.0, 0.0, 0)  # raw hits skip read_file;
    # a stale record from this thread's previous parse must not be charged
    # write=False when a projected entry will land: the v2 entry IS the
    # warm-start intermediate, and duplicating the matrix as raw float32
    # would cost 4x its bytes again on disk (raw hits — this job's earlier
    # format, or another job's read_files cache — are still served)
    rows = cache_lib.read_file_cached(
        path, data.delimiter, cache_dir=data.cache_dir,
        parser_threads=parser_threads, write=(name is None))
    parse_wall = time.perf_counter() - t_parse
    io_stats = reader.last_io_stats()
    rec["rows"] = int(rows.shape[0])
    if io_stats.get("tier") == "raw_cache":
        # the sentinel survived: no parse ran — a raw `.npy` entry served
        # (another job's read_files cache, or a pre-v2 run).  Its np.load
        # wall is cache time, not parse time: charging it to `parse` would
        # put phantom parse seconds with zero source bytes into the
        # cold-ingest throughput the perf gate guards
        rec["tier"] = "raw_cache"
        isec.inc(parse_wall, phase="cache_load")
    else:
        inflate_s = min(max(io_stats.get("inflate_s", 0.0), 0.0),
                        parse_wall)
        rec["parse_s"] = round(parse_wall - inflate_s, 6)
        rec["inflate_s"] = round(inflate_s, 6)
        rec["bytes"] = int(io_stats.get("source_bytes", 0))
        isec.inc(inflate_s, phase="inflate")
        isec.inc(parse_wall - inflate_s, phase="parse")
        obs.counter("ingest_source_bytes_total",
                    "source (compressed) bytes cold ingest read").inc(
            int(io_stats.get("source_bytes", 0)))
    obs.histogram("data_file_parse_seconds",
                  "per-file parse (or raw-cache load) latency").observe(
        parse_wall)
    obs.counter("data_files_read_total", "data files parsed").inc()
    obs.counter("data_rows_read_total",
                "rows ingested into datasets").inc(
        int(rows.shape[0]), source="parse")
    obs.counter("data_bytes_read_total",
                "parsed matrix bytes produced by ingest").inc(
        int(rows.nbytes))
    cols = reader.project_columns(rows, schema)
    if feature_dtype == "bfloat16":
        import ml_dtypes
        cols["features"] = cols["features"].astype(ml_dtypes.bfloat16)
    elif feature_dtype.startswith("int8"):
        # quantize ONCE at load (the grid is static — wire_params — so this
        # equals quantizing at device_put time): 1/4 the host RAM, 1/4 the
        # projected-cache bytes, zero per-epoch encode cost
        scale, offset = wire_params(schema, data)
        cols["features"] = wire_quantize(cols["features"], scale, offset)
    n = cols["features"].shape[0]
    row_ids = ((np.uint64(file_idx) << np.uint64(40))
               + np.arange(n, dtype=np.uint64))
    _, valid_mask = split.train_valid_mask(row_ids, data.valid_ratio,
                                           data.split_seed)
    if cache_dir is not None and name is not None:
        _write_projected(writer, cache_dir, name,
                         {**cols, "valid_mask": valid_mask}, path,
                         data.delimiter, version, rec)
    return cols, valid_mask


def _emit_ingest_report(stats: list, pool_width: int, wall_s: float,
                        mode: str) -> None:
    """One `ingest_report` journal event per completed ingest: the pool
    shape, the per-phase cost split, which cache tier served each file,
    and a (capped) per-file table — the observable record of the cold/warm
    ingest gap docs/PERF.md "Data plane" reasons about.  Never raises."""
    try:
        files = sorted(stats, key=lambda r: r["file"])
        tiers: dict[str, int] = {}
        for r in files:
            tiers[r["tier"]] = tiers.get(r["tier"], 0) + 1
        per_file = [
            {k: (round(v, 6) if isinstance(v, float) else v)
             for k, v in r.items()} for r in files[:32]]
        obs.event(
            "ingest_report", mode=mode, files=len(files),
            pool_width=int(pool_width), wall_s=round(wall_s, 6),
            rows=int(sum(r["rows"] for r in files)),
            parse_s=round(sum(r["parse_s"] for r in files), 6),
            inflate_s=round(sum(r["inflate_s"] for r in files), 6),
            write_s=round(sum(r["write_s"] for r in files), 6),
            source_bytes=int(sum(r.get("bytes", 0) for r in files)),
            host_index=int(os.environ.get("SHIFU_TPU_PROCESS_ID", 0) or 0),
            tiers=tiers, per_file=per_file,
            per_file_truncated=len(files) > 32)
    except Exception:
        pass  # telemetry must never fail the ingest it measures


def _run_ingest_pool(items: Sequence[tuple[int, str]], schema: DataSchema,
                     data: DataConfig, feature_dtype: str, width: int,
                     on_result) -> list:
    """The bounded multi-file ingest pool: `width` part-files inflate+parse
    concurrently (native parser per file, intra-file threads scaled so
    total parallelism stays ~cores), with v2 cache writes overlapped on a
    dedicated writer thread — the cold path never serializes parse behind
    cache IO.  Each per-file result is handed to `on_result` in file order
    as soon as it completes (Executor.map yields in submit order while
    workers run ahead), so a streaming consumer starts before the pool
    drains.  The writer is closed — every entry durable — before this
    returns (or before an error propagates); returns the ingest stats."""
    from . import cache as cache_lib, native_parser
    stats: list = []
    writer = (cache_lib.AsyncEntryWriter()
              if cache_lib.resolve_cache_dir(data.cache_dir) else None)
    threaded = width > 1 and len(items) > 1
    pt = native_parser.pool_parser_threads(width) if threaded else None
    try:
        def load_one(item):
            return _load_one_projected(item, schema, data, feature_dtype,
                                       threaded, parser_threads=pt,
                                       stats=stats, writer=writer)

        if threaded:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(max_workers=width) as pool:
                for res in pool.map(load_one, items):
                    on_result(res)
        else:
            for item in items:
                on_result(load_one(item))
    finally:
        if writer is not None:
            writer.close()
    return stats


def _pool_load_projected(mine: Sequence[tuple[int, str]], schema: DataSchema,
                         data: DataConfig, feature_dtype: str,
                         width: int) -> tuple[list, list]:
    """_run_ingest_pool collecting into a list: (per-file results in file
    order, ingest stats)."""
    results: list = []
    stats = _run_ingest_pool(mine, schema, data, feature_dtype, width,
                             results.append)
    return results, stats


def shard_rotation(seed: int, epoch: int, num_hosts: int) -> int:
    """Deterministic rotation offset of the host<->file-shard round-robin
    for `epoch` — a pure function of (seed, epoch, num_hosts) so every
    host (including one rejoining after an elastic reshape) derives the
    same offset with no coordination.  Epoch 0 is pinned to 0: a cold
    start is bit-identical to the legacy fixed round-robin, so cache and
    out-of-core entry keys written before the rotating plane stay valid."""
    if num_hosts <= 1 or epoch <= 0:
        return 0
    rng = np.random.default_rng(
        np.random.PCG64([int(seed), int(epoch), int(num_hosts), 0x51A4D]))
    return int(rng.integers(num_hosts))


def host_shard_assignment(n_files: int, host_index: int, num_hosts: int,
                          *, seed: int = 0, epoch: int = 0,
                          mode: str = "static") -> list[int]:
    """Global file indices host `host_index` owns for `epoch` — THE pure
    shard-assignment function of the pod data plane (ISSUE 20): a function
    of (process_index, process_count, seed, epoch) and nothing else.  Each
    host reads/decompresses/projects only its ~1/N slice of the source
    bytes; after an elastic reshape the surviving hosts re-derive the
    assignment from the new NUM_PROCESSES at the next epoch boundary, and
    a rejoining host picks its slice back up from the same formula.

    mode "static" (and "auto"): the fixed round-robin `i % num_hosts` —
    the legacy scheme, unchanged across epochs.
    mode "rotate": the round-robin rotated by `shard_rotation(seed, epoch,
    num_hosts)` — across epochs every host visits every slice (page-cache
    diversity after a reshape) while epoch 0 stays identical to "static".

    Either way the assignment is a PARTITION: every file owned by exactly
    one host, global file INDICES preserved (row ids `(file_idx << 40) +
    row` and the train/valid split keyed on them never depend on which
    host reads a file)."""
    if num_hosts <= 1:
        return list(range(n_files))
    r = (shard_rotation(seed, epoch, num_hosts)
         if mode == "rotate" else 0)
    return [i for i in range(n_files)
            if (i + r) % num_hosts == host_index]


def shard_assignment_digest(n_files: int, num_hosts: int, *, seed: int = 0,
                            epoch: int = 0, mode: str = "static") -> str:
    """Digest of the COMPLETE global file->host assignment for `epoch` —
    identical on every host iff the gang agrees on (n_files, num_hosts,
    seed, epoch, mode).  Journaled per epoch (host_skew row / the
    data-dryrun's shard_assign event) and compared by `pod-verify`: a host
    that desynced its shard view (stale file listing, wrong contract env)
    shows up as a digest split instead of silently double- or un-reading
    files."""
    import hashlib
    h = hashlib.blake2b(digest_size=16)
    h.update(f"{n_files}:{num_hosts}:{seed}:{epoch}:{mode}".encode())
    for host in range(num_hosts):
        idx = host_shard_assignment(n_files, host, num_hosts, seed=seed,
                                    epoch=epoch, mode=mode)
        h.update(np.asarray(idx, np.int64).tobytes())
        h.update(b";")
    return h.hexdigest()


def count_source_files(data: DataConfig) -> int:
    """Number of source data files the config resolves to — the `n_files`
    input of host_shard_assignment / shard_assignment_digest."""
    n = 0
    for p in data.paths:
        n += len(reader.list_data_files(p))
    return n


def host_file_shard(data: DataConfig, host_index: int = 0,
                    num_hosts: int = 1, *,
                    epoch: int = 0) -> list[tuple[int, str]]:
    """This host's (global file idx, path) list: paths expanded in config
    order and assigned by GLOBAL index through `host_shard_assignment`
    (successor of yarn/appmaster/TrainingDataSet.java:65-82).  The ONE
    source of the shard scheme — load_datasets, StreamingLoader, the
    out-of-core build, and the cache-hot probe must agree, or row ids (and
    the train/valid split keyed on them) would diverge across entry
    points.  Chaos site `data.host_shard` probes here: the elastic
    training drill kills one host exactly where its slice is derived."""
    from .. import chaos
    chaos.maybe_fail("data.host_shard", epoch=epoch)
    paths: list[str] = []
    for p in data.paths:
        paths.extend(reader.list_data_files(p))
    own = host_shard_assignment(
        len(paths), host_index, num_hosts,
        seed=data.shuffle_seed, epoch=epoch,
        mode=getattr(data, "host_shard", "auto"))
    own_set = set(own)
    return [(i, p) for i, p in enumerate(paths) if i in own_set]


def load_datasets(
    schema: DataSchema,
    data: DataConfig,
    host_index: int = 0,
    num_hosts: int = 1,
    feature_dtype: str = "float32",
) -> tuple[TabularDataset, TabularDataset]:
    """Load (train, valid) datasets for this host.

    Files are round-robined across hosts (successor of
    yarn/appmaster/TrainingDataSet.java:65-82); rows are split train/valid by
    the deterministic hash in `split` (fixes the re-drawn random split quirk,
    ssgd_monitor.py:395).  `feature_dtype` "bfloat16" stores features in the
    wire dtype (see wire_cast_fn) — half the host RAM and H2D bytes.
    """
    if data.out_of_core:
        from .outofcore import load_datasets_out_of_core
        return load_datasets_out_of_core(schema, data, host_index, num_hosts,
                                         feature_dtype=feature_dtype)

    # global row ids must be stable across hosts: derive from (file idx, row idx);
    # shard by index so duplicate path strings still get distinct ids
    mine = host_file_shard(data, host_index, num_hosts)
    t_ingest = time.perf_counter()
    num_threads = ingest_pool_width(data, len(mine))
    results, stats = _pool_load_projected(mine, schema, data, feature_dtype,
                                          num_threads)
    _emit_ingest_report(stats, num_threads,
                        time.perf_counter() - t_ingest, mode="load")

    feats, targs, weights, masks_v = [], [], [], []
    for cols, valid_mask in results:
        feats.append(cols["features"])
        targs.append(cols["target"])
        weights.append(cols["weight"])
        masks_v.append(valid_mask)

    if feats:
        features = np.concatenate(feats)
        target = np.concatenate(targs)
        weight = np.concatenate(weights)
        valid_mask = np.concatenate(masks_v)
    else:
        features = np.zeros((0, schema.feature_count), np.float32)
        target = np.zeros((0, 1), np.float32)
        weight = np.zeros((0, 1), np.float32)
        valid_mask = np.zeros((0,), bool)

    full = TabularDataset(features, target, weight)
    # one-time global row shuffle of the training partition: staged epochs
    # then only permute batch order per epoch (staged_epoch_blocks), which
    # together approximates row-level shuffling at a fraction of the host
    # cost.  The split-select and the shuffle COMPOSE into one gather
    # (train_idx[perm]) — a separate take(~mask) then take(perm) would
    # copy the whole training partition twice
    train_idx = np.nonzero(~valid_mask)[0]
    if len(train_idx) > 1:
        perm = np.random.default_rng(np.random.PCG64(
            data.split_seed ^ 0xC0FFEE)).permutation(len(train_idx))
        train_idx = train_idx[perm]
    train = full.take(train_idx)
    valid = full.take(np.nonzero(valid_mask)[0])
    return train, valid


def projected_cache_complete(schema: DataSchema, data: DataConfig,
                             host_index: int = 0, num_hosts: int = 1,
                             feature_dtype: str = "float32") -> bool:
    """True when EVERY file in this host's shard has a hot projected-cache
    entry — ingest will then run at npz-load speed (tens of millions of
    rows/s), so the streamed first epoch's parse/compute overlap buys
    nothing and the loaded tiers (device-resident / staged) are strictly
    better: they overlap nothing because there is nothing left to hide.
    Cost: one os.stat per source file plus one os.path.exists per entry.
    False on any miss, un-keyable file, or when no cache dir resolves."""
    from . import cache as cache_lib
    cache_dir = cache_lib.resolve_cache_dir(data.cache_dir)
    if cache_dir is None or not os.path.isdir(cache_dir):
        return False
    try:
        mine = host_file_shard(data, host_index, num_hosts)
        if not mine:
            return False
        version = resolved_cache_format(data)
        for file_idx, path in mine:
            # a v1-keyed entry (or a legacy r4-format .npz under either
            # key) is just as hot: the loader serves it — and upgrades it
            # to v2 — in one mmap-speed load, so counting only the current
            # form would permanently disable the fast path for caches
            # written by earlier formats
            versions = (version, 1) if version >= 2 else (version,)
            hot = False
            for v in versions:
                name = cache_lib.projected_entry_name(
                    path, data.delimiter, file_idx, schema, data.valid_ratio,
                    data.split_seed, feature_dtype, version=v)
                if name is None:
                    return False
                entry = os.path.join(cache_dir, name)
                if (os.path.exists(entry)
                        or os.path.exists(cache_lib.legacy_projected_path(
                            entry))):
                    hot = True
                    break
            if not hot:
                return False
        return True
    except OSError:
        return False


def wire_mode(schema: DataSchema, data: DataConfig,
              model_compute_dtype: str) -> str:
    """Resolved wire format for the FEATURES array: "float32" (no cast),
    "bfloat16", or "int8".  "auto" picks bfloat16 exactly when the model
    computes in bfloat16 (the model casts inputs to compute_dtype first —
    models/base.py — so the math is bit-identical) and no categorical id
    columns ride in the feature matrix (integer ids above 256 are not
    bf16-representable)."""
    mode = data.wire_dtype
    if mode == "auto":
        return ("bfloat16" if (model_compute_dtype == "bfloat16"
                               and not schema.categorical_indices)
                else "float32")
    if mode == "int8" and schema.categorical_indices:
        # JobConfig.validate rejects this combination up front; a direct
        # DataConfig user degrades to f32 rather than corrupting ids
        return "float32"
    return mode


def resident_feature_format(schema: DataSchema, data: DataConfig,
                            model_compute_dtype: str) -> str:
    """Resolved in-HBM feature format for the device-resident tier:
    "float32", "bfloat16", or "int8".  "auto"/"wire" keep whatever format
    the wire delivered (no silent precision change); "int8" forces the
    wire_params grid at tier build even when the per-batch wire is wider —
    quartering resident HBM vs f32 staging — with the dequant fused into
    the first-layer matmul where ops/pallas_int8_matmul is engaged
    (train/step.make_wire_decode's XLA op otherwise).  Categorical ids
    cannot ride the affine grid, so such schemas degrade to the wire
    format (mirror of wire_mode's guard; JobConfig.validate rejects the
    config up front)."""
    if data.resident_format == "int8" and not schema.categorical_indices:
        return "int8"
    return wire_mode(schema, data, model_compute_dtype)


def wire_quantize(x: np.ndarray, scale: np.ndarray,
                  offset: np.ndarray) -> np.ndarray:
    """The ONE int8 wire encoder (grid contract single-sourced: callers at
    parse time, per-block cast time, and the bench all share it; the
    device-side inverse is train/step.make_wire_decode):
    round((x - offset) / scale), saturated to [-127, 127], int8."""
    xf = np.asarray(x, np.float32)
    q = np.clip(np.rint((xf - offset) * (1.0 / scale)), -127, 127)
    return q.astype(np.int8)


def wire_dequantize(q: np.ndarray, scale, offset) -> np.ndarray:
    """Host-side inverse of wire_quantize: int8 grid values -> float32
    features (`x = q * scale + offset`).  The device-side inverse is
    train/step.make_wire_decode; this one is the SERVING ingest seam —
    runtime/serve_wire.py decodes request payloads that ride the same
    cache-v2 int8 wire encoding the training data plane stores on disk."""
    return (np.asarray(q, np.float32) * np.asarray(scale, np.float32)
            + np.asarray(offset, np.float32))


def wire_params(schema: DataSchema,
                data: DataConfig) -> tuple[np.ndarray, np.ndarray]:
    """Per-column (scale, offset) vectors for the int8 wire grid.

    The grid is STATIC — a pure function of config, not of data statistics
    — so every host, every block, every tier, and every resume quantizes
    identically (a data-derived grid would diverge across hosts in the
    streamed multihost epoch, whose blocks assemble into one global batch).
    Values encode as round((x - offset) / scale) clipped to [-127, 127];
    the default symmetric clip (DataConfig.wire_int8_clip, 8.0) never
    saturates ZSCALE-normalized data (Shifu clamps at 4-6 sigma upstream).
    """
    f = schema.feature_count
    scale = np.full((f,), float(data.wire_int8_clip) / 127.0, np.float32)
    offset = np.zeros((f,), np.float32)
    return scale, offset


def target_u8_exact(t: np.ndarray) -> bool:
    """True when every target value is an integer in [0, 255] — i.e. a u8
    wire cast round-trips bit-exactly (always true for binary labels)."""
    tf = np.asarray(t)
    if tf.dtype == np.uint8:
        return True
    if tf.dtype.kind not in "fiu":
        return False
    lo, hi = (tf.min(), tf.max()) if tf.size else (0.0, 0.0)
    if not (0.0 <= lo and hi <= 255.0):
        return False
    return bool(np.all(tf == np.floor(tf)))


def weight_all_ones(w: np.ndarray) -> bool:
    """True when every weight is exactly 1.0 — the column carries no
    information and can be elided from the wire (the device step
    synthesizes ones; weighted losses are bit-identical)."""
    wf = np.asarray(w)
    return bool(np.all(wf == 1.0))


def _compact_cols(b: dict, label_on, weight_on) -> dict:
    """Apply the compact target/weight wire to one block.  `label_on` /
    `weight_on` are tri-state: True (apply unconditionally — the caller
    proved the whole dataset qualifies, e.g. via the multihost agreement),
    False (off), or None (detect per block — content-driven and
    deterministic, so resume/replay compacts identically).

    Never raises on unqualified data: forced modes ("uint8"/"elide") are
    enforced DATASET-wide by the train loop's _prepare_tiers — a per-block
    raise would false-positive on legitimately synthetic rows, e.g. the
    zero-WEIGHT padding of a streamed epoch's tail block under all-ones
    user weights."""
    t = b.get("target")
    if t is not None and t.dtype != np.uint8 and label_on is not False:
        if label_on or target_u8_exact(t):
            b = dict(b)
            b["target"] = np.asarray(t).astype(np.uint8)
    w = b.get("weight")
    if w is not None and weight_on is not False:
        if weight_on or weight_all_ones(w):
            b = dict(b)
            del b["weight"]
    return b


def wire_row_bytes(schema: DataSchema, data: DataConfig,
                   model_compute_dtype: str,
                   compact: bool = True) -> int:
    """Bytes one row costs on the H2D wire under the resolved formats (the
    compact target/weight wire assumed applicable when `compact`) — used to
    size staged chunks by bytes rather than rows."""
    mode = wire_mode(schema, data, model_compute_dtype)
    per_feat = {"int8": 1, "bfloat16": 2}.get(mode, 4)
    n_tgt = max(len(schema.all_target_indices), 1)
    tgt = (1 if (compact and data.wire_label_dtype != "float32") else 4)
    wgt = (0 if (compact and data.wire_weight_mode != "float32") else 4)
    return schema.feature_count * per_feat + n_tgt * tgt + wgt


def wire_cast_fn(schema: DataSchema, data: DataConfig,
                 model_compute_dtype: str, compact=False):
    """Host-side cast applied to batches/blocks before device_put, or None.

    bfloat16 wire halves H2D bytes and the device-resident tier's HBM
    footprint; int8 wire (see wire_params) quarters them, dequantized on
    device by the step builders (train/step.py make_wire_decode).

    `compact` additionally engages the target/weight wire
    (DataConfig.wire_label_dtype / wire_weight_mode): targets ride as u8
    when exactly representable and all-ones weight columns are elided —
    38 -> 31 B/row on the int8 wire for a 30-feature schema.  Pass True for
    per-block detection (single-host paths: content-driven, deterministic
    across resume/replay), or an explicit (label_ok, weight_ok) bool pair
    when the decision was made dataset-wide (the multihost tiers agree via
    allgather — per-block detection there could diverge across hosts and
    deadlock the gang on mismatched program signatures).  False (the
    default) keeps the r4 wire: features-only casting, so eval paths and
    external callers are unchanged.
    """
    mode = wire_mode(schema, data, model_compute_dtype)
    if compact is False or compact is None:
        label_on = weight_on = False
    else:
        if compact is True:
            label_on = weight_on = None  # per-block detection
        else:
            label_on, weight_on = compact
        if data.wire_label_dtype == "float32":
            label_on = False
        if data.wire_weight_mode == "float32":
            weight_on = False
    compacting = label_on is not False or weight_on is not False

    def compact_fn(b: dict) -> dict:
        if not compacting:
            return b
        return _compact_cols(b, label_on, weight_on)

    if mode == "int8":
        scale, offset = wire_params(schema, data)

        def cast_q(b: dict) -> dict:
            f = b.get("features")
            if f is not None and f.dtype != np.int8:  # not yet wire dtype
                b = dict(b)
                b["features"] = wire_quantize(f, scale, offset)
            return compact_fn(b)

        return cast_q
    if mode != "bfloat16":
        return compact_fn if compacting else None
    import ml_dtypes

    def cast(b: dict) -> dict:
        f = b.get("features")
        if f is not None and f.dtype == np.float32:  # not yet wire dtype
            b = dict(b)
            b["features"] = f.astype(ml_dtypes.bfloat16)
        return compact_fn(b)

    return cast


class StreamingLoader:
    """Background-parse loader for the streamed first epoch.

    Parses the host's file shard on a background pool (same per-file
    parse/project/split as load_datasets) and exposes the results two ways:

    - `first_epoch_blocks(batch_size, block_batches)`: a generator yielding
      stacked (nb, B, ...) TRAIN blocks as soon as enough rows have parsed —
      the staged-tier feed that lets the first epoch's device compute overlap
      the remaining files' parse.  Rows arrive in file order (the global
      shuffle is applied to the retained dataset afterwards); a remainder
      that doesn't fill a batch carries over to the next block, and the
      final partial batch is trained only via the retained dataset's later
      epochs (drop-remainder semantics, same as staged_epoch_blocks).
    - `datasets()`: blocks until every file parsed; returns the SAME
      (train, valid) pair load_datasets would have built (identical split,
      identical global permutation), for epochs after the first.
    """

    def __init__(self, schema: DataSchema, data: DataConfig,
                 feature_dtype: str = "float32",
                 host_index: int = 0, num_hosts: int = 1):
        self._schema = schema
        self._data = data
        self._feature_dtype = feature_dtype
        # same round-robin + GLOBAL file index as load_datasets, so row ids
        # (and therefore the train/valid split) are identical either way
        self._items = host_file_shard(data, host_index, num_hosts)
        self._results: list[tuple[dict, np.ndarray]] = []
        self._datasets: Optional[tuple[TabularDataset, TabularDataset]] = None
        self.real_batches = 0  # set by first_epoch_blocks

        import queue
        import threading
        # parse-result queue depth: DataConfig.prefetch_depth (auto=0 keeps
        # the historical 4 — the parse queue has no per-epoch ledger to
        # adapt from; only the cross-epoch feeder resizes itself)
        self._q: "queue.Queue" = queue.Queue(maxsize=data.prefetch_depth or 4)
        self._abort = False  # see abort_blocks()
        self._thread = threading.Thread(target=self._produce, daemon=True)
        self._thread.start()

    def _produce(self) -> None:
        data = self._data
        t_ingest = time.perf_counter()
        num_threads = ingest_pool_width(data, len(self._items))
        try:
            # the pool's writer is closed (entries durable) before the
            # stats return — i.e. before the hot-cache probe can run —
            # and before an error is forwarded to the consumer
            stats = _run_ingest_pool(self._items, self._schema, data,
                                     self._feature_dtype, num_threads,
                                     self._q.put)
        except BaseException as e:  # surface parse errors to the consumer
            self._q.put(e)
            return
        _emit_ingest_report(stats, num_threads,
                            time.perf_counter() - t_ingest, mode="stream")
        self._q.put(None)

    def first_epoch_blocks(self, batch_size: int, block_batches: int,
                           pad_tail: bool = True) -> Iterator[dict]:
        """Stacked train blocks in arrival order; retains every result for
        datasets().  Must be consumed before datasets() is called.

        Every yielded block has the SAME static shape (block_batches,
        batch_size, ...) so the scan step compiles exactly once.  With
        `pad_tail` the final partial block is completed with ZERO-WEIGHT
        rows — exact for the weight-normalized losses (weighted_mse divides
        by count(w != 0), weighted_bce by sum(w); zero-weight rows add zero
        loss and zero gradient), so every parsed train row trains in the
        streamed epoch.  Callers whose loss/regularizer is not
        weight-gated (bce ignores weights; an L2 penalty applies per step
        regardless) pass pad_tail=False and the tail rows simply wait for
        the retained dataset's later epochs.  `real_batches` counts batches
        containing at least one real row (the train_error denominator)."""
        self.real_batches = 0
        buf: list[dict] = []
        buffered = 0
        target_rows = batch_size * block_batches

        def take_rows(take: int) -> dict:
            nonlocal buffered
            parts: list[dict] = []
            got = 0
            while got < take:
                head = buf[0]
                need = take - got
                n = head["features"].shape[0]
                if n <= need:
                    parts.append(buf.pop(0))
                    got += n
                else:
                    parts.append({k: v[:need] for k, v in head.items()})
                    buf[0] = {k: v[need:] for k, v in head.items()}
                    got += need
            buffered -= take
            return {k: np.concatenate([p[k] for p in parts])
                    for k in parts[0]}

        def as_block(flat: dict) -> dict:
            return {k: v.reshape(block_batches, batch_size, *v.shape[1:])
                    for k, v in flat.items()}

        import queue as queue_lib
        while True:
            try:
                item = self._q.get(timeout=0.1)
            except queue_lib.Empty:
                if self._abort:
                    return
                continue
            if item is None:
                break
            if isinstance(item, BaseException):
                raise item
            cols, valid_mask = item
            self._results.append((cols, valid_mask))
            if self._abort:
                # cooperative shutdown (abort_blocks): the item was already
                # RETAINED above, so nothing is lost; the caller's _drain
                # takes over the queue from here
                return
            tm = ~valid_mask
            if tm.any():
                buf.append({k: v[tm] for k, v in cols.items()})
                buffered += int(tm.sum())
            while buffered >= target_rows:
                self.real_batches += block_batches
                yield as_block(take_rows(target_rows))
        if buffered and pad_tail:
            n_real = buffered
            flat = take_rows(n_real)
            pad = target_rows - n_real
            padded = {}
            for k, v in flat.items():
                padded[k] = np.concatenate(
                    [v, np.zeros((pad,) + v.shape[1:], v.dtype)])
            padded["weight"][n_real:] = 0.0
            self.real_batches += -(-n_real // batch_size)
            yield as_block(padded)

    def _drain(self) -> None:
        """Join the background parse, collecting anything the block
        generator did not consume.  Timed gets, no unconditional blocking:
        the None sentinel may already have been consumed by
        first_epoch_blocks (a bare get() would hang forever), and the
        producer may be blocked on a full queue (a bare join() first would
        deadlock) — the loop drains and watches thread liveness together."""
        import queue as queue_lib
        done = False
        while not done:
            try:
                item = self._q.get(timeout=0.1)
            except queue_lib.Empty:
                if not self._thread.is_alive():
                    done = True
                continue
            if item is None:
                done = True
            elif isinstance(item, BaseException):
                raise item
            else:
                self._results.append(item)
        self._thread.join()

    def _partition(self, want_valid: bool) -> TabularDataset:
        feats, targs, weights = [], [], []
        for cols, valid_mask in self._results:
            m = valid_mask if want_valid else ~valid_mask
            if m.any():
                feats.append(cols["features"][m])
                targs.append(cols["target"][m])
                weights.append(cols["weight"][m])
        if not feats:
            return TabularDataset(
                np.zeros((0, self._schema.feature_count), np.float32),
                np.zeros((0, 1), np.float32), np.zeros((0, 1), np.float32))
        return TabularDataset(np.concatenate(feats), np.concatenate(targs),
                              np.concatenate(weights))

    def abort_blocks(self) -> None:
        """Cooperative shutdown of a first_epoch_blocks consumer running in
        ANOTHER thread (the streamed epoch's prefetch producer): the
        generator exits at its next poll instead of blocking on the parse
        queue forever, so datasets()/_drain never race it for items.
        Safe because every item the generator consumed was already appended
        to the retained results before any early return."""
        self._abort = True

    def train_rows_total(self) -> int:
        """Total TRAIN rows this host parsed (drains the background parse;
        counts masks only — no array assembly), for skipped-row accounting
        when a streamed epoch ends early."""
        if self._datasets is not None:
            return self._datasets[0].num_rows
        self._drain()
        return int(sum(int((~m).sum()) for _, m in self._results))

    def valid_dataset(self) -> TabularDataset:
        """The valid partition only — cheap (a few % of the rows), so the
        streamed epoch's end-of-epoch eval does not pay for the full train
        assembly."""
        if self._datasets is not None:
            return self._datasets[1]
        if not hasattr(self, "_valid"):
            self._drain()
            self._valid = self._partition(want_valid=True)
        return self._valid

    def train_dataset(self) -> TabularDataset:
        """The train partition with the same global shuffle load_datasets
        applies — deferred until an epoch actually needs the retained
        dataset (an epochs=1 streamed job never assembles it)."""
        return self.datasets()[0]

    def datasets(self) -> tuple[TabularDataset, TabularDataset]:
        """(train, valid), identical to load_datasets' output.  Joins the
        background parse if first_epoch_blocks was not (fully) consumed."""
        if self._datasets is not None:
            return self._datasets
        self._drain()
        valid = self.valid_dataset()
        train = self._partition(want_valid=False)
        if train.num_rows > 1:  # same global shuffle as load_datasets
            perm = np.random.default_rng(np.random.PCG64(
                self._data.split_seed ^ 0xC0FFEE)).permutation(train.num_rows)
            train = train.take(perm)
        self._results = []
        self._datasets = (train, valid)
        return self._datasets


def epoch_permutation(n: int, *, shuffle: bool = True, seed: int = 0,
                      epoch: int = 0) -> np.ndarray:
    """THE per-epoch order stream — a pure function of (seed, epoch), so
    every host and every restart agrees.  Single-sourced: batch_iterator
    (row order), staged_epoch_blocks (block order), the device-resident
    tier (train/loop.py), and epoch_order_digest all draw from HERE, so
    the journaled order fingerprint can never silently drift from the
    order the tiers actually train in."""
    if not shuffle:
        return np.arange(n)
    return np.random.default_rng(
        np.random.PCG64(seed * 1_000_003 + epoch)).permutation(n)


def staged_epoch_offset(num_rows: int, batch_size: int, *,
                        shuffle: bool = True, epoch: int = 0) -> int:
    """The staged tier's per-epoch row-offset rotation (batch composition
    drifts across epochs when rows don't divide the batch evenly) —
    single-sourced next to epoch_permutation for the same reason."""
    nb_total = num_rows // batch_size
    slack = num_rows - nb_total * batch_size
    return (epoch * 997) % (slack + 1) if (shuffle and slack > 0) else 0


def batch_iterator(
    ds: TabularDataset,
    batch_size: int,
    *,
    shuffle: bool = True,
    seed: int = 0,
    epoch: int = 0,
    drop_remainder: bool = True,
) -> Iterator[dict[str, np.ndarray]]:
    """Yield {'features','target','weight'} batches with static shapes.

    Shuffle order is a pure function of (seed, epoch) so every host and every
    restart agrees.  drop_remainder keeps shapes static for XLA; the dropped
    tail rotates across epochs because the permutation changes per epoch.
    """
    n = ds.num_rows
    if n == 0:
        return
    order = epoch_permutation(n, shuffle=shuffle, seed=seed, epoch=epoch)
    num_full = n // batch_size
    end = num_full * batch_size if drop_remainder else n
    for start in range(0, end, batch_size):
        idx = order[start:start + batch_size]
        yield {
            "features": fast_take(ds.features, idx),
            "target": ds.target[idx],
            "weight": ds.weight[idx],
        }


def prefetch_to_device(batches: Iterator[dict[str, np.ndarray]],
                       mesh=None, size: int = 2, put_fn=None) -> Iterator[dict]:
    """Background-thread device feed: host batches are device_put (with
    data-axis sharding when a mesh is given) ahead of consumption, so host
    parse/shuffle overlaps device compute — the double-buffering the
    reference's feed_dict loop could never do (ssgd_monitor.py:271-276
    blocked the worker on every batch).

    `put_fn` overrides the host->device placement (used by the staged-epoch
    path, whose arrays shard on their second axis).
    """
    import queue
    import threading

    import jax

    from ..parallel import sharding as shard_lib

    if put_fn is None:
        def put_fn(b):
            if mesh is not None:
                return shard_lib.shard_batch(b, mesh)
            return {k: jax.device_put(v) for k, v in b.items()}

    # per-batch host latency (produce + wire-cast + device placement),
    # observed in the producer so the histogram sees the true host cost
    # rather than the consumer's (usually zero) queue wait
    lat = obs.histogram("data_batch_latency_seconds",
                        "host batch production + device placement latency")

    def timed_put(b):
        t0 = time.perf_counter()
        out = put_fn(b)
        lat.observe(time.perf_counter() - t0)
        return out

    if size <= 0:
        for b in batches:
            yield timed_put(b)
        return

    q: "queue.Queue" = queue.Queue(maxsize=size)
    _END = object()

    def producer() -> None:
        try:
            for b in batches:
                q.put(timed_put(b))
        except BaseException as e:  # surface errors to the consumer
            q.put(e)
            return
        q.put(_END)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is _END:
            return
        if isinstance(item, BaseException):
            raise item
        yield item


def next_prefetch_depth(current: int, exposed_fraction: float,
                        lo: int = 2, hi: int = 8) -> int:
    """Auto prefetch-depth policy (DataConfig.prefetch_depth == 0): one
    step per epoch, driven by the goodput ledger's exposed-input fraction
    (the share of the epoch wall the device sat waiting for input).
    Resizes the feeder's DEVICE staging gate — the HBM-side run-ahead
    (the host queue keeps its fixed depth).  Visible starvation doubles
    the depth — a starved consumer needs more run-ahead NOW, and a
    half-step would leave it starved for several more epochs; a fully
    hidden input path decays one step per epoch toward `lo`, releasing
    the HBM the extra staged chunks pin.  `hi`=8 bounds worst-case
    run-ahead to 8 chunks (~32 MB wire each — ~256 MB HBM), a deliberate
    ceiling since this gate supersedes DataConfig.prefetch in auto mode."""
    if exposed_fraction > 0.05:
        return min(max(current * 2, lo), hi)
    if exposed_fraction < 0.01 and current > lo:
        return current - 1
    return current


def epoch_order_digest(tier: str, num_rows: int, batch_size: int, *,
                       shuffle: bool = True, seed: int = 0,
                       epoch: int = 0) -> Optional[str]:
    """blake2b hex digest of THE batch order a tier draws for (seed, epoch)
    — the restart/resume determinism contract made checkable: overlap on
    vs off, and a resumed epoch vs the uninterrupted run, must journal the
    same digest (`overlap_report.order_digest`).

    Built from the SAME epoch_permutation / staged_epoch_offset the tiers
    themselves draw from (pinned against the real iterators by
    tests/test_overlap.py): `staged` = block permutation + row-offset
    rotation (staged_epoch_blocks); `batch` = batch_iterator's row
    permutation; `resident` = the train loop's block order.  None when
    the tier has no deterministic (seed, epoch) order (the streamed
    first epoch trains in file-arrival order)."""
    import hashlib

    if tier == "staged":
        nb_total = num_rows // batch_size
        if nb_total == 0:
            return None
        offset = staged_epoch_offset(num_rows, batch_size, shuffle=shuffle,
                                     epoch=epoch)
        order = epoch_permutation(nb_total, shuffle=shuffle, seed=seed,
                                  epoch=epoch)
        payload = np.concatenate([[offset], order]).astype(np.int64)
    elif tier in ("batch", "resident"):
        n = num_rows if tier == "batch" else num_rows // batch_size
        if n == 0:
            return None
        payload = np.asarray(epoch_permutation(n, shuffle=shuffle, seed=seed,
                                               epoch=epoch), np.int64)
    else:
        return None  # "stream" and unknown tiers: no (seed, epoch) order
    return hashlib.blake2b(payload.tobytes(), digest_size=16).hexdigest()


def interleaved_epoch_order(host_row_ids: Sequence[np.ndarray],
                            local_batch_size: int, *,
                            shuffle: bool = True, seed: int = 0,
                            epoch: int = 0) -> np.ndarray:
    """The pod data plane's deterministic global batch order, as row ids.

    Global batch `b` of `epoch` is the rank-order concatenation of every
    host's rows `local_perm[b*lbs : (b+1)*lbs]`, where `local_perm` is the
    SAME `epoch_permutation(min_rows, ...)` stream on every host (same
    (min_rows, seed, epoch) on each rank — exactly what the cross-host
    order-digest agreement in the `host_skew` row pins).  A single process
    emulating N shards through this function therefore reproduces a real
    N-host run's global order bit-for-bit — the loss/AUC-identity contract
    of the sharded ingest plane (tests/test_pod_data_plane.py).

    `host_row_ids[h]` holds host h's global row ids in its local storage
    order; rows past `min_rows` (imbalanced shards) and the batch-tail
    remainder are dropped, matching the train loop's min-host-rows
    agreement and drop-remainder semantics.  Returns a flat (steps *
    n_hosts * lbs,) id array; reshape to (steps, n_hosts, lbs) for
    per-batch views."""
    if not host_row_ids:
        return np.zeros((0,), np.int64)
    min_rows = min(len(r) for r in host_row_ids)
    steps = min_rows // local_batch_size
    if steps == 0:
        return np.zeros((0,), np.int64)
    perm = epoch_permutation(min_rows, shuffle=shuffle, seed=seed,
                             epoch=epoch)
    take = perm[: steps * local_batch_size]
    cols = [np.asarray(r, np.int64)[take].reshape(steps, local_batch_size)
            for r in host_row_ids]
    return np.stack(cols, axis=1).reshape(-1)


class _DepthGate:
    """Resizable counting gate bounding the feeder's device queue: the
    placement thread acquires a slot per staged item, the consumer releases
    one per item drained.  A plain Queue(maxsize=) cannot do this — the
    auto mode resizes the bound BETWEEN epochs (next_prefetch_depth), and
    queue maxsize is fixed at construction.  Shrinking records a deficit
    that absorbs future releases instead of blocking anyone."""

    def __init__(self, depth: int):
        import threading
        self._sem = threading.Semaphore(depth)
        self._lock = threading.Lock()
        self._deficit = 0
        self.depth = depth

    def acquire(self, timeout: float) -> bool:
        return self._sem.acquire(timeout=timeout)

    def release(self) -> None:
        with self._lock:
            if self._deficit > 0:
                self._deficit -= 1
                return
        self._sem.release()

    def resize(self, depth: int) -> None:
        with self._lock:
            delta = depth - self.depth
            self.depth = depth
            if delta < 0:
                self._deficit += -delta
                return
            # pay down an outstanding shrink deficit BEFORE releasing new
            # permits: a cancelled absorption already restores one unit of
            # future capacity, and releasing on top of it would transiently
            # admit more in-flight items than the new bound
            paid = min(self._deficit, delta)
            self._deficit -= paid
            delta -= paid
        for _ in range(delta):
            self._sem.release()


class FeederError(RuntimeError):
    """The persistent feeder died without delivering its epoch — raised in
    the CONSUMER so a dead producer thread fails the epoch loudly instead
    of deadlocking the queue (docs/ROBUSTNESS.md site `data.feeder`)."""


class EpochFeeder:
    """Persistent cross-epoch input feeder — the overlap engine's producer
    side (docs/PERF.md "Overlap engine").

    Replaces the per-epoch producer thread prefetch_to_device spins up:
    ONE pair of host threads lives for the whole job and runs ahead across
    epoch boundaries, so epoch N+1's shuffle + block assembly (and its
    first device_put staging) happen while epoch N is still executing on
    device and while its eval dispatch tail drains — the serialized wall
    between epochs the reference's train→eval→shuffle loop paid every
    epoch (ssgd_monitor.py-style).  Two pipeline stages double-buffer the
    H2D staging itself:

      assembly thread:  epoch_source(epoch) → host items   (shuffle+gather)
      placement thread: put_fn(item) → device items        (cast+device_put)

    so chunk k+1 assembles while chunk k stages.  Determinism is untouched:
    `epoch_source` draws each epoch's order as a pure function of
    (seed, epoch) exactly as the per-epoch path did, and items are
    delivered strictly in epoch order — a restart/resume consumes
    byte-identical batches (pinned by tests/test_overlap.py).

    Bounds: the host staging queue holds `host_depth` assembled chunks
    (DataConfig.prefetch_depth; host RAM), the device queue `depth` staged
    chunks (DataConfig.prefetch; HBM).  `set_depth` resizes the device
    bound between epochs (the auto mode, next_prefetch_depth).

    Failure contract: an assembly/placement exception (including the
    `data.feeder` chaos probe, evaluated at each epoch's assembly start)
    is forwarded and re-raised in the consumer; a thread that dies without
    a sentinel raises FeederError at the consumer's next poll — never a
    silent deadlock.  `close()` (idempotent; the train loop's finally)
    aborts both threads and discards anything produced ahead."""

    _POLL_S = 0.1

    def __init__(self, epoch_source, put_fn, epochs, *,
                 depth: int = 2, host_depth: int = 4):
        import queue
        import threading

        self._source = epoch_source
        self._put_fn = put_fn
        self._epochs = list(epochs)
        self._abort = threading.Event()
        self._hostq: "queue.Queue" = queue.Queue(maxsize=max(host_depth, 1))
        self._devq: "queue.Queue" = queue.Queue()  # bounded by _gate
        self._gate = _DepthGate(max(depth, 1))
        self._staged_lock = threading.Lock()
        self._staged = 0  # 'item' records in devq (sentinels excluded)
        self._prod_s: dict[int, float] = {}  # epoch -> host seconds
        self._lat = obs.histogram(
            "data_batch_latency_seconds",
            "host batch production + device placement latency")
        self._threads = [
            threading.Thread(target=self._assemble, daemon=True,
                             name="shifu-feeder-assemble"),
            threading.Thread(target=self._place, daemon=True,
                             name="shifu-feeder-place"),
        ]
        for t in self._threads:
            t.start()

    # -- producer side ------------------------------------------------------

    def _put(self, q, item) -> bool:
        import queue as queue_lib
        while not self._abort.is_set():
            try:
                q.put(item, timeout=self._POLL_S)
                return True
            except queue_lib.Full:
                continue
        return False

    def _assemble(self) -> None:
        from .. import chaos
        try:
            for ep in self._epochs:
                if self._abort.is_set():
                    return
                # chaos site "data.feeder": the feeder thread boundary —
                # a raise here must fail the epoch in the CONSUMER
                chaos.maybe_fail("data.feeder", epoch=ep)
                prod = 0.0
                t0 = time.perf_counter()
                for item in self._source(ep):
                    prod += time.perf_counter() - t0
                    if not self._put(self._hostq, ("item", ep, item, prod)):
                        return
                    prod = 0.0
                    t0 = time.perf_counter()
                prod += time.perf_counter() - t0
                if not self._put(self._hostq, ("end", ep, None, prod)):
                    return
            self._put(self._hostq, ("done", None, None, 0.0))
        except BaseException as e:  # forwarded, re-raised by the consumer
            self._put(self._hostq, ("error", None, e, 0.0))

    def _host_get(self):
        """Next host-queue record, or None when assembly is gone for good.
        The dead-thread check re-polls the queue non-blocking FIRST: the
        assembly thread's final sentinel ('done'/'error') may land between
        a get timeout and its exit, and returning on liveness alone would
        drop it — the consumer would then see a generic FeederError instead
        of the original error (same defense _get applies device-side)."""
        import queue as queue_lib
        while not self._abort.is_set():
            try:
                return self._hostq.get(timeout=self._POLL_S)
            except queue_lib.Empty:
                if not self._threads[0].is_alive():
                    try:
                        return self._hostq.get_nowait()
                    except queue_lib.Empty:
                        return None
        return None

    def _place(self) -> None:
        place_s: dict[int, float] = {}
        try:
            while not self._abort.is_set():
                item = self._host_get()
                if item is None:
                    return
                tag, ep, payload, prod = item
                if tag == "item":
                    t0 = time.perf_counter()
                    dev = self._put_fn(payload)
                    dt = time.perf_counter() - t0
                    self._lat.observe(prod + dt)
                    place_s[ep] = place_s.get(ep, 0.0) + prod + dt
                    while not self._abort.is_set():
                        if self._gate.acquire(timeout=self._POLL_S):
                            with self._staged_lock:
                                self._staged += 1
                            self._devq.put(("item", ep, dev))
                            break
                    continue
                if tag == "end":
                    total = place_s.pop(ep, 0.0) + prod
                    self._devq.put(("end", ep, total))
                    continue
                self._devq.put((tag, ep, payload))  # done / error
                return
        except BaseException as e:
            self._devq.put(("error", None, e))

    # -- consumer side ------------------------------------------------------

    def _get(self):
        import queue as queue_lib
        while True:
            try:
                return self._devq.get(timeout=self._POLL_S)
            except queue_lib.Empty:
                if self._abort.is_set() or not any(
                        t.is_alive() for t in self._threads):
                    # one last non-blocking look: the sentinel may have
                    # landed between the timeout and the liveness check
                    try:
                        return self._devq.get_nowait()
                    except queue_lib.Empty:
                        raise FeederError(
                            "input feeder died without delivering its "
                            "epoch (producer thread gone; see the journal "
                            "for a chaos_inject or the original error)")

    def epoch(self, epoch: int) -> Iterator:
        """Device items for `epoch`, in deterministic order.  Epochs must
        be consumed in the order the feeder was constructed with."""
        while True:
            tag, ep, payload = self._get()
            if tag == "error":
                self._abort.set()
                raise payload
            if tag == "done":
                raise FeederError(
                    f"feeder exhausted before epoch {epoch} (consumed out "
                    "of order?)")
            if ep != epoch:
                self._abort.set()
                raise FeederError(
                    f"feeder/consumer epoch mismatch: got {ep}, "
                    f"expected {epoch}")
            if tag == "end":
                self._prod_s[epoch] = payload
                return
            with self._staged_lock:
                self._staged -= 1
            try:
                yield payload
            finally:
                self._gate.release()

    def production_seconds(self, epoch: int) -> float:
        """Host seconds this epoch's items cost to assemble + stage (the
        producer-side, per-host-attributable input cost — the straggler
        line's lens), regardless of WHEN they ran; 0.0 until the epoch's
        end marker was consumed."""
        return self._prod_s.get(epoch, 0.0)

    def ready_ahead(self) -> int:
        """Items already staged on device beyond what the consumer pulled —
        at an epoch boundary this is the NEXT epoch's prefetched chunks
        (the boundary work the overlap hid).  Counts real items only
        (epoch-end sentinels in the queue never held gate slots and would
        overstate the report)."""
        with self._staged_lock:
            return max(self._staged, 0)

    @property
    def depth(self) -> int:
        return self._gate.depth

    def set_depth(self, depth: int) -> None:
        """Resize the device-queue bound (auto mode; between epochs)."""
        self._gate.resize(max(int(depth), 1))

    def close(self) -> None:
        """Abort both threads and discard run-ahead items (early stop,
        SIGTERM drain, mid-epoch exceptions).  Idempotent."""
        import queue as queue_lib
        self._abort.set()
        deadline = time.monotonic() + 10.0
        while (any(t.is_alive() for t in self._threads)
               and time.monotonic() < deadline):
            try:  # drain so a producer blocked on a full gate/queue exits
                self._devq.get_nowait()
                self._gate.release()
            except queue_lib.Empty:
                time.sleep(self._POLL_S / 2)
        for t in self._threads:
            t.join(timeout=1.0)


def staged_epoch_blocks(
    ds: TabularDataset,
    batch_size: int,
    *,
    shuffle: bool = True,
    seed: int = 0,
    epoch: int = 0,
    block_batches: int = 32,
) -> Iterator[dict[str, np.ndarray]]:
    """Yield {'features': (nb, B, F), ...} stacked blocks for the staged
    (scan-on-device) epoch path.

    Host cost per block is a gather of whole contiguous batches (large
    memcpys), not per-row fancy indexing: the dataset is viewed as
    (num_batches, B, ...) and only the *batch order* is permuted per epoch,
    with a cheap row-offset rotation so batch composition drifts across
    epochs.  Row-level shuffling happens once at load time (load_datasets
    applies a global permutation), which together with batch-order shuffling
    is the standard approximation for large-scale SGD.
    """
    n = ds.num_rows
    nb_total = n // batch_size
    if nb_total == 0:
        return
    offset = staged_epoch_offset(n, batch_size, shuffle=shuffle, epoch=epoch)

    def as_blocks(arr: np.ndarray) -> np.ndarray:
        return arr[offset:offset + nb_total * batch_size].reshape(
            nb_total, batch_size, *arr.shape[1:])

    feats = as_blocks(ds.features)
    targ = as_blocks(ds.target)
    wgt = as_blocks(ds.weight)

    order = epoch_permutation(nb_total, shuffle=shuffle, seed=seed,
                              epoch=epoch)

    for start in range(0, nb_total, block_batches):
        idx = order[start:start + block_batches]
        yield {
            "features": fast_take(feats, idx),
            "target": targ[idx],
            "weight": wgt[idx],
        }


def num_batches(ds: TabularDataset, batch_size: int, drop_remainder: bool = True) -> int:
    if drop_remainder:
        return ds.num_rows // batch_size
    return -(-ds.num_rows // batch_size)


def pad_to_batch(batch: dict[str, np.ndarray], batch_size: int) -> tuple[dict[str, np.ndarray], np.ndarray]:
    """Pad a short batch up to batch_size; returns (padded, validity mask).

    Padding rows get weight 0 so they contribute nothing to weighted losses or
    metrics — used by full-dataset eval so no validation row is dropped (the
    reference evaluates the full valid set each epoch, ssgd_monitor.py:281-284).
    """
    n = batch["features"].shape[0]
    if n == batch_size:
        return batch, np.ones((batch_size,), bool)
    pad = batch_size - n
    out = {}
    for k, v in batch.items():
        out[k] = np.concatenate([v, np.zeros((pad,) + v.shape[1:], v.dtype)])
    out["weight"][n:] = 0.0
    mask = np.zeros((batch_size,), bool)
    mask[:n] = True
    return out, mask
