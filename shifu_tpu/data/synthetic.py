"""Synthetic tabular data for tests and benchmarks.

Plays the role of the reference's bundled WDBC demo dataset (30 z-scaled
features, binary target — reference: resources/ssgd.py:20 FEATURE_COUNT=30):
a reproducible generator for normalized pipe-delimited rows with a learnable
logistic ground truth, plus writers that produce the exact gzip on-disk format
the reference trainer consumed (ssgd_monitor.py:375-385).
"""

from __future__ import annotations

import gzip
import os
from typing import Optional, Sequence

import numpy as np

from ..config.schema import ColumnSpec, DataSchema


def make_schema(
    num_features: int = 30,
    with_weight: bool = False,
    num_categorical: int = 0,
    vocab_size: int = 100,
    num_targets: int = 1,
) -> DataSchema:
    """Column layout: [targets..., (weight,) f0..fN-1]; the last
    num_categorical features are categorical; num_targets > 1 models Shifu
    multi-target mode."""
    columns = [ColumnSpec(index=t, name=f"target{t}" if num_targets > 1 else "target",
                          is_target=True)
               for t in range(num_targets)]
    weight_index = -1
    offset = num_targets
    if with_weight:
        weight_index = offset
        columns.append(ColumnSpec(index=weight_index, name="wgt", is_weight=True))
        offset += 1
    selected = []
    for i in range(num_features):
        idx = offset + i
        is_cat = i >= num_features - num_categorical
        columns.append(ColumnSpec(
            index=idx, name=f"f{i}", is_selected=True,
            is_categorical=is_cat, vocab_size=vocab_size if is_cat else 0))
        selected.append(idx)
    return DataSchema(
        columns=tuple(columns),
        target_index=0,
        weight_index=weight_index,
        selected_indices=tuple(selected),
        target_indices=tuple(range(num_targets)) if num_targets > 1 else (),
    )


def make_rows(
    num_rows: int,
    schema: DataSchema,
    seed: int = 0,
    noise: float = 0.5,
) -> np.ndarray:
    """Generate (N, C) raw rows matching `schema` column indices.

    Numeric features ~ N(0,1) (post-ZSCALE normalization, like the reference's
    normalized input); categorical features are integer ids stored as floats.
    Target = Bernoulli(sigmoid(w.x + noise)) for a fixed random w, so models
    can beat AUC 0.5 by a wide, stable margin.
    """
    rng = np.random.default_rng(seed)
    ncols = max(c.index for c in schema.columns) + 1
    rows = np.zeros((num_rows, ncols), dtype=np.float32)

    cat_set = set(schema.categorical_indices)
    num_idx = [i for i in schema.selected_indices if i not in cat_set]
    by_index = {c.index: c for c in schema.columns}

    logits = np.zeros(num_rows, dtype=np.float64)
    if num_idx:
        x = rng.standard_normal((num_rows, len(num_idx))).astype(np.float32)
        rows[:, num_idx] = x
        w = rng.standard_normal(len(num_idx))
        w /= max(np.linalg.norm(w), 1e-9)  # unit norm: signal strength is
        logits += 1.5 * (x @ w)            # seed-independent (std 1.5)
    for i in sorted(cat_set):
        vocab = max(by_index[i].vocab_size, 2)
        ids = rng.integers(0, vocab, size=num_rows)
        rows[:, i] = ids.astype(np.float32)
        effect = rng.standard_normal(vocab) * 0.5
        logits += effect[ids]

    for h, t_idx in enumerate(schema.all_target_indices):
        # each target head mixes the shared logits with its own projection
        head_logits = logits + noise * rng.standard_normal(num_rows)
        if h > 0 and num_idx:
            w_h = rng.standard_normal(len(num_idx))
            w_h /= max(np.linalg.norm(w_h), 1e-9)
            head_logits = 0.5 * head_logits + 1.5 * (rows[:, num_idx] @ w_h)
        prob = 1.0 / (1.0 + np.exp(-head_logits))
        rows[:, t_idx] = (rng.random(num_rows) < prob).astype(np.float32)
    if schema.weight_index >= 0:
        rows[:, schema.weight_index] = rng.uniform(0.5, 2.0, num_rows).astype(np.float32)
    return rows


def write_files(
    rows: np.ndarray,
    directory: str,
    num_files: int = 4,
    delimiter: str = "|",
    compress: bool = True,
) -> list[str]:
    """Write rows as pipe-delimited gzip part files (the reference's on-disk
    normalized format, ssgd_monitor.py:375-385 + gzip)."""
    os.makedirs(directory, exist_ok=True)
    paths = []
    chunks = np.array_split(rows, num_files)
    for i, chunk in enumerate(chunks):
        name = f"part-{i:05d}" + (".gz" if compress else "")
        path = os.path.join(directory, name)
        lines = "\n".join(
            delimiter.join(_fmt(v) for v in row) for row in chunk)
        data = (lines + "\n").encode()
        if compress:
            with gzip.open(path, "wb") as f:
                f.write(data)
        else:
            with open(path, "wb") as f:
                f.write(data)
        paths.append(path)
    return paths


def _fmt(v: float) -> str:
    # integers (targets, categorical ids) print compactly
    if float(v).is_integer():
        return str(int(v))
    return f"{v:.6g}"
