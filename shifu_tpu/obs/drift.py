"""Serving-side drift engine: live traffic vs the frozen baseline
profile (docs/OBSERVABILITY.md "Drift observatory").

Five observability planes watch *how fast* shifu_tpu serves; this one
watches *whether the model is still right*.  The train loop freezes a
reference profile of the training partition into the export artifact
(``baseline_profile.json`` — obs/sketch.py, export/artifact.py); the
scoring daemon accumulates the same sketches over live traffic; and the
`DriftEngine` here diffs the two on a fixed tick over FAST and SLOW
trailing windows with exactly the fire-once/latch/resolve discipline of
the SLO engine (obs/slo.py):

- **feature_psi** — per-feature Population Stability Index on the
  shared int8 wire grid.  Fires ONE `drift_alert` naming the offending
  features when any feature's PSI is at/above the threshold in BOTH
  windows; latches until the fast window is healthy, then resolves.
- **score_kl** — KL(baseline || live) of the score distribution: the
  model's *output* moving is drift even when no single input feature
  trips PSI.
- **auc_decay** — with the labeled-feedback path on (wire FEEDBACK
  frames -> `ScoringDaemon.feedback`), a trailing-window live AUC vs
  the artifact's training AUC, journaled in every `drift_report` (a
  quality metric, not an alert objective — labels usually arrive too
  sparsely and lagged for burn-rate semantics).

Trailing windows come from cumulative-snapshot subtraction: every
sketch's state is additive, so window = newest snapshot minus the
newest snapshot at/older than the horizon — the same ring mechanics as
SloEngine, carrying histograms instead of counters.

Pure given injected timestamps; numpy-only, no jax import anywhere.
"""

from __future__ import annotations

import collections
import json
import os
import threading
from typing import Optional

import numpy as np

from . import sketch as sketch_mod

BASELINE_FILE = "baseline_profile.json"

OBJ_FEATURE_PSI = "feature_psi"
OBJ_SCORE_KL = "score_kl"

# gauges exported per tick (the scrape-file face of the drift plane)
GAUGE_PSI = "drift_psi"
GAUGE_SCORE = "score_drift"
GAUGE_AUC_DECAY = "auc_decay"


# ----------------------------------------------------- baseline loading


def baseline_digest(path: str) -> Optional[str]:
    """blake2b-16 hex of the baseline file bytes — the same digest
    recipe the artifact sync manifest uses (runtime/fleet.py), so
    `fleet-verify` can check every member served the same profile."""
    import hashlib

    try:
        h = hashlib.blake2b(digest_size=16)
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()
    except OSError:
        return None


def load_baseline(export_dir: str) -> Optional[tuple[dict, str]]:
    """(profile, digest) from ``<export_dir>/baseline_profile.json``,
    or None when the artifact carries no profile (pre-drift exports,
    checkpoint-recovery re-exports) or the file fails validation —
    drift degrades to off, it never blocks serving."""
    path = os.path.join(str(export_dir), BASELINE_FILE)
    if not os.path.isfile(path):
        return None
    try:
        with open(path, "r") as f:
            profile = json.load(f)
        sketch_mod.validate_profile(profile)
    except (OSError, ValueError) as e:
        try:
            from . import _sinks
            _sinks.event("drift_baseline_invalid", path=path,
                         error=str(e)[:200])
        except Exception:
            pass
        return None
    return profile, baseline_digest(path) or ""


def feature_names(profile: dict) -> list[str]:
    """Display names for the profile's features (f<j> fallback)."""
    n = int(profile.get("num_features", 0))
    names = profile.get("feature_names")
    if isinstance(names, list) and len(names) == n:
        return [str(x) for x in names]
    return [f"f{j}" for j in range(n)]


# --------------------------------------------------------- live monitor


class DriftMonitor:
    """Live-traffic sketch accumulation for ONE model version: the
    cumulative feature/score sketches the dispatch path feeds, the
    labeled-feedback accumulator, and the ring of timed cumulative
    snapshots that turns them into trailing windows.

    `observe_batch` is the dispatch-path hook: one flattened bincount
    for all features + one score bincount, under a lock the tick
    thread's `snapshot` briefly shares.  Everything else runs at tick
    cadence."""

    def __init__(self, profile: dict, model_id: str = "default",
                 version: int = 1, digest: str = "",
                 feedback_bins: int = 1024):
        self.profile = profile
        self.model_id = str(model_id)
        self.version = int(version)
        self.digest = digest
        base_feat, base_score = sketch_mod.profile_sketches(profile)
        self.base_features = base_feat
        self.base_score = base_score
        self.names = feature_names(profile)
        self._lock = threading.Lock()
        self.features = sketch_mod.FeatureSketch(
            base_feat.num_features, scale=base_feat.scale,
            offset=base_feat.offset)
        self.score = sketch_mod.ScoreSketch(bins=base_score.bins)
        from ..ops.metrics import StreamingMetrics
        self.feedback = StreamingMetrics(bins=int(feedback_bins))
        # ring of cumulative snapshots: (t, rows, hist, score_hist,
        # fb_pos, fb_neg, fb_rows) — pruned to the slow window + 1 base
        self._samples: collections.deque = collections.deque()

    # -- hot path ------------------------------------------------------

    def observe_batch(self, x: np.ndarray, scores) -> None:
        """Accumulate one dispatched batch (features as admitted — int8
        wire bytes bin without dequantization — plus the head-0 scores).
        Never raises into the dispatch path."""
        try:
            s = np.asarray(scores)
            if s.ndim > 1:
                s = s[:, 0]
            with self._lock:
                self.features.update(x)
                self.score.update(s)
        except Exception:
            pass  # the drift plane must never fail a dispatch

    def observe_feedback(self, scores, labels, weights=None) -> int:
        """Labeled feedback (the FEEDBACK wire frame / client.feedback):
        feeds the trailing-window live-AUC accumulator.  Returns rows
        accepted."""
        s = np.asarray(scores, np.float64).ravel()
        with self._lock:
            self.feedback.update(s, labels, weights)
        return int(s.size)

    # -- windows -------------------------------------------------------

    def snapshot(self, now: float, slow_window_s: float) -> None:
        """Append one cumulative snapshot; prune the ring to the slow
        window plus one base sample (the SloEngine ring discipline)."""
        with self._lock:
            fb = self.feedback.state_arrays()
            self._samples.append((
                float(now), int(self.features.rows),
                self.features.hist.copy(), self.score.hist.copy(),
                fb[0].copy(), fb[1].copy(), int(self.feedback.rows)))
            horizon = float(now) - float(slow_window_s)
            while (len(self._samples) >= 2
                   and self._samples[1][0] <= horizon):
                self._samples.popleft()

    def window(self, now: float, seconds: float) -> Optional[dict]:
        """Sketch deltas over the trailing `seconds` (newest snapshot vs
        the newest snapshot at/older than now - seconds; the oldest held
        sample when none is old enough)."""
        with self._lock:
            if len(self._samples) < 2:
                return None
            cur = self._samples[-1]
            cut = float(now) - float(seconds)
            base = self._samples[0]
            for s in self._samples:
                if s[0] <= cut:
                    base = s
                else:
                    break
            span = cur[0] - base[0]
            if span <= 0:
                return None
            return {
                "span_s": span,
                "rows": cur[1] - base[1],
                "hist": cur[2] - base[2],
                "score_hist": cur[3] - base[3],
                "fb_pos": cur[4] - base[4],
                "fb_neg": cur[5] - base[5],
                "fb_rows": cur[6] - base[6],
            }

    def totals(self) -> dict:
        with self._lock:
            return {"rows": int(self.features.rows),
                    "feedback_rows": int(self.feedback.rows)}


def _auc_from_bins(pos: np.ndarray, neg: np.ndarray) -> Optional[float]:
    """Binned weighted Mann-Whitney AUC from (pos, neg) score-bin
    weights — the StreamingMetrics statistic over a WINDOW delta."""
    wp, wn = float(pos.sum()), float(neg.sum())
    if wp <= 0 or wn <= 0:
        return None
    neg_below = np.concatenate([[0.0], np.cumsum(neg)[:-1]])
    credit = neg_below + 0.5 * neg
    return float(np.sum(pos * credit) / (wp * wn))


# --------------------------------------------------------------- engine


class DriftEngine:
    """Fast/slow-window drift evaluation vs the frozen baseline, with
    the SLO engine's alert discipline: an objective fires ONE
    `drift_alert` when BOTH windows violate, stays latched until the
    fast window is healthy again (one "resolved" per episode), and an
    idle monitor (fast window below min_rows) unlatches rather than
    showing a stale FIRING alert forever.

    `tick(now)` is the whole cadence step: snapshot the monitor, build
    both windows, evaluate, and return (transitioned_alerts, report) —
    the caller (the daemon's drift loop) journals them.  Pure given
    injected timestamps, so drills replay deterministically."""

    def __init__(self, monitor: DriftMonitor, config):
        self.monitor = monitor
        self.cfg = config
        self._lock = threading.Lock()
        self._firing: dict[str, dict] = {}
        self._last: dict = {}        # last computed per-objective values
        self.alerts_fired = 0
        self._last_report_t: Optional[float] = None

    # -- per-objective math --------------------------------------------

    def _psi_pair(self, fast: dict, slow: dict) -> tuple:
        base = self.monitor.base_features.hist
        return (sketch_mod.psi(base, fast["hist"]),
                sketch_mod.psi(base, slow["hist"]))

    def _score_pair(self, fast: dict, slow: dict) -> tuple:
        base = self.monitor.base_score.hist
        return (sketch_mod.kl_divergence(base, fast["score_hist"]),
                sketch_mod.kl_divergence(base, slow["score_hist"]))

    def _window_auc(self, w: dict) -> Optional[float]:
        if w["fb_rows"] < max(int(self.cfg.min_rows), 1):
            return None
        return _auc_from_bins(w["fb_pos"], w["fb_neg"])

    def _base_event(self, fast: dict, slow: dict) -> dict:
        return {
            "model": self.monitor.model_id,
            "version": self.monitor.version,
            "fast_window_s": round(fast["span_s"], 3),
            "slow_window_s": round(slow["span_s"], 3),
            "rows_fast": int(fast["rows"]),
            "rows_slow": int(slow["rows"]),
        }

    # -- evaluation ----------------------------------------------------

    def tick(self, now: float,
             force_report: bool = False) -> tuple[list[dict],
                                                  Optional[dict]]:
        """One cadence step: returns (alert transitions, drift_report
        payload or None when the report interval hasn't elapsed).
        `force_report` emits a report regardless of the interval — the
        end-of-drill flush (`ScoringDaemon.drift_flush`) uses it so
        late-landing labeled feedback reaches a journaled report."""
        self.monitor.snapshot(now, self.cfg.slow_window_s)
        fast = self.monitor.window(now, self.cfg.fast_window_s)
        slow = self.monitor.window(now, self.cfg.slow_window_s)
        alerts = self.evaluate(now, fast, slow)
        report = None
        interval = max(float(self.cfg.fast_window_s), 1.0)
        if force_report or self._last_report_t is None \
                or now - self._last_report_t >= interval:
            report = self.report(fast, slow)
            if report is not None:
                self._last_report_t = now
        return alerts, report

    def evaluate(self, now: float, fast: Optional[dict],
                 slow: Optional[dict]) -> list[dict]:
        """The transitioned `drift_alert` payloads at `now` (firing AND
        resolved) — idempotent between transitions, exactly one firing
        per violation episode."""
        out: list[dict] = []
        with self._lock:
            if fast is None or slow is None:
                return out
            min_rows = max(int(self.cfg.min_rows), 1)
            if fast["rows"] < min_rows:
                # no judgment on a near-empty window — but latched
                # alerts must not outlive the traffic that caused them
                for name in list(self._firing):
                    del self._firing[name]
                    out.append({
                        "objective": name, "state": "resolved",
                        **self._base_event(fast, slow),
                        "note": "window below min_rows — traffic "
                                "stopped"})
                return out
            names = self.monitor.names
            k = max(int(self.cfg.top_k), 1)

            # ---- feature PSI ----
            psi_fast, psi_slow = self._psi_pair(fast, slow)
            psi_fast = np.atleast_1d(psi_fast)
            psi_slow = np.atleast_1d(psi_slow)
            t = float(self.cfg.psi_threshold)
            order = np.argsort(psi_fast)[::-1]
            worst = [{"feature": names[j],
                      "psi_fast": round(float(psi_fast[j]), 4),
                      "psi_slow": round(float(psi_slow[j]), 4)}
                     for j in order[:k]]
            self._last["worst_features"] = worst
            self._last["worst_psi"] = round(float(psi_fast[order[0]]), 4) \
                if len(order) else None
            if t > 0:
                offend = np.flatnonzero((psi_fast >= t) & (psi_slow >= t))
                firing = OBJ_FEATURE_PSI in self._firing
                if offend.size and not firing:
                    offend = offend[np.argsort(psi_fast[offend])[::-1]]
                    ev = {
                        "objective": OBJ_FEATURE_PSI, "state": "firing",
                        **self._base_event(fast, slow),
                        "psi_threshold": t,
                        "features": [
                            {"feature": names[j],
                             "psi_fast": round(float(psi_fast[j]), 4),
                             "psi_slow": round(float(psi_slow[j]), 4)}
                            for j in offend[:k]],
                    }
                    self._firing[OBJ_FEATURE_PSI] = ev
                    self.alerts_fired += 1
                    out.append(ev)
                elif firing and float(psi_fast.max(initial=0.0)) < t:
                    ev = {
                        "objective": OBJ_FEATURE_PSI, "state": "resolved",
                        **self._base_event(fast, slow),
                        "psi_threshold": t,
                        "worst_psi_fast":
                            round(float(psi_fast.max(initial=0.0)), 4),
                    }
                    del self._firing[OBJ_FEATURE_PSI]
                    out.append(ev)

            # ---- score KL ----
            kl_fast, kl_slow = self._score_pair(fast, slow)
            self._last["score_kl"] = round(kl_fast, 4)
            st = float(self.cfg.score_kl_threshold)
            if st > 0:
                firing = OBJ_SCORE_KL in self._firing
                if (not firing and kl_fast >= st and kl_slow >= st):
                    ev = {
                        "objective": OBJ_SCORE_KL, "state": "firing",
                        **self._base_event(fast, slow),
                        "score_kl_threshold": st,
                        "score_kl_fast": round(kl_fast, 4),
                        "score_kl_slow": round(kl_slow, 4),
                    }
                    self._firing[OBJ_SCORE_KL] = ev
                    self.alerts_fired += 1
                    out.append(ev)
                elif firing and kl_fast < st:
                    ev = {
                        "objective": OBJ_SCORE_KL, "state": "resolved",
                        **self._base_event(fast, slow),
                        "score_kl_threshold": st,
                        "score_kl_fast": round(kl_fast, 4),
                    }
                    del self._firing[OBJ_SCORE_KL]
                    out.append(ev)

            # ---- mean shift + live AUC (report axes, not alerts) ----
            base_mean, base_var = self.monitor.base_features.moments()
            live_fast = sketch_mod.FeatureSketch(
                self.monitor.base_features.num_features,
                scale=self.monitor.base_features.scale,
                offset=self.monitor.base_features.offset)
            live_fast.hist = fast["hist"]
            live_fast.rows = fast["rows"]
            live_mean, _ = live_fast.moments()
            shift = sketch_mod.mean_shift_sigmas(base_mean, base_var,
                                                 live_mean)
            jmax = int(np.argmax(shift)) if shift.size else 0
            self._last["mean_shift_max"] = round(float(
                shift.max(initial=0.0)), 4)
            self._last["mean_shift_feature"] = names[jmax] \
                if shift.size else None
            auc_live = self._window_auc(fast)
            self._last["auc_live"] = round(auc_live, 6) \
                if auc_live is not None else None
            base_auc = self.monitor.profile.get("train_auc")
            if auc_live is not None and base_auc is not None:
                self._last["auc_decay"] = round(float(base_auc)
                                                - auc_live, 6)
            else:
                self._last["auc_decay"] = None
        return out

    def report(self, fast: Optional[dict],
               slow: Optional[dict]) -> Optional[dict]:
        """The periodic `drift_report` payload (the last evaluated
        values + window row counts); None before any window exists."""
        if fast is None or slow is None:
            return None
        with self._lock:
            rep = {
                "model": self.monitor.model_id,
                "version": self.monitor.version,
                "baseline_digest": self.monitor.digest,
                "rows_fast": int(fast["rows"]),
                "rows_slow": int(slow["rows"]),
                "feedback_rows_fast": int(fast["fb_rows"]),
                "worst": list(self._last.get("worst_features") or []),
                "worst_psi": self._last.get("worst_psi"),
                "score_kl": self._last.get("score_kl"),
                "mean_shift_max": self._last.get("mean_shift_max"),
                "mean_shift_feature": self._last.get(
                    "mean_shift_feature"),
                "auc_live": self._last.get("auc_live"),
                "auc_decay": self._last.get("auc_decay"),
                "firing": sorted(self._firing),
            }
            if self.monitor.profile.get("train_auc") is not None:
                rep["train_auc"] = self.monitor.profile["train_auc"]
            return rep

    def export_gauges(self) -> None:
        """Scrape-file face: drift_psi{feature,model} for the worst
        features, score_drift and auc_decay per model."""
        from . import metrics as metrics_mod

        with self._lock:
            worst = list(self._last.get("worst_features") or [])
            score_kl = self._last.get("score_kl")
            auc_decay = self._last.get("auc_decay")
        model = self.monitor.model_id
        g = metrics_mod.gauge(GAUGE_PSI, "per-feature PSI of live "
                              "traffic vs the frozen baseline profile "
                              "(fast window)")
        for w in worst:
            g.set(w["psi_fast"], feature=w["feature"], model=model)
        if score_kl is not None:
            metrics_mod.gauge(GAUGE_SCORE, "KL(baseline || live) of "
                              "the score distribution").set(
                score_kl, model=model)
        if auc_decay is not None:
            metrics_mod.gauge(GAUGE_AUC_DECAY, "training AUC minus "
                              "trailing-window live AUC from labeled "
                              "feedback").set(auc_decay, model=model)

    def state(self) -> dict:
        """Operator snapshot (`stats()["drift"]` / the `top` drift
        row)."""
        with self._lock:
            totals = self.monitor.totals()
            return {
                "model": self.monitor.model_id,
                "version": self.monitor.version,
                "baseline_digest": self.monitor.digest,
                "baseline_rows": int(self.monitor.profile.get("rows", 0)),
                "rows": totals["rows"],
                "feedback_rows": totals["feedback_rows"],
                "worst_psi": self._last.get("worst_psi"),
                "worst_feature": (self._last.get("worst_features")
                                  or [{}])[0].get("feature"),
                "score_kl": self._last.get("score_kl"),
                "auc_live": self._last.get("auc_live"),
                "auc_decay": self._last.get("auc_decay"),
                "firing": sorted(self._firing),
                "alerts_fired": self.alerts_fired,
            }
