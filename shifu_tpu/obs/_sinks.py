"""Process-global telemetry sinks: where the registry and journal land.

One journal + one scrape file per process, configured once (launcher CLI,
supervisor, bench, or lazily from SHIFU_TPU_METRICS_DIR).  Call sites
everywhere else stay sink-agnostic: `obs.event(...)` no-ops until a journal
is configured, and the default registry always collects in memory.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from . import journal as journal_mod
from . import metrics as metrics_mod

ENV_METRICS_DIR = "SHIFU_TPU_METRICS_DIR"
SCRAPE_FILE = "metrics.prom"

_lock = threading.RLock()
_journal: Optional[journal_mod.RunJournal] = None
_scrape_path: Optional[str] = None
_metrics_dir: Optional[str] = None


def _join(base: str, name: str) -> str:
    try:
        from ..data import fsio
        return fsio.join(base, name)
    except Exception:
        return os.path.join(base, name)


def resolve_metrics_dir(explicit: Optional[str] = None) -> Optional[str]:
    """Explicit argument wins; else SHIFU_TPU_METRICS_DIR; else None."""
    return explicit or os.environ.get(ENV_METRICS_DIR) or None


def configure(metrics_dir: str, scrape: bool = True,
              flush_every: int = 16,
              journal_name: str = journal_mod.JOURNAL_FILE
              ) -> journal_mod.RunJournal:
    """Point this process's telemetry at `metrics_dir` (local or remote):
    journal at <dir>/<journal_name>, scrape file at <dir>/metrics.prom
    (unless `scrape=False` — e.g. the supervisor parent journals restarts
    but must not overwrite its child's scrape file).  `journal_name` lets a
    SECOND writer on a REMOTE dir use its own object (remote journals are
    whole-object rewrites of the writer's OWN lines — two writers on one
    object would erase each other; obs/render.py merges the sidecar).
    Reconfiguring closes the previous journal."""
    global _journal, _scrape_path, _metrics_dir
    with _lock:
        if _journal is not None:
            _journal.close()
        _journal = journal_mod.RunJournal(
            _join(metrics_dir, journal_name), flush_every=flush_every)
        _scrape_path = _join(metrics_dir, SCRAPE_FILE) if scrape else None
        _metrics_dir = metrics_dir
        return _journal


def set_journal(journal: Optional[journal_mod.RunJournal]) -> None:
    """Install a journal object directly (bench: in-memory journal)."""
    global _journal
    with _lock:
        _journal = journal


def configure_from_env() -> bool:
    """Configure sinks from SHIFU_TPU_METRICS_DIR, if set and nothing is
    configured yet.  Returns True when a journal is active after the call —
    the lazy hook library entry points (train()) use so a bare env var is
    enough to get telemetry without touching the CLI."""
    with _lock:
        if _journal is not None:
            return True
        d = os.environ.get(ENV_METRICS_DIR)
        if not d:
            return False
        try:
            configure(d)
            return True
        except Exception:
            return False


def get_journal() -> Optional[journal_mod.RunJournal]:
    return _journal


def metrics_dir() -> Optional[str]:
    """The directory the sinks were configured at (None until then) —
    siblings like the device-trace dir (obs/devprof.py) anchor here."""
    return _metrics_dir


def event(kind: str, **fields) -> Optional[dict]:
    """Journal one event; no-op (returns None) when no journal is
    configured.  Never raises — telemetry must not fail the caller."""
    j = _journal
    if j is None:
        return None
    try:
        return j.event(kind, **fields)
    except Exception:
        return None


def flush() -> None:
    """Flush the journal and (re)write the Prometheus scrape file."""
    with _lock:
        if _journal is not None:
            _journal.flush()
        if _scrape_path is not None:
            metrics_mod.write_scrape_file(_scrape_path)


def shutdown() -> None:
    """flush + close the journal (job end)."""
    global _journal
    with _lock:
        flush()
        if _journal is not None:
            _journal.close()
            _journal = None


def reset_for_tests() -> None:
    """Tear down all global telemetry state (tests only)."""
    global _journal, _scrape_path, _metrics_dir
    with _lock:
        if _journal is not None:
            try:
                _journal.close()
            except Exception:
                pass
        _journal = None
        _scrape_path = None
        _metrics_dir = None
        metrics_mod.default_registry().clear()
        from . import goodput, introspect
        introspect.reset_for_tests()
        goodput.reset_for_tests()
