"""Device flight recorder: per-kernel device-time attribution, HBM
watermarks, and anomaly-triggered trace capture.

The goodput ledger (obs/goodput.py) says how much of an epoch's wall was
`step`; this module opens that bucket: WHICH kernels own the device time,
whether each is compute- or HBM-bound, how close HBM sits to its limit,
and — when a chunk suddenly runs slow — a trace of the very next chunk so
the anomaly is attributable after the fact.  Four legs:

- **Windowed trace capture** — `DeviceProfiler.epoch_capture(epoch)`
  wraps the train loop's `jax.profiler` seam (train/profiler.trace) on
  the `obs.trace_epochs` schedule (default off; "first" = the first
  trained epoch only); the emitted Chrome-trace files parse into a
  per-kernel rollup (obs/tracefmt.py) journaled as a `device_profile`
  event.  The capture is chaos-probed (site `obs.trace`): a failing or
  hanging profiler degrades to a journaled `trace_fallback` and the
  epoch trains on untraced.
- **Roofline attribution** — the rollup joins obs/introspect.py's
  cost-analysis FLOPs/bytes (matched per hlo_module) against the
  platform peaks (`goodput.PEAK_BF16_TFLOPS`, `PEAK_HBM_GBPS` below):
  each matched kernel carries its program's achieved-vs-peak FLOP/s and
  HBM-bandwidth fractions and a `bound` verdict (compute vs hbm).
- **HBM watermarks** — `hbm_snapshot()` polls
  `device.memory_stats()` at epoch boundaries into `hbm_bytes_in_use` /
  `hbm_peak_bytes` gauges and an `hbm_watermark` journal event;
  backends without live stats (CPU) fall back to the XLA
  memory-analysis peak of the instrumented programs (`source:
  "xla_estimate"`), so the event exists on every backend.
- **Flight recorder + anomaly trigger** — `FlightRecorder` keeps a ring
  of the last K per-chunk (input_s, step_s) timings (fed by
  train/profiler.StepTimer's chunk hook) and runs a rolling robust
  z-score (median/MAD) on the step time.  An anomalous chunk journals
  an `anomaly` event carrying the ring, and — when the trace plane is
  enabled — fires a ONE-SHOT trace capture of the next chunk, journaled
  as a `device_profile` with `trigger: "anomaly"`.

Always-on cost: the ring is an O(K) deque touched once per chunk (K
defaults to 32, chunks are ~32 MB of wire) — well under the <=2%-of-epoch
budget the acceptance criteria pin; everything expensive (profiler,
parse, journal) runs only on scheduled/triggered epochs.
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
import time
from typing import Callable, Iterator, Optional

from . import tracefmt

# peak HBM GB/s per chip by device-kind substring (public specs) — the
# roofline's bandwidth axis, next to goodput.PEAK_BF16_TFLOPS (same
# first-match-wins convention: "v5p" before "v5").
PEAK_HBM_GBPS: tuple[tuple[str, float], ...] = (
    ("v6", 1640.0),      # Trillium / v6e
    ("v5p", 2765.0),
    ("v5", 819.0),       # v5e
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
)

ENV_PEAK_HBM_GBPS = "SHIFU_TPU_PEAK_HBM_GBPS"

# hlo_module -> instrumented-fn aliases the suffix match can't reach (the
# module name comes from the INNER function jit wrapped, the stats key
# from instrument_jit's explicit name; train/step.py's three scan tiers
# all wrap an inner fn literally named `epoch_step`)
_MODULE_ALIASES = {
    "score": ("eval_step", "jax_scorer"),
    "step": ("train_step",),
    "epoch_step": ("epoch_scan_step", "device_epoch_step",
                   "local_sgd_epoch_step"),
}

CHAOS_SITE = "obs.trace"


def peak_hbm_gbps(device_kind: Optional[str] = None) -> Optional[float]:
    """Peak HBM GB/s for a device kind (current backend's device 0 when
    omitted); SHIFU_TPU_PEAK_HBM_GBPS overrides; None when unknown (CPU,
    new parts) — roofline fractions are then null, never guessed."""
    env = os.environ.get(ENV_PEAK_HBM_GBPS)
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:
            return None
    kind = str(device_kind).lower()
    for sub, peak in PEAK_HBM_GBPS:
        if sub in kind:
            return peak
    return None


# the one definition of "tracing off" — parse_trace_epochs and
# DeviceProfiler.tracing_enabled must never disagree on it
_OFF_TOKENS = ("", "off", "0", "false", "none")


def trace_spec_off(spec: str) -> bool:
    return (spec or "").strip().lower() in _OFF_TOKENS


def parse_trace_epochs(spec: str) -> Callable[[int, int], bool]:
    """`obs.trace_epochs` -> predicate(epoch, start_epoch).

    Forms: "off"/"" (never), "first"/"on" (the first trained epoch only),
    "every:N" (every Nth epoch), or a comma list of epoch numbers
    ("0,2,5").  Malformed specs raise ValueError at config time
    (JobConfig.validate), never mid-run.
    """
    s = (spec or "").strip().lower()
    if trace_spec_off(s):
        return lambda epoch, start: False
    if s in ("first", "on", "true"):
        return lambda epoch, start: epoch == start
    if s.startswith("every:"):
        n = int(s.split(":", 1)[1])
        if n <= 0:
            raise ValueError(f"obs.trace_epochs every:N needs N > 0: {spec!r}")
        return lambda epoch, start, n=n: epoch % n == 0
    try:
        epochs = frozenset(int(tok) for tok in s.split(",") if tok.strip())
    except ValueError:
        raise ValueError(
            f"obs.trace_epochs must be off/first/every:N/or a comma list "
            f"of epoch numbers: {spec!r}")
    return lambda epoch, start, es=epochs: epoch in es


def resolve_trace_dir(explicit: str = "") -> Optional[str]:
    """Where trace windows land: `obs.trace_dir` when set, else a
    `trace/` dir beside this process's telemetry sinks (local dirs only —
    jax.profiler writes real files), else None (capture disabled)."""
    if explicit:
        return explicit
    from . import _sinks
    base = _sinks.metrics_dir()
    if not base:
        return None
    try:
        from ..data import fsio
        if fsio.is_remote(base):
            return None
    except Exception:
        pass
    return os.path.join(base, "trace")


# ---------------------------------------------------------------- roofline


def _match_stats(module: Optional[str],
                 stats: dict) -> Optional[tuple[str, dict]]:
    """(stats key, entry) for one hlo_module.  jit names modules after
    the INNER function (`jit_epoch_step`), instrument_jit keys stats by
    its explicit name (`epoch_scan_step`) — resolved exact-name first,
    then the alias table (train/step.py's inner fns are shared across
    tiers), then suffix both ways; within a rank the largest-FLOPs
    candidate wins (in one run usually a single tier is live)."""
    if not module:
        return None
    name = module[4:] if module.startswith("jit_") else module
    name = name.strip("_")
    if not name:
        return None
    cands = []  # (rank, -flops) minimized: exact < alias < suffix
    for key, st in stats.items():
        if key == name:
            rank = 0
        elif key in _MODULE_ALIASES.get(name, ()):
            rank = 1
        elif key.endswith(name) or name.endswith(key):
            rank = 2
        else:
            continue
        cands.append(((rank, -(st.get("flops") or 0.0)), key, st))
    if not cands:
        return None
    _prio, key, st = min(cands)
    return key, st


def roofline_join(rollup: dict, stats: Optional[dict] = None,
                  dispatches: Optional[dict] = None) -> dict:
    """Annotate a tracefmt rollup with roofline attribution (in place,
    returned for chaining).

    Per-DISPATCH FLOPs/bytes come from the instrumented programs'
    cost_analysis (obs/introspect.stats()); the achieved rate scales
    them by `dispatches` — the per-fn dispatch counts executed INSIDE
    the traced window (DeviceProfiler snapshots
    introspect.dispatch_counts() around each capture; a window holding
    1000 step dispatches must not read as 1000x under-utilized).  When
    `dispatches` is omitted the window is assumed to hold ONE dispatch
    per module (bench-style micro-windows).  The module's device-time
    denominator is the rollup's pre-truncation `modules` total, so
    tail kernels folded into other_us still count.

    A kernel inherits its module's achieved-vs-peak fractions (module
    cost spread over the module's device time — per-kernel FLOP counts
    don't exist outside the compiler, so this is time-proportional
    attribution, stated as such).  `bound` is the limiting resource:
    "compute" when the FLOP/s fraction >= the bandwidth fraction,
    "hbm" otherwise; null when the platform peaks, the module cost, or
    the window's dispatch count are unknown (CPU tests: bytes are
    known, peaks are not — intensity still journals).
    """
    if stats is None:
        from . import introspect
        stats = introspect.stats()
    peak_tf = None
    try:
        from . import goodput
        peak_tf = goodput.peak_tflops()
    except Exception:
        pass
    peak_bw = peak_hbm_gbps()
    rollup["peak_tflops"] = peak_tf
    rollup["peak_hbm_gbps"] = peak_bw
    # module device time: pre-truncation totals when the rollup carries
    # them (tracefmt >= this PR), else the kept kernels as the fallback
    mod_us: dict[str, float] = dict(rollup.get("modules") or {})
    if not mod_us:
        for k in rollup.get("kernels") or []:
            if k.get("module"):
                mod_us[k["module"]] = mod_us.get(k["module"], 0.0) \
                    + float(k["device_us"])
    mod_info: dict[str, dict] = {}
    for module, us in mod_us.items():
        matched = _match_stats(module, stats)
        if not matched or us <= 0:
            continue
        key, st = matched
        n_disp = 1 if dispatches is None else dispatches.get(key)
        flops = st.get("flops")
        bytes_acc = st.get("bytes_accessed")
        info: dict = {}
        if flops and bytes_acc:
            info["intensity_flops_per_byte"] = round(flops / bytes_acc, 4)
        sec = float(us) * 1e-6
        if n_disp and n_disp > 0:
            info["window_dispatches"] = int(n_disp)
            if flops and peak_tf:
                info["flops_frac"] = round(
                    flops * n_disp / sec / 1e12 / peak_tf, 6)
            if bytes_acc and peak_bw:
                info["hbm_frac"] = round(
                    bytes_acc * n_disp / sec / 1e9 / peak_bw, 6)
        if "flops_frac" in info and "hbm_frac" in info:
            info["bound"] = ("compute"
                             if info["flops_frac"] >= info["hbm_frac"]
                             else "hbm")
        if info:
            mod_info[module] = info
    for k in rollup.get("kernels") or []:
        info = mod_info.get(k.get("module") or "")
        if info:
            k.update(info)
        k.setdefault("bound", None)  # explicit null: "not classified"
    return rollup


# -------------------------------------------------------------- watermarks


def hbm_snapshot() -> dict:
    """Per-device HBM occupancy right now.

    {"source": "memory_stats", "devices": [...], "bytes_in_use",
    "peak_bytes", "bytes_limit"} from `device.memory_stats()` where the
    backend exposes it; falls back to the XLA memory-analysis peak of the
    instrumented programs ({"source": "xla_estimate"}) so CPU runs (and
    tests) still get a watermark.  Never raises.
    """
    devices = []
    try:
        import jax
        for d in jax.local_devices():
            try:
                st = d.memory_stats()
            except Exception:
                st = None
            if not st:
                continue
            devices.append({
                "id": int(getattr(d, "id", len(devices))),
                "kind": str(getattr(d, "device_kind", "?")),
                "bytes_in_use": int(st.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(st.get("peak_bytes_in_use",
                                                st.get("bytes_in_use", 0))),
                "bytes_limit": int(st.get("bytes_limit", 0)),
            })
    except Exception:
        pass
    if devices:
        return {
            "source": "memory_stats",
            "devices": devices[:16],
            "device_count": len(devices),
            "bytes_in_use": sum(d["bytes_in_use"] for d in devices),
            "peak_bytes": max(d["peak_bytes_in_use"] for d in devices),
            "bytes_limit": sum(d["bytes_limit"] for d in devices),
        }
    # CPU / backends without allocator stats: the instrumented programs'
    # memory_analysis peak is the best standing estimate of device-memory
    # high water (docs/OBSERVABILITY.md)
    peak = 0
    try:
        from . import introspect
        for st in introspect.stats().values():
            peak = max(peak, int(st.get("peak_bytes") or 0))
    except Exception:
        pass
    return {"source": "xla_estimate", "devices": [], "device_count": 0,
            "bytes_in_use": 0, "peak_bytes": peak, "bytes_limit": 0}


def journal_watermark(epoch: int) -> Optional[dict]:
    """One `hbm_watermark` event + the gauges, at an epoch boundary.
    Never raises (telemetry must not fail the epoch it measures)."""
    try:
        from . import _sinks, metrics as metrics_mod
        snap = hbm_snapshot()
        snap["epoch"] = int(epoch)
        in_use = metrics_mod.gauge(
            "hbm_bytes_in_use", "device memory in use at the last epoch "
            "boundary (memory_stats; xla_estimate on backends without it)")
        peak = metrics_mod.gauge(
            "hbm_peak_bytes", "device-memory high water observed so far")
        if snap["devices"]:
            for d in snap["devices"]:
                in_use.set(d["bytes_in_use"], device=str(d["id"]))
                peak.set(d["peak_bytes_in_use"], device=str(d["id"]))
        else:
            in_use.set(snap["bytes_in_use"], device="est")
            peak.set(snap["peak_bytes"], device="est")
        _sinks.event("hbm_watermark", **snap)
        return snap
    except Exception:
        return None


# --------------------------------------------------------- flight recorder


class FlightRecorder:
    """Ring buffer of the last K per-chunk timings + a rolling robust
    z-score anomaly detector on the device step time.

    A chunk is anomalous when, against the ring of PRIOR chunks (at least
    `min_chunks` of them), its step time is BOTH a `zscore`-sigma outlier
    under the median/MAD robust scale AND at least `min_ratio` slower
    than the median — the second guard keeps near-constant (MAD ~ 0)
    quiet series from flagging scheduler jitter.  One-sided on purpose:
    a suspiciously FAST chunk is a bug for a correctness tool, not a
    stall for this one.
    """

    def __init__(self, window: int = 32, zscore: float = 6.0,
                 min_chunks: int = 8, min_ratio: float = 0.5) -> None:
        self.window = max(int(window), 4)
        self.zscore = float(zscore)
        self.min_chunks = max(int(min_chunks), 2)
        self.min_ratio = float(min_ratio)
        self.ring: collections.deque = collections.deque(maxlen=self.window)
        self.anomalies = 0
        self._chunk = 0

    def record(self, epoch: int, input_s: float, step_s: float
               ) -> Optional[dict]:
        """Feed one chunk; returns the anomaly record (also journaled by
        the caller) when this chunk trips the detector, else None."""
        self._chunk += 1
        verdict = None
        if (step_s == step_s and step_s != float("inf")
                and len(self.ring) >= self.min_chunks):
            steps = sorted(r["step_s"] for r in self.ring)
            n = len(steps)
            med = (steps[n // 2] if n % 2
                   else 0.5 * (steps[n // 2 - 1] + steps[n // 2]))
            mad = sorted(abs(s - med) for s in steps)[n // 2]
            scale = 1.4826 * mad + 1e-12
            z = (step_s - med) / scale
            if z > self.zscore and step_s > med * (1.0 + self.min_ratio):
                self.anomalies += 1
                verdict = {
                    "epoch": int(epoch),
                    "chunk": self._chunk,
                    "step_s": round(step_s, 6),
                    "median_s": round(med, 6),
                    "mad_s": round(mad, 6),
                    "zscore": round(min(z, 1e6), 2),
                    "window": self.window,
                    "ring": [dict(r) for r in self.ring],
                }
        self.ring.append({"epoch": int(epoch), "chunk": self._chunk,
                          "input_s": round(float(input_s), 6),
                          "step_s": round(float(step_s), 6)})
        return verdict


# ---------------------------------------------------------- the profiler


class DeviceProfiler:
    """The train loop's device-profiling plane: epoch-scheduled trace
    windows, the always-on flight recorder with its one-shot anomaly
    trace, and epoch-boundary HBM watermarks.  Every leg is best-effort:
    a broken profiler (or an injected `obs.trace` fault) journals a
    `trace_fallback` and training continues."""

    def __init__(self, cfg, start_epoch: int = 0,
                 enabled: bool = True) -> None:
        self.cfg = cfg
        self.start_epoch = int(start_epoch)
        self.enabled = bool(enabled)
        self.trace_dir = resolve_trace_dir(cfg.trace_dir) if enabled else None
        self._sched = parse_trace_epochs(cfg.trace_epochs)
        self.tracing_enabled = (bool(self.trace_dir)
                                and not trace_spec_off(cfg.trace_epochs))
        self.recorder = FlightRecorder(
            window=cfg.anomaly_window, zscore=cfg.anomaly_zscore,
            min_chunks=cfg.anomaly_min_chunks,
            min_ratio=cfg.anomaly_min_ratio)
        self._lock = threading.Lock()
        self._trace_active = False   # jax.profiler allows ONE trace
        self._oneshot: Optional[dict] = None
        # introspect dispatch tallies at the active capture's start: the
        # delta at stop scales per-dispatch cost to the window's work
        self._disp0: dict = {}

    # -- capture plumbing ---------------------------------------------

    def _start_trace(self, log_dir: str, epoch: int) -> bool:
        """chaos-probed jax.profiler.start_trace; False (journaled
        trace_fallback) on any failure."""
        from .. import chaos
        from . import _sinks, metrics as metrics_mod
        try:
            chaos.maybe_fail(CHAOS_SITE, epoch=epoch, path=log_dir)
            import jax
            os.makedirs(log_dir, exist_ok=True)
            try:
                from . import introspect
                self._disp0 = introspect.dispatch_counts()
            except Exception:
                self._disp0 = {}
            jax.profiler.start_trace(log_dir)
            self._trace_active = True
            return True
        except Exception as e:
            _sinks.event("trace_fallback", epoch=int(epoch), stage="start",
                         error=str(e)[:200])
            metrics_mod.counter(
                "trace_fallback_total",
                "trace captures degraded to untraced epochs").inc(
                    stage="start")
            return False

    def _stop_and_journal(self, log_dir: str, epoch: int, trigger: str,
                          window_s: Optional[float] = None) -> Optional[dict]:
        from . import _sinks, metrics as metrics_mod
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:
            _sinks.event("trace_fallback", epoch=int(epoch), stage="stop",
                         error=str(e)[:200])
            metrics_mod.counter("trace_fallback_total", "").inc(stage="stop")
            self._trace_active = False
            return None
        self._trace_active = False
        try:
            rollup = tracefmt.rollup_trace_dir(log_dir,
                                               top_k=self.cfg.trace_top_k)
        except Exception as e:
            rollup = None
            _sinks.event("trace_fallback", epoch=int(epoch), stage="parse",
                         error=str(e)[:200])
            metrics_mod.counter("trace_fallback_total", "").inc(stage="parse")
        if rollup is None:
            return None
        delta = None
        try:
            from . import introspect
            now = introspect.dispatch_counts()
            delta = {k: n - self._disp0.get(k, 0) for k, n in now.items()
                     if n - self._disp0.get(k, 0) > 0}
        except Exception:
            delta = None
        roofline_join(rollup, dispatches=delta or None)
        rollup.update(epoch=int(epoch), trigger=trigger, trace_dir=log_dir)
        if window_s is not None and window_s > 0:
            # device time as a fraction of the WALL the capture spanned
            # (the trace window above is device-event span only)
            rollup["capture_wall_s"] = round(window_s, 6)
        _sinks.event("device_profile", **rollup)
        metrics_mod.counter(
            "device_profiles_total",
            "device trace captures rolled up and journaled").inc(
                trigger=trigger)
        if rollup.get("device_fraction") is not None:
            metrics_mod.gauge(
                "device_trace_fraction",
                "device-busy fraction of the last traced window").set(
                    rollup["device_fraction"])
        return rollup

    def _fresh_capture_dir(self, base: str) -> str:
        """A capture dir that holds ONLY this capture: a resumed job (or
        a re-traced epoch) would otherwise re-enter the same dir and
        rollup_trace_dir would merge the stale run's events — window_us
        then spans the wall between the two processes and every
        fraction collapses toward 0."""
        if not os.path.exists(base):
            return base
        for n in range(1, 1000):
            cand = f"{base}-r{n}"
            if not os.path.exists(cand):
                return cand
        return base  # pathological; the merge is the lesser evil

    def note_superseded(self, epoch: int) -> None:
        """The legacy SHIFU_TPU_PROFILE_DIR dump owns this epoch's
        capture (the two can't nest): when the schedule would have fired,
        say so in the journal instead of silently producing nothing."""
        if (self.enabled and self.tracing_enabled
                and self._sched(epoch, self.start_epoch)):
            from . import _sinks
            _sinks.event(
                "trace_fallback", epoch=int(epoch), stage="superseded",
                error="SHIFU_TPU_PROFILE_DIR owns this epoch's capture "
                      "(raw TensorBoard dump; no device_profile rollup)")

    @contextlib.contextmanager
    def epoch_capture(self, epoch: int) -> Iterator[None]:
        """Trace the whole epoch when `obs.trace_epochs` schedules it;
        a plain no-op context otherwise."""
        if (not self.enabled or not self.tracing_enabled
                or self._trace_active
                or not self._sched(epoch, self.start_epoch)):
            yield
            return
        log_dir = self._fresh_capture_dir(
            os.path.join(self.trace_dir, f"epoch{epoch:05d}"))
        t0 = time.perf_counter()
        if not self._start_trace(log_dir, epoch):
            yield
            return
        try:
            yield
        finally:
            self._stop_and_journal(log_dir, epoch, "schedule",
                                   window_s=time.perf_counter() - t0)

    # -- flight recorder ----------------------------------------------

    def chunk_hook(self, epoch: int) -> Optional[Callable[[float, float],
                                                          None]]:
        """The per-chunk callback train/profiler.StepTimer feeds (input_s,
        step_s) into; None when the profiler is disabled (timer then pays
        nothing)."""
        if not self.enabled:
            return None

        def hook(input_s: float, step_s: float) -> None:
            try:
                self.note_chunk(epoch, input_s, step_s)
            except Exception:
                pass  # the recorder must never fail the chunk it times

        return hook

    def note_chunk(self, epoch: int, input_s: float, step_s: float) -> None:
        with self._lock:
            # a one-shot armed by the PREVIOUS chunk's anomaly has now
            # traced this chunk: close and journal it first
            if self._oneshot is not None:
                shot, self._oneshot = self._oneshot, None
                self._stop_and_journal(shot["dir"], shot["epoch"], "anomaly")
            verdict = self.recorder.record(epoch, input_s, step_s)
            if verdict is None:
                return
            from . import _sinks, metrics as metrics_mod
            _sinks.event("anomaly", **verdict)
            metrics_mod.counter(
                "anomaly_total",
                "flight-recorder step-time anomalies detected").inc()
            if self.tracing_enabled and not self._trace_active:
                # one-shot capture of the NEXT chunk (the stall's
                # neighborhood): closed at the next note_chunk/end_epoch
                log_dir = self._fresh_capture_dir(os.path.join(
                    self.trace_dir,
                    f"anomaly-e{epoch:05d}-c{verdict['chunk']:06d}"))
                if self._start_trace(log_dir, epoch):
                    self._oneshot = {"dir": log_dir, "epoch": int(epoch)}

    # -- epoch boundary -----------------------------------------------

    def end_epoch(self, epoch: int) -> None:
        """Close a dangling one-shot (anomaly on the epoch's last chunk)
        and journal the HBM watermark."""
        if not self.enabled:
            return
        with self._lock:
            if self._oneshot is not None:
                shot, self._oneshot = self._oneshot, None
                self._stop_and_journal(shot["dir"], shot["epoch"], "anomaly")
        if self.cfg.hbm_watermarks:
            journal_watermark(epoch)

    def close(self) -> None:
        """However the loop exits: never leave jax.profiler tracing."""
        with self._lock:
            if self._oneshot is not None:
                shot, self._oneshot = self._oneshot, None
                self._stop_and_journal(shot["dir"], shot["epoch"], "anomaly")
            elif self._trace_active:
                try:
                    import jax
                    jax.profiler.stop_trace()
                except Exception:
                    pass
                self._trace_active = False
