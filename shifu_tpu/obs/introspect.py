"""XLA cost introspection: what every compiled program costs, journaled.

The jit entry points the hot paths build (train/step.py's step/scan/eval
programs, the export scorer's forward) route through `instrument_jit`
instead of bare `jax.jit`.  The wrapper is transparent at call time (one
`_cache_size()` probe per dispatch); when a call triggers a compile it:

- journals an `xla_compile` event — function name, compile wall
  (`compile_s`: the compiling call's wall, i.e. trace + XLA compile +
  first dispatch), per-program `cost_analysis()` (FLOPs, bytes
  accessed) and `memory_analysis()` (argument/output/temp/code bytes,
  derived peak), and the persistent-cache verdict from
  utils/compilecache.py (`cache`: off / miss / hit);
- feeds the registry: `xla_compiles_total{fn}`,
  `xla_compile_seconds`, `xla_flops{fn}` / `xla_bytes_accessed{fn}` /
  `xla_peak_bytes{fn}` gauges;
- credits the compile wall to the active goodput ledger's `compile`
  bucket (obs/goodput.py), so a recompile-heavy epoch shows up as lost
  goodput, not as a mysteriously slow "step".

Per-dispatch FLOPs (the MFU numerator) accumulate onto the ledger via
`goodput.note_flops` on EVERY call whose signature has a captured cost —
a lax.scan epoch program's cost_analysis covers all its batches, so one
dispatch credits the whole chunk.

Cost capture itself runs the AOT path (`fn.lower(avals).compile()`),
which pays a SECOND compile of the program.  That is nearly free on CPU
(tier-1, tests) but real money on TPU — and the tunneled TPU backend's
cost_analysis additionally under-reports FLOPs ~40x (bench.py module
docstring), so capture defaults to CPU-only.  `SHIFU_TPU_XLA_COST=1`
forces it everywhere (accepting the recompile; the persistent cache
usually absorbs it), `=0` disables even on CPU.  The `xla_compile`
event itself is always journaled — capture gates only the cost/memory
fields.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Callable, Iterator, Optional

ENV_COST = "SHIFU_TPU_XLA_COST"

_lock = threading.Lock()
# fn name -> {"compiles": n, "compile_s": total, "flops": last,
#             "bytes_accessed": last, "peak_bytes": last}
_stats: dict[str, dict] = {}


def capture_enabled() -> bool:
    """Whether cost/memory capture (the second AOT compile) is on."""
    mode = os.environ.get(ENV_COST, "auto").lower()
    if mode in ("1", "on", "true", "force"):
        return True
    if mode in ("0", "off", "false"):
        return False
    try:  # auto: CPU backends only (see module docstring)
        import jax
        return jax.default_backend() == "cpu"
    except Exception:
        return False


def stats() -> dict[str, dict]:
    """Per-function compile/cost stats captured so far this process."""
    with _lock:
        return {k: dict(v) for k, v in _stats.items()}


def dispatch_counts() -> dict[str, int]:
    """Per-function dispatch tallies (every call, compiling or cached).
    The device flight recorder snapshots this around a trace window to
    scale per-dispatch cost_analysis numbers to the work the window
    actually executed (obs/devprof.roofline_join)."""
    with _lock:
        return {k: int(v.get("dispatches", 0)) for k, v in _stats.items()}


def _aval(x):
    """Shape/dtype/sharding abstraction of a pytree leaf — enough to
    re-lower without touching buffers (donated args stay untouched).

    Only mesh placements (NamedSharding) ride into the aval: the real
    dispatch may freely move an uncommitted single-device array (a bare
    jnp.arange riding next to mesh-placed state), but an aval's explicit
    SingleDeviceSharding would make the AOT lowering reject the mix as
    "incompatible devices"."""
    import jax
    from jax.sharding import NamedSharding

    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        return x  # static / python leaf: pass through
    sharding = getattr(x, "sharding", None)
    if isinstance(sharding, NamedSharding):
        try:
            return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)
        except TypeError:
            pass
    return jax.ShapeDtypeStruct(shape, dtype)


def _signature(args, kwargs) -> tuple:
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (treedef,
            tuple((getattr(l, "shape", None), str(getattr(l, "dtype", type(l))))
                  for l in leaves))


def _normalize_cost(ca) -> dict:
    """cost_analysis() returns a dict on some backends, a 1-list of
    dicts on others; empty when unavailable."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return ca if isinstance(ca, dict) else {}


def _analyze(fn, args, kwargs) -> dict:
    """AOT cost/memory analysis for one signature (the second compile —
    gated by capture_enabled at the call site)."""
    import jax

    avals_args, avals_kwargs = jax.tree_util.tree_map(_aval, (args, kwargs))
    compiled = fn.lower(*avals_args, **avals_kwargs).compile()
    out: dict = {}
    try:
        cost = _normalize_cost(compiled.cost_analysis())
        if "flops" in cost:
            out["flops"] = float(cost["flops"])
        if "bytes accessed" in cost:
            out["bytes_accessed"] = float(cost["bytes accessed"])
    except Exception:
        pass
    try:
        mem = compiled.memory_analysis()
        arg_b = int(getattr(mem, "argument_size_in_bytes", 0))
        out_b = int(getattr(mem, "output_size_in_bytes", 0))
        tmp_b = int(getattr(mem, "temp_size_in_bytes", 0))
        alias_b = int(getattr(mem, "alias_size_in_bytes", 0))
        out.update(argument_bytes=arg_b, output_bytes=out_b,
                   temp_bytes=tmp_b,
                   generated_code_bytes=int(getattr(
                       mem, "generated_code_size_in_bytes", 0)),
                   # the program's device-memory high water: live args +
                   # outputs + XLA temporaries, donated aliases counted once
                   peak_bytes=max(arg_b + out_b + tmp_b - alias_b, 0))
    except Exception:
        pass
    return out


def _record_compile(name: str, fn, args, kwargs, wall_s: float,
                    capture: Optional[bool] = None) -> dict:
    """Journal + registry + goodput for one observed compile; returns
    the captured analysis (possibly empty).  Never raises."""
    from ..utils import compilecache
    from . import _sinks, goodput, metrics as metrics_mod

    analysis: dict = {}
    try:
        if capture_enabled() if capture is None else capture:
            analysis = _analyze(fn, args, kwargs)
    except Exception:
        analysis = {}
    try:
        cache = compilecache.observe_compile()
    except Exception:
        cache = "off"
    try:
        with _lock:
            st = _stats.setdefault(name, {"compiles": 0, "compile_s": 0.0})
            st["compiles"] += 1
            st["compile_s"] = round(st["compile_s"] + wall_s, 6)
            st.update({k: analysis[k] for k in
                       ("flops", "bytes_accessed", "peak_bytes")
                       if k in analysis})
        metrics_mod.counter(
            "xla_compiles_total",
            "XLA compiles observed per instrumented function").inc(fn=name)
        metrics_mod.histogram(
            "xla_compile_seconds",
            "compiling-call wall (trace + compile + first dispatch)",
        ).observe(wall_s, fn=name)
        if "flops" in analysis:
            metrics_mod.gauge(
                "xla_flops", "per-dispatch FLOPs of the last compiled "
                "program (cost_analysis)").set(analysis["flops"], fn=name)
        if "bytes_accessed" in analysis:
            metrics_mod.gauge(
                "xla_bytes_accessed", "per-dispatch HBM bytes of the last "
                "compiled program").set(analysis["bytes_accessed"], fn=name)
        if "peak_bytes" in analysis:
            metrics_mod.gauge(
                "xla_peak_bytes", "device-memory high water of the last "
                "compiled program").set(analysis["peak_bytes"], fn=name)
        goodput.note("compile", wall_s)
        _sinks.event("xla_compile", fn=name, compile_s=round(wall_s, 6),
                     cache=cache, **analysis)
    except Exception:
        pass
    return analysis


class InstrumentedJit:
    """jax.jit with compile observation (see module docstring).  Drop-in
    for the call/lower surface the code base uses; `donate_argnums` etc.
    pass straight through to jit."""

    def __init__(self, fun: Callable, name: str, **jit_kwargs) -> None:
        import jax

        self._fn = jax.jit(fun, **jit_kwargs)
        self.name = name
        # resolved ONCE: the env read + backend probe must not ride the
        # per-batch dispatch path (the flag is process-stable in practice;
        # flipping SHIFU_TPU_XLA_COST applies to fns built after the flip)
        self._capture = capture_enabled()
        self._flops_by_sig: dict[tuple, float] = {}

    def _note_dispatch(self) -> None:
        # per-name dispatch tally: a plain dict bump (GIL-atomic enough —
        # an off-by-one under a race is noise next to the window sizes
        # devprof divides by), skipped until the first compile creates
        # the stats entry, so the steady-state cost is one dict.get
        st = _stats.get(self.name)
        if st is not None:
            st["dispatches"] = st.get("dispatches", 0) + 1

    def _sig_of(self, args, kwargs):
        # AFTER the call is safe: donation deletes buffer *data*, but the
        # shape/dtype metadata _signature reads stays accessible — so the
        # steady-state path pays the pytree flatten only once a capture
        # has actually produced a FLOPs number to look up
        try:
            return _signature(args, kwargs)
        except Exception:
            return None

    def __call__(self, *args, **kwargs):
        fn = self._fn
        try:
            n0 = fn._cache_size()
        except Exception:
            n0 = None
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        wall = time.perf_counter() - t0
        if n0 is not None:
            try:
                compiled = fn._cache_size() > n0
            except Exception:
                compiled = False
            if compiled:
                analysis = _record_compile(self.name, fn, args, kwargs,
                                           wall, capture=self._capture)
                if "flops" in analysis:
                    sig = self._sig_of(args, kwargs)
                    if sig is not None:
                        self._flops_by_sig[sig] = analysis["flops"]
                        from . import goodput
                        goodput.note_flops(analysis["flops"])
                    self._note_dispatch()
                    return out
        if self._flops_by_sig:  # MFU numerator: credit per dispatch
            flops = self._flops_by_sig.get(self._sig_of(args, kwargs))
            if flops:
                from . import goodput
                goodput.note_flops(flops)
        self._note_dispatch()
        return out

    def lower(self, *args, **kwargs):
        return self._fn.lower(*args, **kwargs)


def instrument_jit(fun: Callable, name: str, **jit_kwargs) -> InstrumentedJit:
    """`jax.jit(fun, **jit_kwargs)` + compile/cost observation under
    `name` — the spelling train/step.py and the export scorer use."""
    return InstrumentedJit(fun, name, **jit_kwargs)


@contextlib.contextmanager
def compile_span(name: str, **fields) -> Iterator[None]:
    """Journal a compile that happens outside an instrumented jit (the
    export path's jax_export lowering, AOT warmups): times the block and
    emits the same `xla_compile` event shape, minus the cost fields."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        wall = time.perf_counter() - t0
        try:
            from ..utils import compilecache
            from . import _sinks, goodput, metrics as metrics_mod

            with _lock:
                st = _stats.setdefault(name,
                                       {"compiles": 0, "compile_s": 0.0})
                st["compiles"] += 1
                st["compile_s"] = round(st["compile_s"] + wall, 6)
            metrics_mod.counter(
                "xla_compiles_total",
                "XLA compiles observed per instrumented function",
            ).inc(fn=name)
            metrics_mod.histogram(
                "xla_compile_seconds",
                "compiling-call wall (trace + compile + first dispatch)",
            ).observe(wall, fn=name)
            goodput.note("compile", wall)
            _sinks.event("xla_compile", fn=name, compile_s=round(wall, 6),
                         cache=compilecache.observe_compile(), **fields)
        except Exception:
            pass


def reset_for_tests() -> None:
    with _lock:
        _stats.clear()


# re-exported through obs/__init__ for call sites
__all__ = ["instrument_jit", "InstrumentedJit", "compile_span",
           "capture_enabled", "stats", "reset_for_tests"]
