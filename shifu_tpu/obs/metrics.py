"""Metrics registry: counters, gauges, histograms with label sets.

The unified successor of the reference's 4-hop metric funnel (worker ->
Java socket -> ZooKeeper -> AM -> HDFS board; SURVEY.md section 5.5): every
subsystem writes into ONE process-local registry, and the registry exports
two ways — a Prometheus text-format scrape file (`metrics.prom`, written
through data/fsio so remote job dirs work) and structured snapshots that
feed the run journal and the cross-host skew table (obs/aggregate.py).

Dependency-free by design: stdlib + nothing.  Instruments are cheap enough
for per-batch call sites (one dict update under a lock); per-ROW call sites
should aggregate first (`counter.inc(n)`), never loop.
"""

from __future__ import annotations

import threading
from typing import Iterable, Optional

# Latency-shaped default buckets (seconds): sub-ms host work through
# multi-minute epochs.  Fixed bounds, not adaptive — cross-host and
# cross-run snapshots must merge bucket-for-bucket.
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in key)
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class Counter:
    """Monotonic counter; one value per label set."""

    kind = "counter"

    def __init__(self, name: str, help: str, lock: threading.RLock):
        self.name = name
        self.help = help
        self._lock = lock
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative inc {amount}")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def total(self) -> float:
        with self._lock:
            return sum(self._values.values())

    def _render(self, out: list[str]) -> None:
        for key in sorted(self._values):
            out.append(f"{self.name}{_fmt_labels(key)} "
                       f"{_fmt_value(self._values[key])}")

    def _snapshot(self) -> dict:
        return {"type": self.kind,
                "values": {";".join("=".join(kv) for kv in k): v
                           for k, v in self._values.items()}}


class Gauge(Counter):
    """Last-write-wins value; `inc` may go either direction."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics) per label set."""

    kind = "histogram"

    def __init__(self, name: str, help: str, lock: threading.RLock,
                 buckets: Iterable[float] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets))
        self._lock = lock
        # key -> [counts per bucket + inf, sum, count]
        self._series: dict[tuple, list] = {}

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = [[0] * (len(self.buckets) + 1),
                                         0.0, 0]
            counts, _sum, _n = s
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            s[1] = _sum + float(value)
            s[2] = _n + 1

    def observe_many(self, values, **labels) -> None:
        """Bulk observe in one lock acquisition — the stdlib-only bulk
        path (this module depends on nothing): bin with bisect, then
        merge.  Callers that already hold numpy arrays should bin with
        searchsorted and call merge_counts directly — that is what the
        serving plane's per-request latencies go through
        (export/scorer.py observe_request_latencies)."""
        import bisect

        counts = [0] * (len(self.buckets) + 1)
        total = 0.0
        n = 0
        for v in values:
            v = float(v)
            # index of the first bound >= v, i.e. the `value <= bound`
            # bucket observe() finds by scanning; == len(buckets) -> +Inf
            counts[bisect.bisect_left(self.buckets, v)] += 1
            total += v
            n += 1
        self.merge_counts(counts, total, n, **labels)

    def merge_counts(self, counts, total: float, n: int, **labels) -> None:
        """Merge a pre-bucketed batch (len(buckets)+1 counts in bound
        order, +Inf last) in one lock acquisition — the vectorized fast
        path for per-request serving latencies, where the caller bins
        thousands of values with numpy (export/scorer.py
        observe_request_latencies) instead of a Python loop here."""
        counts = list(counts)
        if len(counts) != len(self.buckets) + 1:
            raise ValueError(
                f"merge_counts: got {len(counts)} buckets, histogram "
                f"{self.name} has {len(self.buckets) + 1}")
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = [[0] * (len(self.buckets) + 1),
                                         0.0, 0]
            for i, c in enumerate(counts):
                if c:
                    s[0][i] += int(c)
            s[1] += float(total)
            s[2] += int(n)

    def counts(self, **labels) -> Optional[tuple[list, float, int]]:
        """Snapshot of one series: (per-bucket counts incl. +Inf, sum,
        n), or None when empty — lets a caller window/difference a
        cumulative histogram (e.g. the serving daemon's per-daemon
        percentiles over the process-global latency schema)."""
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                return None
            return list(s[0]), float(s[1]), int(s[2])

    def quantile(self, q: float, **labels) -> Optional[float]:
        """Histogram-estimated quantile (linear interpolation inside the
        owning bucket, Prometheus histogram_quantile semantics).  None for
        an empty series; values beyond the last finite bound clamp to it.
        An ESTIMATE bounded by bucket resolution — exact percentiles need
        the raw samples (tools/loadtest.py keeps them)."""
        snap = self.counts(**labels)
        if snap is None or snap[2] == 0:
            return None
        return quantile_from_counts(self.buckets, snap[0], snap[2], q)

    def count(self, **labels) -> int:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return 0 if s is None else int(s[2])

    def sum(self, **labels) -> float:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return 0.0 if s is None else float(s[1])

    def _render(self, out: list[str]) -> None:
        for key in sorted(self._series):
            counts, total, n = self._series[key]
            cum = 0
            for i, bound in enumerate(self.buckets):
                cum += counts[i]
                le = dict(key)
                le["le"] = _fmt_value(bound)
                out.append(f"{self.name}_bucket{_fmt_labels(_label_key(le))}"
                           f" {cum}")
            le = dict(key)
            le["le"] = "+Inf"
            out.append(f"{self.name}_bucket{_fmt_labels(_label_key(le))} {n}")
            out.append(f"{self.name}_sum{_fmt_labels(key)} "
                       f"{_fmt_value(total)}")
            out.append(f"{self.name}_count{_fmt_labels(key)} {n}")

    def _snapshot(self) -> dict:
        return {"type": self.kind,
                "values": {";".join("=".join(kv) for kv in k):
                           {"sum": s[1], "count": s[2]}
                           for k, s in self._series.items()}}


def quantile_from_counts(buckets, counts, n: int, q: float
                         ) -> Optional[float]:
    """The quantile interpolation over an explicit (buckets, counts, n)
    triple — shared by Histogram.quantile and callers that difference
    two counts() snapshots into a window."""
    if n <= 0:
        return None
    rank = q * n
    cum = 0.0
    lo = 0.0
    for i, bound in enumerate(buckets):
        prev = cum
        cum += counts[i]
        if cum >= rank and counts[i] > 0:
            frac = (rank - prev) / counts[i]
            return lo + (bound - lo) * min(max(frac, 0.0), 1.0)
        lo = bound
    return buckets[-1] if buckets else None


class MetricsRegistry:
    """Named instruments, one registry per process (default_registry()).

    Re-registering a name returns the SAME instrument (call sites stay
    declaration-free: `registry.counter("x").inc()` anywhere); a name
    re-registered as a different type raises — silently splitting a metric
    across types would corrupt every consumer.
    """

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._metrics: dict[str, object] = {}

    def _get(self, name: str, cls, help: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, self._lock, **kw)
            elif not isinstance(m, cls) or type(m) is not cls:
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, Counter, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, Gauge, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(name, Histogram, help, buckets=buckets)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._metrics)

    def to_prometheus_text(self) -> str:
        """The registry in Prometheus exposition text format (scrape-file
        contract: point a node-exporter textfile collector, or any tool
        that reads the format, at `metrics.prom`)."""
        out: list[str] = []
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                if m.help:
                    out.append(f"# HELP {name} {m.help}")
                out.append(f"# TYPE {name} {m.kind}")
                m._render(out)
        return "\n".join(out) + ("\n" if out else "")

    def snapshot(self) -> dict:
        """Structured {name: {type, values}} view — the journal / skew-table
        encoding (JSON-safe, merge-friendly)."""
        with self._lock:
            return {name: m._snapshot()
                    for name, m in sorted(self._metrics.items())}

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _DEFAULT


def counter(name: str, help: str = "") -> Counter:
    return _DEFAULT.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return _DEFAULT.gauge(name, help)


def histogram(name: str, help: str = "",
              buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
    return _DEFAULT.histogram(name, help, buckets)


def write_scrape_file(path: str,
                      registry: Optional[MetricsRegistry] = None) -> None:
    """Write the registry as a Prometheus text file at `path` — local or
    remote (gs:// hdfs:// mock://) through data/fsio, like the board.
    Best-effort: telemetry must never fail the job."""
    text = (registry or _DEFAULT).to_prometheus_text()
    try:
        from ..data import fsio
        if fsio.is_remote(path):
            fsio.write_bytes(path, text.encode())
            return
        import os
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)  # scrapers never see a half-written file
    except Exception:
        pass
