"""Trace-event parsing: jax.profiler captures -> per-kernel device rollups.

`jax.profiler.start_trace(dir)` emits, per capture, a timestamped run under
`<dir>/plugins/profile/<run>/` holding an xplane protobuf AND a Chrome
trace-event JSON (`*.trace.json.gz`).  The protobuf needs the tensorboard
profile plugin to read; the Chrome trace is plain gzip+JSON — this module
parses THAT, with stdlib only, so the device flight recorder works in any
checkout (no profiler-plugin dependency, no jax import).

What counts as a *device* event: XLA's trace converter tags every executed
kernel with `args.hlo_op` (+ `args.hlo_module`).  Host-side Python/dispatch
events carry no such tag, and the duplicate grouping lanes a TPU trace adds
(per-module rows, step rows) don't either — so filtering on `hlo_op`
selects exactly one record per kernel execution on every backend this has
been checked against (CPU TFRT, TPU).

The rollup is the `device_profile` journal event's payload (obs/devprof.py
adds the roofline join): per-kernel name/module/calls/device-µs/fraction of
the traced window, top-K by device time with the tail folded into
`other_us` — bounded output no matter how many distinct kernels a trace
holds.
"""

from __future__ import annotations

import gzip
import json
import os
from typing import Iterable, Optional

TRACE_SUFFIXES = (".trace.json.gz", ".trace.json")
DEFAULT_TOP_K = 16


def find_trace_files(log_dir: str) -> list[str]:
    """Every Chrome-trace file under a profiler log dir (any nesting —
    captures land in timestamped run subdirs), newest run last."""
    out: list[str] = []
    for root, _dirs, files in os.walk(log_dir):
        for name in files:
            if name.endswith(TRACE_SUFFIXES):
                out.append(os.path.join(root, name))
    return sorted(out)


def load_trace_events(path: str) -> list[dict]:
    """The `traceEvents` list of one Chrome-trace file (gzip or plain)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:  # type: ignore[operator]
        doc = json.loads(f.read().decode("utf-8", "replace"))
    events = doc.get("traceEvents", []) if isinstance(doc, dict) else []
    return events if isinstance(events, list) else []


def device_events(events: Iterable[dict]) -> list[dict]:
    """Complete ("X") events that are device kernel executions — the
    records carrying `args.hlo_op` (see module docstring)."""
    out = []
    for e in events:
        if not isinstance(e, dict) or e.get("ph") != "X":
            continue
        args = e.get("args")
        if isinstance(args, dict) and args.get("hlo_op"):
            out.append(e)
    return out


def _self_times(lane_events: list[tuple]) -> list[tuple]:
    """(ts, dur, self_us, name, module) per event of ONE lane.

    Device traces nest: a scan's `while` op spans its inner dots on the
    same lane, so summing raw durations double-counts every level of the
    flame.  Classic stack reconstruction — events sorted by (start,
    -dur); an event starting before the stack top ends is its child and
    subtracts from the parent's SELF time — makes per-kernel times sum
    to the lane's busy time exactly.
    """
    ordered = sorted(lane_events, key=lambda e: (e[0], -e[1]))
    out = [[ts, dur, dur, name, module] for ts, dur, name, module in ordered]
    stack: list[list] = []
    for rec in out:
        ts, dur = rec[0], rec[1]
        while stack and ts >= stack[-1][0] + stack[-1][1] - 1e-9:
            stack.pop()
        if stack:
            stack[-1][2] -= dur  # child time is not the parent's self time
        stack.append(rec)
    return [(ts, dur, max(self_us, 0.0), name, module)
            for ts, dur, self_us, name, module in out]


def kernel_rollup(events: Iterable[dict],
                  top_k: int = DEFAULT_TOP_K) -> Optional[dict]:
    """Per-kernel device-time rollup of one capture's device events.

    Returns None when the capture holds no device events (a trace window
    that bracketed no dispatch).  Per-kernel `device_us` is SELF time
    (nested children subtracted — see _self_times), so kernels sum to
    the device-busy time, never above it.  Fractions are of the traced
    window — first device-event start to last end — divided across
    `lanes` (the distinct (pid, tid) execution rows device events ran
    on), so they sum to <= 1 even when kernels on different devices
    overlap in wall time.
    """
    devs = device_events(events)
    if not devs:
        return None
    by_lane: dict[tuple, list[tuple]] = {}
    for e in devs:
        try:
            ts = float(e.get("ts", 0.0))
            dur = float(e.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        if not (dur >= 0.0) or dur == float("inf"):
            continue
        args = e.get("args") or {}
        name = str(e.get("name") or args.get("hlo_op") or "?")
        module = str(args.get("hlo_module") or "")
        by_lane.setdefault((e.get("pid"), e.get("tid")), []).append(
            (ts, dur, name, module))
    per: dict[tuple, dict] = {}  # (name, module) -> {calls, us}
    mod_totals: dict[str, float] = {}  # module -> us over ALL its kernels
    lanes = set(by_lane)
    t_lo = float("inf")
    t_hi = float("-inf")
    total_us = 0.0
    for lane, lane_events in by_lane.items():
        for ts, dur, self_us, name, module in _self_times(lane_events):
            k = per.setdefault((name, module), {"calls": 0, "us": 0.0})
            k["calls"] += 1
            k["us"] += self_us
            total_us += self_us
            if module:
                mod_totals[module] = mod_totals.get(module, 0.0) + self_us
            t_lo = min(t_lo, ts)
            t_hi = max(t_hi, ts + dur)
    if not per:
        return None
    window_us = max(t_hi - t_lo, 0.0)
    denom = window_us * max(len(lanes), 1)
    ranked = sorted(per.items(), key=lambda kv: -kv[1]["us"])
    kernels = [{
        "name": name,
        "module": module or None,
        "calls": v["calls"],
        "device_us": round(v["us"], 3),
        "fraction": round(v["us"] / denom, 6) if denom > 0 else None,
    } for (name, module), v in ranked[:max(top_k, 1)]]
    other_us = sum(v["us"] for _k, v in ranked[max(top_k, 1):])
    return {
        "window_us": round(window_us, 3),
        "device_us_total": round(total_us, 3),
        "device_fraction": (round(total_us / denom, 6) if denom > 0
                            else None),
        "lanes": len(lanes),
        "kernel_count": len(per),
        "kernels": kernels,
        "other_us": round(other_us, 3),
        # per-module device time over ALL kernels, before the top-K cut:
        # the roofline denominators (devprof.roofline_join) must cover a
        # module's tail kernels too, or its fractions overstate
        "modules": {m: round(us, 3)
                    for m, us in sorted(mod_totals.items(),
                                        key=lambda kv: -kv[1])},
    }


def rollup_trace_dir(log_dir: str,
                     top_k: int = DEFAULT_TOP_K) -> Optional[dict]:
    """Rollup over every trace file under `log_dir` (one capture = one
    run subdir; merging multiple runs merges their kernels).  None when
    no file yields device events.

    Memory: each file is parsed and immediately FILTERED to its device
    events (the Chrome trace is dominated by host Python events — often
    100x the device rows), so the retained working set is one file's
    decoded document plus the device events, not every file's full
    event list.  Long epoch windows on dispatch-heavy jobs still decode
    one large document; schedule such windows sparingly
    (obs.trace_epochs) rather than every epoch.
    """
    merged: list[dict] = []
    for path in find_trace_files(log_dir):
        try:
            merged.extend(device_events(load_trace_events(path)))
        except (OSError, ValueError):
            continue  # a torn capture must not hide the readable ones
    return kernel_rollup(merged, top_k=top_k)


def diff_rollups(a: dict, b: dict) -> list[dict]:
    """Per-kernel device-time deltas between two rollups (A = before,
    B = after) — the regression-attribution table tools/trace_diff.py
    prints.  Kernels are matched by (name, module); one-sided kernels
    show with the missing side at 0."""
    def index(r: dict) -> dict[tuple, dict]:
        return {(k["name"], k.get("module")): k
                for k in r.get("kernels") or []}

    ia, ib = index(a), index(b)
    out = []
    for key in sorted(set(ia) | set(ib)):
        ka, kb = ia.get(key), ib.get(key)
        ua = float(ka["device_us"]) if ka else 0.0
        ub = float(kb["device_us"]) if kb else 0.0
        out.append({
            "name": key[0],
            "module": key[1],
            "a_us": round(ua, 3),
            "b_us": round(ub, 3),
            "delta_us": round(ub - ua, 3),
            "ratio": round(ub / ua, 4) if ua > 0 else None,
            "a_calls": ka["calls"] if ka else 0,
            "b_calls": kb["calls"] if kb else 0,
        })
    out.sort(key=lambda d: -abs(d["delta_us"]))
    return out
