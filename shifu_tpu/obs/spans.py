"""Span tracing: nested host-side phase timing feeding registry + journal.

`with obs.span("epoch/eval"):` times the block, records the duration into
the `span_seconds` histogram (labeled with the full nested path) and
journals a `span` event.  Nesting composes paths — a span opened inside
`span("epoch")` named "eval" journals as "epoch/eval" — so one stream
reconstructs where wall time went across phases, the host-side complement
of the jax.profiler device trace (train/profiler.py).

Thread-local nesting: the prefetch producer thread's spans nest
independently of the main thread's — each thread reads as its own
coherent phase stack.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Iterator

from . import metrics as metrics_mod

_state = threading.local()


def current_path() -> str:
    """The active nested span path ("" at top level)."""
    return "/".join(getattr(_state, "stack", ()))


def emit(path: str, dur_s: float, journal: bool = True, **fields) -> None:
    """Record one completed span: `span_seconds` histogram observation +
    (optionally) a `span` journal event.  The ONE emission contract —
    shared by the `span()` context manager and external phase trackers
    (bench._PhaseTrack), so bench phases and real spans can never diverge
    into split metrics.  Never raises."""
    try:
        metrics_mod.histogram(
            "span_seconds",
            "host-side phase durations by nested span path",
        ).observe(dur_s, span=path)
        if journal:
            from . import _sinks
            _sinks.event("span", span=path, dur_s=round(dur_s, 6), **fields)
    except Exception:
        pass  # telemetry must never fail the phase it measures


@contextlib.contextmanager
def span(name: str, journal: bool = True, **fields) -> Iterator[None]:
    """Time a phase.  `fields` ride into the journal event (e.g.
    `span("epoch/train", epoch=3)`); set `journal=False` for hot spans that
    should only feed the histogram."""
    stack = getattr(_state, "stack", None)
    if stack is None:
        stack = _state.stack = []
    stack.append(name)
    path = "/".join(stack)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dur = time.perf_counter() - t0
        stack.pop()
        emit(path, dur, journal=journal, **fields)
