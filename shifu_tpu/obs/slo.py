"""Serving SLO engine: rolling-window burn-rate evaluation, per-request
lifecycle stage accounting, and the anomaly->device-trace bridge for the
scoring daemon (docs/OBSERVABILITY.md "Serving SLO engine").

The reference shipped NO scoring-side signal at all (its eval module was a
row-at-a-time JNI call with aggregation left to the Shifu host; the only
production metrics were the per-epoch training funnel, PAPER.md §0), and
TPU serving comparisons treat p99-under-SLO as *the* serving figure of
merit (arxiv 2605.25645, PAPERS.md).  Three pieces:

- **Lifecycle stages** — every request through runtime/serve.py is
  decomposed into the span chain
  ``admission -> queue -> coalesce -> dispatch -> device -> reply``
  whose durations sum EXACTLY to the end-to-end latency (the stamps are
  shared batch boundaries, so no stage gap or overlap is possible).
  `observe_stage_seconds` bins a whole batch's per-stage values into the
  always-on `serve_stage_seconds{stage=...}` histogram in one vectorized
  pass per stage (searchsorted + bincount + one merge_counts lock), so a
  p99 excursion decomposes into stages from the scrape file alone.
- **SloEngine** — objectives from `ServingConfig` (`shifu.serving.slo.*`):
  p99 latency, error rate, availability.  The daemon feeds cumulative
  counters + latency-histogram snapshots on a fixed tick; the engine
  keeps a rolling sample ring and evaluates each objective over a FAST
  and a SLOW window (multiwindow burn-rate alerting: both windows must
  burn past `slo_burn_threshold` to fire, so a one-tick blip cannot
  alert but a sustained burn fires within ~one fast window).  A firing
  objective emits ONE `slo_alert` (state="firing") and stays latched
  until the fast window is healthy again (burn < 1), which emits
  state="resolved" — exactly one alert per violation episode.
- **ServeTraceTrigger** — the serving analog of the flight recorder's
  one-shot anomaly trace (obs/devprof.py): a p99 alert arms it, the
  daemon's next dispatch runs under `jax.profiler` capture, and the
  rollup journals a `device_profile` event with ``trigger="slo"`` — so
  a serving latency excursion gets kernel-level attribution exactly
  like a training anomaly.  Chaos-probed at the shared `obs.trace`
  site; every failure degrades to a journaled `trace_fallback` and the
  dispatch itself is never blocked.

Everything here is jax-free except the armed trace capture; the engine is
pure given injected timestamps, so drills replay deterministically.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
from typing import Callable, Optional

# The span chain, in request order.  `admission` is the submit-side cost
# (validation + enqueue), `queue` the wait until a dispatch worker opened
# the coalesce window, `coalesce` the time inside that window, `dispatch`
# host-side batch assembly (stack + pad bucket), `device` the engine's
# compute_batch, `reply` future resolution back to the caller.
STAGES = ("admission", "queue", "coalesce", "dispatch", "device", "reply")

STAGE_HISTOGRAM = "serve_stage_seconds"

# objective keys, as journaled in slo_alert events
OBJ_P99 = "p99_latency"
OBJ_ERRORS = "error_rate"
OBJ_AVAILABILITY = "availability"

# engines with no device plane: the one-shot slo trace skips the
# profiler window for them (it would stall a dispatch for seconds to
# capture zero XLA events) and journals the empty attribution directly
HOST_ENGINES = ("numpy", "native")


def _latency_buckets() -> tuple:
    # the ONE serving latency bucket table (export/scorer.py) — lazy so
    # importing obs.slo never pulls the artifact machinery
    from ..export.scorer import SCORE_LATENCY_BUCKETS
    return SCORE_LATENCY_BUCKETS


def observe_stage_seconds(stage_values: dict, n: int) -> None:
    """Record one dispatched batch's per-stage durations into the
    `serve_stage_seconds{stage=...}` histogram.  `stage_values` maps a
    stage name to either a scalar (the whole batch shared it: dispatch /
    device / reply) or a length-n array (per-request: admission / queue /
    coalesce).  One vectorized bin + one lock acquisition per stage —
    the always-on cost the quiet-traffic budget test pins."""
    import numpy as np

    from . import metrics as metrics_mod

    if n <= 0:
        return
    buckets = _latency_buckets()
    bounds = np.asarray(buckets, np.float64)
    hist = metrics_mod.histogram(
        STAGE_HISTOGRAM,
        "per-request serving lifecycle stage durations "
        "(admission/queue/coalesce/dispatch/device/reply)",
        buckets=buckets)
    for stage, v in stage_values.items():
        arr = np.asarray(v, np.float64)
        if arr.ndim == 0:
            # scalar stage: all n requests saw the same duration — one
            # bucket gets the whole count, no per-request loop
            counts = [0] * (len(buckets) + 1)
            counts[int(np.searchsorted(bounds, float(arr), side="left"))] = n
            hist.merge_counts(counts, float(arr) * n, n, stage=stage)
        else:
            idx = np.searchsorted(bounds, arr, side="left")
            counts = np.bincount(idx, minlength=len(buckets) + 1)
            hist.merge_counts(counts.tolist(), float(arr.sum()), int(arr.size),
                              stage=stage)


def stage_stats(per_stage: dict) -> dict:
    """{stage: (bounds, counts, sum_seconds, n)} -> {stage: {mean_ms,
    p99_ms, count, share}} — the ONE stage-decomposition shape every
    renderer shows (`shifu-tpu top` from the scrape file, loadtest /
    stats() from differenced histogram snapshots): share is the stage's
    summed seconds over all stages' (where the e2e wall went)."""
    from .metrics import quantile_from_counts

    out: dict = {}
    sums: dict = {}
    for stage, (bounds, counts, total, n) in per_stage.items():
        if n <= 0:
            continue
        p99 = quantile_from_counts(bounds, counts, n, 0.99)
        out[stage] = {
            "mean_ms": round(total / n * 1e3, 4),
            "p99_ms": round(p99 * 1e3, 4) if p99 is not None else None,
            "count": int(n),
        }
        sums[stage] = total
    total_s = sum(sums.values())
    if total_s > 0:
        for stage, s in out.items():
            s["share"] = round(sums[stage] / total_s, 4)
    return out


# ------------------------------------------------------------- objectives


@dataclasses.dataclass(frozen=True)
class SloObjectives:
    """The serving objectives + burn-rate windows (ServingConfig's
    `slo_*` fields / `shifu.serving.slo.*` XML keys).  An objective at 0
    is disabled; `enabled()` is False when all three are."""

    p99_ms: float = 0.0          # p99 latency target; budget = 1% over it
    error_rate: float = 0.0      # allowed error fraction (e.g. 0.001)
    availability: float = 0.0    # target admitted-and-scored fraction
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    burn_threshold: float = 2.0  # both windows must burn past this
    min_requests: int = 20       # don't judge near-empty windows

    def enabled(self) -> bool:
        return (self.p99_ms > 0 or self.error_rate > 0
                or self.availability > 0)

    @classmethod
    def from_serving_config(cls, cfg) -> "SloObjectives":
        return cls(p99_ms=cfg.slo_p99_ms,
                   error_rate=cfg.slo_error_rate,
                   availability=cfg.slo_availability,
                   fast_window_s=cfg.slo_fast_window_s,
                   slow_window_s=cfg.slo_slow_window_s,
                   burn_threshold=cfg.slo_burn_threshold)


class SloEngine:
    """Rolling-window burn-rate evaluation over cumulative daemon
    counters.  Pure given injected timestamps: `observe(now, ...)` feeds
    one sample, `evaluate(now)` returns the alert events (firing AND
    resolved) that transitioned at that instant — the caller journals
    them.  Thread-compat: the daemon's SLO loop is the only caller, but
    state mutation is lock-guarded so stats() can read burn rates."""

    def __init__(self, objectives: SloObjectives,
                 buckets: Optional[tuple] = None):
        self.obj = objectives
        self.buckets = tuple(buckets if buckets is not None
                             else _latency_buckets())
        self._lock = threading.Lock()
        # ring of (t, requests, rejected, errors, latency_counts tuple);
        # pruned to the slow window plus one base sample
        self._samples: collections.deque = collections.deque()
        self._firing: dict[str, dict] = {}
        self._burns: dict[str, dict] = {}  # objective -> last burn pair
        self.alerts_fired = 0

    # -- sampling ------------------------------------------------------

    def observe(self, now: float, requests: int, rejected: int,
                errors: int, latency_counts: Optional[list] = None) -> None:
        """Feed one cumulative snapshot.  `latency_counts` is the
        per-bucket counts list (len(buckets)+1, +Inf last) of THIS
        daemon's `score_latency_seconds` series (already baselined to
        the daemon's lifetime by the caller); None when no request has
        been scored yet."""
        counts = (tuple(int(c) for c in latency_counts)
                  if latency_counts is not None else None)
        with self._lock:
            self._samples.append((float(now), int(requests), int(rejected),
                                  int(errors), counts))
            horizon = float(now) - self.obj.slow_window_s
            # keep ONE sample at/older than the horizon as the window base
            while (len(self._samples) >= 2
                   and self._samples[1][0] <= horizon):
                self._samples.popleft()

    def _window(self, now: float, seconds: float) -> Optional[dict]:
        """Counter deltas over the trailing `seconds` (newest sample vs
        the newest sample at/older than now - seconds; the oldest held
        sample when none is old enough — early life uses what exists)."""
        if len(self._samples) < 2:
            return None
        cur = self._samples[-1]
        cut = now - seconds
        base = self._samples[0]
        for s in self._samples:
            if s[0] <= cut:
                base = s
            else:
                break
        span = cur[0] - base[0]
        if span <= 0:
            return None
        counts = None
        if cur[4] is not None:
            base_counts = base[4] or (0,) * len(cur[4])
            counts = [c - b for c, b in zip(cur[4], base_counts)]
        return {"span_s": span,
                "requests": cur[1] - base[1],
                "rejected": cur[2] - base[2],
                "errors": cur[3] - base[3],
                "latency_counts": counts}

    # -- burn computation ----------------------------------------------

    def _burn_p99(self, w: dict) -> Optional[tuple]:
        """(burn, observed_p99_s) for the latency objective: the burn is
        the fraction of requests slower than the target divided by the 1%
        budget.  Counting is bucket-conservative: requests in buckets
        whose upper bound is <= the target count as meeting it — pick the
        target from the bucket table (1/2.5/5/10/25ms...) for exactness."""
        n = w["requests"]
        counts = w["latency_counts"]
        if counts is None or n < self.obj.min_requests:
            return None
        threshold = self.obj.p99_ms / 1000.0
        ok = 0
        for bound, c in zip(self.buckets, counts):
            if bound <= threshold + 1e-12:
                ok += c
        total = sum(counts)
        if total <= 0:
            return None
        violations = max(total - ok, 0)
        burn = (violations / total) / 0.01
        from .metrics import quantile_from_counts
        p99 = quantile_from_counts(self.buckets, counts, total, 0.99)
        return burn, p99

    def _burn_errors(self, w: dict) -> Optional[tuple]:
        total = w["requests"] + w["errors"]
        if total < self.obj.min_requests:
            return None
        rate = w["errors"] / total
        return rate / self.obj.error_rate, rate

    def _burn_availability(self, w: dict) -> Optional[tuple]:
        total = w["requests"] + w["errors"] + w["rejected"]
        if total < self.obj.min_requests:
            return None
        ok_frac = w["requests"] / total
        budget = max(1.0 - self.obj.availability, 1e-9)
        return (1.0 - ok_frac) / budget, ok_frac

    # -- evaluation ----------------------------------------------------

    def evaluate(self, now: float) -> list[dict]:
        """Evaluate every enabled objective at `now`; returns the
        `slo_alert` event payloads that TRANSITIONED (fired or resolved)
        this call, most severe first.  Idempotent between transitions —
        a latched alert never re-emits."""
        out: list[dict] = []
        with self._lock:
            fast = self._window(now, self.obj.fast_window_s)
            slow = self._window(now, self.obj.slow_window_s)
            if fast is None or slow is None:
                return out
            specs = []
            if self.obj.p99_ms > 0:
                specs.append((OBJ_P99, self._burn_p99,
                              {"target_p99_ms": self.obj.p99_ms}))
            if self.obj.error_rate > 0:
                specs.append((OBJ_ERRORS, self._burn_errors,
                              {"target_error_rate": self.obj.error_rate}))
            if self.obj.availability > 0:
                specs.append((OBJ_AVAILABILITY, self._burn_availability,
                              {"target_availability":
                               self.obj.availability}))
            for name, fn, target in specs:
                bf = fn(fast)
                bs = fn(slow)
                if bf is None:
                    # window below min_requests: no judgment — but a
                    # LATCHED alert must not survive the traffic that
                    # caused it going away (an idle daemon showing a
                    # stale FIRING alert forever helps no one)
                    if name in self._firing:
                        del self._firing[name]
                        self._burns.pop(name, None)
                        out.append({
                            "objective": name, "state": "resolved",
                            "burn_fast": 0.0, "burn_slow": 0.0,
                            "burn_threshold": self.obj.burn_threshold,
                            "fast_window_s": round(fast["span_s"], 3),
                            "slow_window_s": round(slow["span_s"], 3),
                            "requests_window": fast["requests"],
                            "note": "window below min_requests — "
                                    "traffic stopped", **target})
                    continue
                burn_fast, observed = bf
                burn_slow = bs[0] if bs is not None else burn_fast
                self._burns[name] = {"burn_fast": round(burn_fast, 4),
                                     "burn_slow": round(burn_slow, 4)}
                firing = name in self._firing
                ev = {
                    "objective": name,
                    "burn_fast": round(burn_fast, 4),
                    "burn_slow": round(burn_slow, 4),
                    "burn_threshold": self.obj.burn_threshold,
                    "fast_window_s": round(fast["span_s"], 3),
                    "slow_window_s": round(slow["span_s"], 3),
                    "requests_window": fast["requests"],
                    **target,
                }
                if name == OBJ_P99 and observed is not None:
                    ev["observed_p99_ms"] = round(observed * 1e3, 3)
                elif name == OBJ_ERRORS:
                    ev["observed_error_rate"] = round(observed, 6)
                elif name == OBJ_AVAILABILITY:
                    ev["observed_availability"] = round(observed, 6)
                if (not firing and burn_fast >= self.obj.burn_threshold
                        and burn_slow >= self.obj.burn_threshold):
                    ev["state"] = "firing"
                    self._firing[name] = ev
                    self.alerts_fired += 1
                    out.append(ev)
                elif firing and burn_fast < 1.0:
                    ev["state"] = "resolved"
                    del self._firing[name]
                    out.append(ev)
        return out

    def state(self) -> dict:
        """Operator snapshot: per-objective last burn pair + firing set
        (`stats()["slo"]` / the `shifu-tpu top` active-alerts column)."""
        with self._lock:
            return {
                "objectives": {
                    k: v for k, v in (
                        (OBJ_P99, self.obj.p99_ms),
                        (OBJ_ERRORS, self.obj.error_rate),
                        (OBJ_AVAILABILITY, self.obj.availability)) if v > 0},
                "burns": {k: dict(v) for k, v in self._burns.items()},
                "firing": sorted(self._firing),
                "alerts_fired": self.alerts_fired,
            }


# ------------------------------------------------- one-shot device trace


class ServeTraceTrigger:
    """One-shot `jax.profiler` capture of the NEXT dispatched batch,
    armed by a p99 `slo_alert` — journals a `device_profile` event with
    ``trigger="slo"`` so a serving latency excursion carries kernel-level
    attribution like a training anomaly (obs/devprof.py).

    `armed` is a plain attribute the dispatch hot path reads for free;
    `capture(fn)` is only entered when it is set.  Best-effort end to
    end: chaos site `obs.trace` probes every capture attempt, any
    failure journals `trace_fallback`, and `fn` runs regardless — the
    trace plane must never fail (or block) the dispatch it observes."""

    def __init__(self, trace_dir: str = "", top_k: int = 16):
        self._explicit_dir = trace_dir
        self.top_k = int(top_k)
        self._lock = threading.Lock()
        self.armed = False
        self._context: Optional[dict] = None
        self._seq = 0
        self.captures = 0
        # a capture whose finalize (stop + parse + journal) is still
        # running on its background thread — a new capture must not
        # start a second profiler session under it
        self._finishing = False

    def arm(self, **context) -> bool:
        """Arm for the next dispatch; no-op (False) while already armed."""
        with self._lock:
            if self.armed:
                return False
            self._context = dict(context)
            self.armed = True
            return True

    def _resolve_dir(self) -> Optional[str]:
        if self._explicit_dir:
            return self._explicit_dir
        from . import devprof
        return devprof.resolve_trace_dir()

    def capture(self, fn: Callable):
        """Run `fn` under a one-shot profiler window and journal the
        rollup; falls through to a plain `fn()` on any trace failure."""
        with self._lock:
            if not self.armed:
                return fn()
            context, self._context = self._context or {}, None
            self.armed = False
            self._seq += 1
            seq = self._seq
        from . import _sinks, devprof, metrics as metrics_mod

        import sys

        if context.get("engine") in HOST_ENGINES:
            # a host-side engine (numpy/native) has no device plane: a
            # profiler window around it yields zero XLA events AFTER
            # stalling this dispatch for seconds (profiler start/stop +
            # trace parse).  Skip straight to the attribution: "not
            # device time" IS the answer for a host-side engine.
            self._journal_empty(context, "host-side engine "
                                f"({context.get('engine')}) — no device "
                                "plane to trace")
            return fn()
        if "jax" not in sys.modules:
            # an exotic engine without jax loaded: a cold jax import
            # inside THIS dispatch would stall it for seconds — worse
            # than the excursion being diagnosed
            self._journal_empty(context, "jax not loaded — no device "
                                         "plane to trace")
            return fn()
        with self._lock:
            if self._finishing:
                # the previous capture's finalize still owns the (single)
                # profiler session; starting another would only raise
                self._journal_empty(context, "previous slo capture still "
                                             "finalizing — skipped")
                return fn()
            self._finishing = True
        base = self._resolve_dir()
        log_dir = (os.path.join(base, f"slo-{seq:04d}")
                   if base else None)
        started = False
        if log_dir is not None:
            try:
                from .. import chaos
                chaos.maybe_fail(devprof.CHAOS_SITE, trigger="slo",
                                 path=log_dir)
                import jax
                os.makedirs(log_dir, exist_ok=True)
                jax.profiler.start_trace(log_dir)
                started = True
            except Exception as e:
                _sinks.event("trace_fallback", stage="start", trigger="slo",
                             error=str(e)[:200])
                metrics_mod.counter(
                    "trace_fallback_total",
                    "trace captures degraded to untraced epochs").inc(
                        stage="start")
        else:
            _sinks.event("trace_fallback", stage="start", trigger="slo",
                         error="no trace dir (telemetry sinks not "
                               "configured or remote)")
        if not started:
            with self._lock:
                self._finishing = False
            return fn()
        try:
            return fn()
        finally:
            # finalize (profiler stop + trace parse + journal — hundreds
            # of ms) OFF the dispatch path: the batch's futures must not
            # absorb the parse, and the latency the SLO window sees must
            # stay the daemon's, not the diagnostics'.  The window simply
            # extends until the stop lands — a wider capture, never a
            # stalled dispatch.
            threading.Thread(target=self._finish_and_clear,
                             args=(log_dir, context), daemon=True,
                             name="serve-slo-trace-finish").start()

    def _finish_and_clear(self, log_dir: str, context: dict) -> None:
        try:
            self._finish(log_dir, context)
        finally:
            with self._lock:
                self._finishing = False

    def _journal_empty(self, context: dict, note: str) -> None:
        """A device_profile event with no kernels — the excursion's
        attribution when there is nothing on the device side to trace."""
        from . import _sinks, metrics as metrics_mod
        _sinks.event("device_profile", trigger="slo", window_us=0,
                     device_us_total=0, device_fraction=None, lanes=0,
                     kernel_count=0, kernels=[], other_us=0, note=note,
                     **context)
        metrics_mod.counter(
            "device_profiles_total",
            "device trace captures rolled up and journaled").inc(
                trigger="slo")
        self.captures += 1

    def _finish(self, log_dir: str, context: dict) -> None:
        from . import _sinks, devprof, metrics as metrics_mod, tracefmt
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:
            _sinks.event("trace_fallback", stage="stop", trigger="slo",
                         error=str(e)[:200])
            metrics_mod.counter("trace_fallback_total", "").inc(stage="stop")
            return
        try:
            rollup = tracefmt.rollup_trace_dir(log_dir, top_k=self.top_k)
        except Exception as e:
            _sinks.event("trace_fallback", stage="parse", trigger="slo",
                         error=str(e)[:200])
            metrics_mod.counter("trace_fallback_total", "").inc(stage="parse")
            return
        if rollup is None:
            # the capture bracketed no XLA dispatch (numpy/native engine):
            # the event still lands — an empty kernel table IS the
            # attribution ("the excursion was not device time")
            rollup = {"window_us": 0, "device_us_total": 0,
                      "device_fraction": None, "lanes": 0,
                      "kernel_count": 0, "kernels": [], "other_us": 0,
                      "note": "no device events in the traced dispatch "
                              "(host-side engine)"}
        else:
            try:
                devprof.roofline_join(rollup)
            except Exception:
                pass
        rollup.update(trigger="slo", trace_dir=log_dir, **context)
        _sinks.event("device_profile", **rollup)
        metrics_mod.counter(
            "device_profiles_total",
            "device trace captures rolled up and journaled").inc(
                trigger="slo")
        self.captures += 1
