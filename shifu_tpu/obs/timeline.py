"""Skew-corrected fleet timeline: merge every member's journal into one
causally-ordered event stream and reconstruct incidents from it
(docs/OBSERVABILITY.md "Fleet timeline").

A fleet's journals are per-member files stamped by per-host clocks.
Raw concatenation therefore lies about causality: a standby promoted on
a host whose clock runs 10s slow appears to serve *before* the failover
that promoted it.  This module fixes the merge in three layers:

1. **Clock-offset correction** — the manager observes every member's
   lease round-trip (runtime/fleet.py `check_members`) and journals a
   `fleet_clock_skew` event per host: a running MIN over
   ``manager_now - lease.ts``.  True lease age is >= 0, so the min
   approximates the host's clock offset with a positive bias bounded by
   one heartbeat period — good enough to order events separated by more
   than a beat, which is exactly the failover/promotion scale.  Each
   member event's corrected time is ``ts + offset[host]`` (the manager's
   own journal is the reference frame, offset 0).

2. **Happens-before nudging** — causal edges the protocol guarantees
   (failover -> promotion, failover -> rejoin, swap-degraded ->
   readmit, member `request_trace` -> router `route_trace` of the same
   trace) override residual clock error: a child event is never ordered
   before its parent, whatever the clocks claim.

3. **Incident reconstruction** — `fleet_failover`, `slo_alert`, and
   `fleet_swap_degraded` episodes become first-class `incident` records:
   root event, causal chain, affected sampled traces (hedged or failed
   `route_trace`s in the window), recovery duration, and a chaos-inject
   root-cause hint when an injection immediately precedes the root.

Everything here is journal-reads only — no jax import, bounded tails for
the CLI path (`shifu-tpu timeline`, like `top`), full reads for
`fleet-verify` (which needs complete history for its counting checks).
"""

from __future__ import annotations

import os
from typing import Optional

from . import journal as journal_mod

# journaled by the manager per host (runtime/fleet.py `_observe_skew`)
CLOCK_SKEW_KIND = "fleet_clock_skew"

# bounded per-journal tail for interactive views (same rationale as
# render._TOP_TAIL_BYTES: a long-lived fleet's journals grow without
# bound; a timeline frame must not pay O(run-length) reads)
TAIL_BYTES = 4 << 20

# a chaos injection at most this many seconds before an incident's root
# event is surfaced as the root-cause hint
_CHAOS_HINT_WINDOW_S = 5.0
# affected-trace collection window pads the incident span by this much
# on each side (route_trace lands at reply time, after the damage)
_TRACE_WINDOW_PAD_S = 1.0
_MAX_AFFECTED_TRACES = 20
_MAX_JOURNALS = 64
_EPS = 1e-4  # minimal causal nudge past a parent event


# -- journal discovery ------------------------------------------------------


def discover_journals(path: str) -> list[str]:
    """Every journal under a fleet dir: the root journal (job dir /
    telemetry dir / direct path, resolved like `top`) plus one level of
    member subdirs holding their own `journal.jsonl` (process-mode
    members each journal into their tele dir).  Remote roots resolve the
    root journal only — no remote listdir."""
    from . import render

    out: list[str] = []
    root = render.find_journal(path)
    if root is not None:
        out.append(root)
    base = os.path.dirname(root) if root else (
        path if os.path.isdir(path) else None)
    if base and os.path.isdir(base):
        try:
            names = sorted(os.listdir(base))
        except OSError:
            names = []
        for name in names:
            j = os.path.join(base, name, journal_mod.JOURNAL_FILE)
            if os.path.isfile(j):
                out.append(j)
    return out[:_MAX_JOURNALS]


def _journal_host(jpath: str) -> str:
    """The host a journal's events were stamped by, from the member
    lease next to it (runtime/fleet.py writes `host` into the lease).
    No lease / no host -> "" (reference frame: no correction)."""
    from . import render

    lease = render._read_lease_nearby(jpath)
    if lease and lease.get("host"):
        return str(lease["host"])
    return ""


# -- skew-corrected merge ---------------------------------------------------


def estimate_offsets(events: list[dict]) -> dict[str, float]:
    """{host: clock offset_s} from the manager's `fleet_clock_skew`
    events (newest observation per host wins — the manager already
    publishes a running min, so the last event is the best estimate)."""
    out: dict[str, float] = {}
    for ev in events:
        if ev.get("kind") == CLOCK_SKEW_KIND and ev.get("host"):
            try:
                out[str(ev["host"])] = float(ev.get("offset_s", 0.0))
            except (TypeError, ValueError):
                continue
    return out


def _order_key(ev: dict):
    return (ev.get("ts_fleet", 0.0), ev.get("src", 0), ev.get("seq", 0))


def _apply_happens_before(events: list[dict]) -> None:
    """Enforce protocol-guaranteed causal edges on an already
    ts-sorted merge: a child event whose corrected clock still places it
    before its parent is nudged just past the parent.  Edges:
    failover -> promotion swap, failover -> rejoin, swap-degraded ->
    readmit, member request_trace -> router route_trace (same trace).
    In-place; re-sorts at the end."""
    failover_by_standby: dict[str, dict] = {}
    failover_by_member: dict[str, dict] = {}
    degraded_by_member: dict[str, dict] = {}
    last_request_ts: dict[str, float] = {}
    for ev in events:
        k = ev.get("kind")
        if k == "fleet_failover":
            if ev.get("standby"):
                failover_by_standby[str(ev["standby"])] = ev
            if ev.get("member"):
                failover_by_member[str(ev["member"])] = ev
        elif k == "fleet_swap_degraded" and ev.get("member"):
            degraded_by_member[str(ev["member"])] = ev
        elif k == "request_trace" and ev.get("trace_id"):
            tid = str(ev["trace_id"])
            last_request_ts[tid] = max(last_request_ts.get(tid, 0.0),
                                       ev.get("ts_fleet", 0.0))
    for ev in events:
        k = ev.get("kind")
        parent = None
        if k == "fleet_member_swap" and ev.get("via") == "promote":
            parent = failover_by_standby.get(str(ev.get("member")))
        elif k == "fleet_rejoin":
            parent = failover_by_member.get(str(ev.get("member")))
        elif k == "fleet_readmit":
            parent = degraded_by_member.get(str(ev.get("member")))
        if parent is not None and ev["ts_fleet"] <= parent["ts_fleet"]:
            ev["ts_fleet"] = parent["ts_fleet"] + _EPS
        if k == "route_trace" and ev.get("trace_id"):
            t = last_request_ts.get(str(ev["trace_id"]), 0.0)
            if 0.0 < ev["ts_fleet"] < t:
                ev["ts_fleet"] = t + _EPS
    events.sort(key=_order_key)


def merge_sources(sources: list[tuple[list[dict], str]], *,
                  skew_correct: bool = True,
                  max_offset_s: float = 300.0) -> list[dict]:
    """Merge per-journal event lists into one causally-ordered stream.
    `sources` is ``[(events, host), ...]``; host "" means the reference
    (manager) clock.  Pure — the unit under test for the skew-regression
    suite.  Each returned event is a copy annotated with `ts_fleet`
    (corrected epoch seconds), `src` (source index), and `host` (when
    the journal has one and the event doesn't)."""
    offsets: dict[str, float] = {}
    if skew_correct:
        for evs, _host in sources:
            offsets.update(estimate_offsets(evs))
    merged: list[dict] = []
    for si, (evs, host) in enumerate(sources):
        off = offsets.get(host, 0.0) if (skew_correct and host) else 0.0
        off = max(-max_offset_s, min(max_offset_s, off))
        last_ts = 0.0  # a ts-less event rides at its predecessor's time
        for ev in evs:
            ts = ev.get("ts")
            if isinstance(ts, (int, float)):
                last_ts = float(ts)
            rec = dict(ev)
            rec["ts_fleet"] = round(last_ts + off, 6)
            rec["src"] = si
            if host and "host" not in rec:
                rec["host"] = host
            merged.append(rec)
    # stable sort: ts-less runs keep their within-journal order
    merged.sort(key=_order_key)
    _apply_happens_before(merged)
    return merged


def load_merged(path: str, *, skew_correct: bool = True,
                max_offset_s: float = 300.0,
                tail_bytes: Optional[int] = None) -> Optional[dict]:
    """Discover + read + merge a fleet dir's journals.  `tail_bytes`
    bounds each journal read (CLI views); None reads whole journals
    (fleet-verify needs complete history).  None when no journal."""
    from . import render

    jpaths = discover_journals(path)
    if not jpaths:
        return None
    sources: list[tuple[list[dict], str]] = []
    truncated = False
    for jp in jpaths:
        if tail_bytes:
            evs, _n, trunc = render._load_events_tail(jp, tail_bytes)
            truncated = truncated or trunc
        else:
            evs = journal_mod.read_journal(jp)
        sources.append((evs, _journal_host(jp)))
    offsets: dict[str, float] = {}
    for evs, _host in sources:
        offsets.update(estimate_offsets(evs))
    events = merge_sources(sources, skew_correct=skew_correct,
                           max_offset_s=max_offset_s)
    return {"journals": jpaths,
            "hosts": [h for _evs, h in sources],
            "offsets": {h: round(o, 4) for h, o in offsets.items()},
            "skew_correct": bool(skew_correct),
            "truncated": truncated,
            "events": events}


def merged_fleet_events(path: str, *, skew_correct: bool = True,
                        max_offset_s: float = 300.0) -> list[dict]:
    """The full skew-corrected merged event stream for `fleet-verify`:
    whole-journal reads (its checks count events over the entire run).
    Empty list when no journal resolves."""
    merged = load_merged(path, skew_correct=skew_correct,
                         max_offset_s=max_offset_s, tail_bytes=None)
    return merged["events"] if merged else []


# -- traces -----------------------------------------------------------------


def collect_traces(events: list[dict]) -> dict[str, dict]:
    """Group trace-carrying events by trace_id: the router's terminal
    `route_trace` (hops + queueing + e2e) joined with every member-side
    `request_trace` (stage decomposition) of the same trace."""
    traces: dict[str, dict] = {}
    for ev in events:
        tid = ev.get("trace_id")
        if not tid:
            continue
        t = traces.setdefault(str(tid), {"trace_id": str(tid),
                                         "route": None, "requests": []})
        if ev.get("kind") == "route_trace":
            t["route"] = ev
        elif ev.get("kind") == "request_trace":
            t["requests"].append(ev)
    return traces


_REQUEST_FIELDS = ("seq", "hop", "admission_ms", "queue_ms",
                   "coalesce_ms", "dispatch_ms", "device_ms", "reply_ms",
                   "e2e_ms", "batch", "engine", "model_version", "error")


def _trace_row(t: dict) -> dict:
    route = t.get("route") or {}
    row = {"trace_id": t["trace_id"],
           "ts": route.get("ts_fleet"),
           "hops": route.get("hops") or [],
           "queue_ms": route.get("queue_ms"),
           "e2e_ms": route.get("e2e_ms"),
           "hedged": bool(route.get("hedged")),
           "outcome": route.get("outcome"),
           "rows": route.get("rows")}
    row["requests"] = [
        {k: ev[k] for k in _REQUEST_FIELDS if k in ev}
        | {"host": ev.get("host", ""), "ts": ev.get("ts_fleet")}
        for ev in t.get("requests", ())]
    return row


# -- incidents --------------------------------------------------------------


def _affected_traces(events: list[dict], t0: float, t1: float) -> list[str]:
    """trace_ids of hedged or non-ok route_traces inside [t0, t1],
    padded — the sampled requests an incident actually touched."""
    out: list[str] = []
    lo, hi = t0 - _TRACE_WINDOW_PAD_S, t1 + _TRACE_WINDOW_PAD_S
    for ev in events:
        if ev.get("kind") != "route_trace" or not ev.get("trace_id"):
            continue
        ts = ev.get("ts_fleet", 0.0)
        if lo <= ts <= hi and (ev.get("hedged")
                               or ev.get("outcome") not in (None, "ok")):
            tid = str(ev["trace_id"])
            if tid not in out:
                out.append(tid)
            if len(out) >= _MAX_AFFECTED_TRACES:
                break
    return out


def _chaos_hint(events: list[dict], root_ts: float) -> Optional[dict]:
    """The latest chaos injection at most _CHAOS_HINT_WINDOW_S before
    the incident root — the injected-fault root-cause pointer."""
    hint = None
    for ev in events:
        if ev.get("kind") != "chaos_inject":
            continue
        ts = ev.get("ts_fleet", 0.0)
        if root_ts - _CHAOS_HINT_WINDOW_S <= ts <= root_ts:
            hint = {"site": ev.get("site"), "action": ev.get("action"),
                    "ts": ts}
    return hint


def reconstruct_incidents(events: list[dict]) -> list[dict]:
    """First-class incident records from a merged, causally-ordered
    stream.  Three episode shapes:

    - **fleet failover** (one per `fleet_failover`): chain lease_expiry
      -> failover -> promotion -> recovery.  Promotion is the matching
      ``fleet_member_swap via="promote"``; recovery is the failed
      member's later `fleet_rejoin` when one exists, else the moment the
      promoted standby restored capacity.  No standby -> the chain stops
      at failover and the incident stays unresolved until a rejoin.
    - **SLO episode**: `slo_alert` firing -> resolved per objective.
    - **degraded swap**: `fleet_swap_degraded` -> that member's
      `fleet_readmit`.

    Each record: {id, kind, root, chain, affected_traces, recovery_s,
    resolved, [suspect_chaos]}."""
    incidents: list[dict] = []

    def _finish(kind: str, root: dict, chain: list[dict],
                resolved: bool) -> None:
        root_ts = root.get("ts", 0.0)
        end_ts = chain[-1]["ts"] if chain else root_ts
        rec = {"id": f"inc-{len(incidents) + 1:03d}",
               "kind": kind, "root": root, "chain": chain,
               "affected_traces": _affected_traces(events, root_ts,
                                                   end_ts),
               "recovery_s": (round(end_ts - root_ts, 3)
                              if resolved else None),
               "resolved": bool(resolved)}
        hint = _chaos_hint(events, root_ts)
        if hint is not None:
            rec["suspect_chaos"] = hint
        incidents.append(rec)

    # fleet failovers
    for i, ev in enumerate(events):
        if ev.get("kind") != "fleet_failover":
            continue
        member = ev.get("member")
        standby = ev.get("standby")
        ts = ev.get("ts_fleet", 0.0)
        root = {"event": "lease_expiry", "ts": ts,
                "member": member, "host": ev.get("host", ""),
                "lease_age_s": ev.get("lease_age_s"),
                "ttl_s": ev.get("ttl_s")}
        chain = [{"step": "lease_expiry", "ts": ts, "member": member,
                  "lease_age_s": ev.get("lease_age_s")},
                 {"step": "failover", "ts": ts, "member": member,
                  "host": ev.get("host", "")}]
        # an explicit promote-swap only exists when the standby needed a
        # generation catch-up; a plain promotion is implicit in the
        # fleet_failover record itself (standby + promoted_in_s fields)
        promo = next(
            (e for e in events[i:]
             if e.get("kind") == "fleet_member_swap"
             and e.get("via") == "promote"
             and standby and e.get("member") == standby), None)
        rejoin = next(
            (e for e in events[i:]
             if e.get("kind") == "fleet_rejoin"
             and member and e.get("member") == member), None)
        resolved = False
        if standby:
            promo_ts = promo.get("ts_fleet", ts) if promo is not None \
                else ts
            step = {"step": "promotion", "ts": promo_ts,
                    "member": standby,
                    "host": (promo.get("host", "") if promo is not None
                             else ev.get("standby_host") or "")}
            if ev.get("promoted_in_s") is not None:
                step["promoted_in_s"] = ev["promoted_in_s"]
            chain.append(step)
            recovery_ts = (rejoin.get("ts_fleet", promo_ts)
                           if rejoin is not None else promo_ts)
            chain.append({"step": "recovery",
                          "ts": max(recovery_ts, promo_ts),
                          "via": ("rejoin" if rejoin is not None
                                  else "promote")})
            resolved = True
        elif rejoin is not None:
            chain.append({"step": "recovery",
                          "ts": rejoin.get("ts_fleet", ts),
                          "via": "rejoin"})
            resolved = True
        _finish("fleet_failover", root, chain, resolved)

    # SLO episodes
    open_alerts: dict[str, dict] = {}
    for ev in events:
        if ev.get("kind") != "slo_alert":
            continue
        obj = str(ev.get("objective", ""))
        if ev.get("state") == "firing":
            open_alerts[obj] = ev
        elif ev.get("state") == "resolved" and obj in open_alerts:
            fired = open_alerts.pop(obj)
            t0 = fired.get("ts_fleet", 0.0)
            root = {"event": "slo_alert", "ts": t0, "objective": obj}
            chain = [{"step": "firing", "ts": t0, "objective": obj},
                     {"step": "resolved",
                      "ts": ev.get("ts_fleet", t0), "objective": obj}]
            _finish("slo_alert", root, chain, True)
    for obj, fired in open_alerts.items():
        t0 = fired.get("ts_fleet", 0.0)
        _finish("slo_alert",
                {"event": "slo_alert", "ts": t0, "objective": obj},
                [{"step": "firing", "ts": t0, "objective": obj}], False)

    # degraded swaps
    for i, ev in enumerate(events):
        if ev.get("kind") != "fleet_swap_degraded":
            continue
        member = ev.get("member")
        t0 = ev.get("ts_fleet", 0.0)
        root = {"event": "fleet_swap_degraded", "ts": t0,
                "member": member, "error": ev.get("error")}
        chain = [{"step": "swap_degraded", "ts": t0, "member": member}]
        readmit = next(
            (e for e in events[i:]
             if e.get("kind") == "fleet_readmit"
             and member and e.get("member") == member), None)
        resolved = readmit is not None
        if resolved:
            chain.append({"step": "readmit",
                          "ts": readmit.get("ts_fleet", t0),
                          "generation": readmit.get("generation")})
        _finish("fleet_swap_degraded", root, chain, resolved)

    incidents.sort(key=lambda r: r["root"].get("ts", 0.0))
    for n, rec in enumerate(incidents):
        rec["id"] = f"inc-{n + 1:03d}"
    return incidents


# -- the timeline view ------------------------------------------------------

# event kinds worth a row in the human timeline (everything else —
# reports, epochs, goodput ticks — is cadence noise at incident scale)
_TIMELINE_KINDS = frozenset((
    "fleet_start", "fleet_failover", "fleet_member_swap", "fleet_rejoin",
    "fleet_readmit", "fleet_swap", "fleet_swap_degraded",
    "fleet_standby_down", "fleet_scale", "fleet_clock_skew",
    "slo_alert", "chaos_inject", "route_trace", "serve_start",
    "serve_stop", "loadtest_report",
))
_MAX_TIMELINE_ROWS = 200
_MAX_TRACE_ROWS = 50


def timeline_summary(path: str, *, trace_id: Optional[str] = None,
                     incidents_only: bool = False,
                     skew_correct: bool = True,
                     max_offset_s: float = 300.0,
                     tail_bytes: int = TAIL_BYTES) -> Optional[dict]:
    """One `shifu-tpu timeline` frame: bounded journal tails only (no
    jax, safe against a live fleet).  None when no journal resolves."""
    merged = load_merged(path, skew_correct=skew_correct,
                         max_offset_s=max_offset_s, tail_bytes=tail_bytes)
    if merged is None:
        return None
    events = merged.pop("events")
    out = dict(merged)
    out["path"] = path
    out["event_count"] = len(events)
    out["incidents"] = reconstruct_incidents(events)
    traces = collect_traces(events)
    if trace_id is not None:
        traces = ({trace_id: traces[trace_id]}
                  if trace_id in traces else {})
    if incidents_only:
        # incident records only: the incidents carry their own
        # affected_traces — keep just those, drop the general sample
        affected = {tid for inc in out["incidents"]
                    for tid in inc.get("affected_traces", ())}
        traces = {k: v for k, v in traces.items() if k in affected}
    rows = [_trace_row(t) for t in traces.values()]
    rows.sort(key=lambda r: r["ts"] or 0.0)
    out["traces"] = rows[-_MAX_TRACE_ROWS:]
    if incidents_only:
        out["timeline"] = []
        return out
    tl = []
    for ev in events:
        if ev.get("kind") not in _TIMELINE_KINDS:
            continue
        if trace_id is not None and ev.get("trace_id") not in (None,
                                                               trace_id):
            continue
        row = {"ts": ev.get("ts_fleet"), "kind": ev.get("kind")}
        for k in ("host", "member", "standby", "via", "generation",
                  "objective", "state", "site", "action", "trace_id",
                  "outcome", "offset_s"):
            if ev.get(k) not in (None, ""):
                row[k] = ev[k]
        tl.append(row)
    out["timeline"] = tl[-_MAX_TIMELINE_ROWS:]
    return out


def _fmt_ts(ts) -> str:
    if not isinstance(ts, (int, float)):
        return "-"
    import datetime
    return datetime.datetime.fromtimestamp(ts).strftime("%H:%M:%S.%f")[:-3]


def render_timeline_text(summary: dict) -> str:
    lines = []
    hosts = [h for h in summary.get("hosts", ()) if h]
    lines.append(
        f"fleet timeline — {len(summary.get('journals', ()))} journal(s)"
        + (f", hosts: {', '.join(sorted(set(hosts)))}" if hosts else "")
        + (", skew-corrected" if summary.get("skew_correct") else
           ", raw clocks")
        + (", tail-truncated" if summary.get("truncated") else ""))
    if summary.get("offsets"):
        offs = ", ".join(f"{h}: {o:+.3f}s"
                         for h, o in sorted(summary["offsets"].items()))
        lines.append(f"  clock offsets  {offs}")
    incidents = summary.get("incidents", ())
    lines.append(f"  incidents      {len(incidents)} "
                 f"({sum(1 for i in incidents if not i['resolved'])} open)")
    for inc in incidents:
        root = inc["root"]
        head = (f"  {inc['id']}  {inc['kind']}"
                f"  root={root.get('event')}@{_fmt_ts(root.get('ts'))}"
                + (f"  member={root['member']}" if root.get("member")
                   else "")
                + (f"  objective={root['objective']}"
                   if root.get("objective") else ""))
        if inc.get("recovery_s") is not None:
            head += f"  recovered_in={inc['recovery_s']:.3f}s"
        elif not inc["resolved"]:
            head += "  OPEN"
        lines.append(head)
        lines.append("    chain: " + " -> ".join(
            s["step"] for s in inc["chain"]))
        if inc.get("suspect_chaos"):
            c = inc["suspect_chaos"]
            lines.append(f"    suspect chaos: {c.get('action')} @ "
                         f"{c.get('site')}")
        if inc.get("affected_traces"):
            lines.append("    affected traces: "
                         + ", ".join(inc["affected_traces"][:6])
                         + (" …" if len(inc["affected_traces"]) > 6
                            else ""))
    traces = summary.get("traces", ())
    if traces:
        lines.append(f"  traces         {len(traces)} sampled")
        for t in traces[-10:]:
            hops = t.get("hops") or []
            hop_s = " + ".join(
                f"{h.get('member', '?')}@{h.get('host', '?')}"
                f"[{h.get('outcome', '?')} {h.get('ms', 0):.1f}ms]"
                for h in hops)
            lines.append(
                f"    {t['trace_id']}  "
                + (f"e2e={t['e2e_ms']:.1f}ms  "
                   if isinstance(t.get("e2e_ms"), (int, float)) else "")
                + (f"queue={t['queue_ms']:.1f}ms  "
                   if isinstance(t.get("queue_ms"), (int, float)) else "")
                + ("HEDGED  " if t.get("hedged") else "")
                + (f"hops: {hop_s}" if hop_s else "no hops"))
    tl = summary.get("timeline", ())
    if tl:
        lines.append(f"  events         last {len(tl)}")
        for row in tl:
            extra = " ".join(f"{k}={v}" for k, v in row.items()
                             if k not in ("ts", "kind"))
            lines.append(f"    {_fmt_ts(row.get('ts'))}  "
                         f"{row['kind']:<20} {extra}")
    return "\n".join(lines)
