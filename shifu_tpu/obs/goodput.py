"""Goodput ledger: classify every epoch's wall time into named buckets.

Raw step time says a PR made "the job" slower; it cannot say WHICH part.
Pod-scale TPU practice (MLPerf-0.6 on v3 pods, arXiv:1909.09756; the
TensorFlow system paper, arXiv:1605.08695) optimizes *utilization* —
what fraction of the wall the chips spent on model math — not wall time
alone.  This module is that accounting for shifu_tpu:

- **Buckets** (`BUCKETS`): `compile` (XLA compiles, reported by
  obs/introspect.py), `input` (host-side input wait), `step` (device
  step/scan dispatch-to-done, compile time subtracted), `checkpoint`
  (save), `restore` (mid-run restore/recovery — chaos drills land
  here), `eval` (validation pass), `other` (the unclassified residue:
  tier setup, shuffles, journal flushes).  Buckets sum to the epoch
  wall by construction (`other` absorbs the remainder).
- **Goodput fraction** = step seconds / wall: the fraction of the epoch
  the devices spent advancing the model.
- **MFU** = achieved FLOP/s ÷ the platform's peak.  Achieved FLOPs come
  from the XLA `cost_analysis()` of the instrumented step programs
  (per-dispatch FLOPs x dispatches, accumulated via `note_flops`); the
  peak comes from `PEAK_BF16_TFLOPS` below, overridable with
  `SHIFU_TPU_PEAK_TFLOPS` (the escape hatch for new parts and for CPU
  tests).  On backends where cost capture is off (see introspect.py)
  MFU is null, never guessed.

Every epoch journals ONE `goodput` event and feeds the
`goodput_bucket_seconds_total{bucket=...}` counter plus the
`goodput_fraction` / `mfu` gauges, so `shifu-tpu profile`,
`shifu-tpu status`, bench.py, and tools/perf_gate.py all read the same
record (docs/PERF.md "Goodput & MFU").
"""

from __future__ import annotations

import os
import threading
from typing import Optional

# peak dense bf16 TFLOP/s per chip by device-kind substring (public
# specs) — THE per-platform table the MFU denominator comes from
# (bench.py imports this; one table, one truth).  First match wins, so
# "v5p" must precede "v5".
PEAK_BF16_TFLOPS: tuple[tuple[str, float], ...] = (
    ("v6", 918.0),       # Trillium / v6e
    ("v5p", 459.0),
    ("v5", 197.0),       # v5e / "TPU v5 lite"
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)

ENV_PEAK_TFLOPS = "SHIFU_TPU_PEAK_TFLOPS"

BUCKETS = ("compile", "input", "step", "checkpoint", "restore", "eval",
           "other")


def peak_tflops(device_kind: Optional[str] = None) -> Optional[float]:
    """Peak bf16 TFLOP/s for a device kind (current backend's device 0
    when omitted); SHIFU_TPU_PEAK_TFLOPS overrides the table; None when
    the platform is unknown (CPU, new parts) — MFU is then null."""
    env = os.environ.get(ENV_PEAK_TFLOPS)
    if env:
        try:
            return float(env)
        except ValueError:
            pass  # a typo'd override must not crash telemetry
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:
            return None
    kind = str(device_kind).lower()
    for sub, peak in PEAK_BF16_TFLOPS:
        if sub in kind:
            return peak
    return None


class GoodputLedger:
    """One epoch's wall-time classification.  Threads may `add` /
    `add_flops` concurrently (the prefetch producer compiles its
    device_put path; checkpoint saves may run from hooks)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._seconds: dict[str, float] = {}
        self._flops = 0.0
        self._compiles = 0

    def add(self, bucket: str, seconds: float) -> None:
        # `not (seconds > 0)` rather than `<= 0`: it also rejects NaN (a
        # clock hiccup upstream must not poison the whole ledger, the
        # bucket counters, and every artifact field derived from them)
        if not (seconds > 0) or seconds == float("inf"):
            return
        with self._lock:
            self._seconds[bucket] = self._seconds.get(bucket, 0.0) + seconds
            if bucket == "compile":
                self._compiles += 1

    def add_flops(self, flops: float) -> None:
        if flops > 0 and flops != float("inf"):  # NaN > 0 is False
            with self._lock:
                self._flops += float(flops)

    def summary(self, wall_s: float) -> dict:
        """The goodput record for an epoch of `wall_s` seconds.  Compile
        time happens INSIDE the timed step/eval dispatches (a compiling
        call's wall includes its compile), so it is subtracted from
        `step` first, then `eval` — the buckets stay disjoint and sum to
        the wall, with `other` absorbing the unclassified residue."""
        with self._lock:
            b = dict(self._seconds)
            flops = self._flops
            compiles = self._compiles
        compile_s = b.get("compile", 0.0)
        overlap = min(compile_s, b.get("step", 0.0))
        b["step"] = b.get("step", 0.0) - overlap
        b["eval"] = max(b.get("eval", 0.0) - (compile_s - overlap), 0.0)
        buckets = {k: round(b.get(k, 0.0), 6) for k in BUCKETS
                   if k != "other"}
        classified = sum(buckets.values())
        buckets["other"] = round(max(wall_s - classified, 0.0), 6)
        out = {
            "wall_s": round(wall_s, 6),
            "buckets": buckets,
            "goodput_fraction": round(buckets["step"] / wall_s, 4)
            if wall_s > 0 else None,
            "compiles": compiles,
        }
        peak = peak_tflops()
        achieved = (flops / wall_s / 1e12) if wall_s > 0 and flops > 0 \
            else None
        # significant digits, not fixed decimals: CPU-scale TFLOP/s (and
        # the MFU they imply) are legitimately tiny and must not round
        # to a meaningless 0.0
        out["achieved_tflops"] = (float(f"{achieved:.6g}")
                                  if achieved is not None else None)
        out["peak_tflops"] = peak
        out["mfu"] = (float(f"{achieved / peak:.6g}")
                      if achieved is not None and peak else None)
        return out


_lock = threading.Lock()
_current: Optional[GoodputLedger] = None


def begin_epoch() -> GoodputLedger:
    """Open a fresh ledger as the process's active epoch ledger."""
    global _current
    with _lock:
        _current = GoodputLedger()
        return _current


def current() -> Optional[GoodputLedger]:
    return _current


def note(bucket: str, seconds: float) -> None:
    """Credit `seconds` to `bucket` on the active ledger; no-op between
    epochs — instrumented call sites (checkpoint saves, compiles) never
    check whether a ledger is open.  Never raises."""
    led = _current
    if led is not None:
        try:
            led.add(bucket, seconds)
        except Exception:
            pass


def note_flops(flops: float) -> None:
    led = _current
    if led is not None:
        try:
            led.add_flops(flops)
        except Exception:
            pass


def end_epoch(epoch: int, wall_s: float) -> Optional[dict]:
    """Close the active ledger: journal the `goodput` event, feed the
    registry, return the record (None when no ledger is open)."""
    global _current
    with _lock:
        led = _current
        _current = None
    if led is None:
        return None
    try:
        from . import _sinks, metrics as metrics_mod
        rec = led.summary(wall_s)
        rec["epoch"] = int(epoch)
        sec = metrics_mod.counter(
            "goodput_bucket_seconds_total",
            "epoch wall seconds by goodput bucket (docs/PERF.md)")
        for bucket, s in rec["buckets"].items():
            sec.inc(s, bucket=bucket)
        if rec["goodput_fraction"] is not None:
            metrics_mod.gauge(
                "goodput_fraction",
                "last epoch's device-step fraction of wall time",
            ).set(rec["goodput_fraction"])
        if rec["mfu"] is not None:
            metrics_mod.gauge(
                "mfu", "last epoch's model FLOP utilization").set(rec["mfu"])
        _sinks.event("goodput", **rec)
        return rec
    except Exception:
        return None  # telemetry must never fail the epoch it measures


def reset_for_tests() -> None:
    """Drop any ledger left open by an aborted epoch (obs.reset_for_tests
    calls this — a mid-epoch exception must not leak state across
    tests)."""
    global _current
    with _lock:
        _current = None
