"""Unified telemetry subsystem: metrics registry, run journal, span tracing,
cross-host aggregation.

The single observability layer every subsystem writes into (ISSUE 1),
replacing the siloed successors of the reference's 4-hop metric funnel
(SURVEY.md section 5.5).  Three pillars:

- **metrics** (obs/metrics.py): process-local counters / gauges /
  histograms with label sets, exported as a Prometheus text scrape file.
- **journal** (obs/journal.py): append-only JSONL event stream — run
  metadata, epochs, checkpoints, restarts, cache hits, spans — written
  through data/fsio so gs:// / mock:// job dirs work like the board.
- **spans** (obs/spans.py): `with obs.span("epoch/eval"):` nested phase
  timing feeding both of the above.

Sinks are configured once per process (`configure(metrics_dir)`, or lazily
from SHIFU_TPU_METRICS_DIR via `configure_from_env`); until then the
registry still collects in memory and `event()` is a no-op, so
instrumented call sites never need to know whether telemetry is on.
`obs/aggregate.py` adds the cross-host skew table (one allgather per
epoch); `obs/render.py` renders a job's telemetry for `shifu-tpu metrics`
and `shifu-tpu profile`.  On top of the pillars, ISSUE 3 adds
`obs/introspect.py` (per-compiled-program XLA cost/memory capture,
`xla_compile` events) and `obs/goodput.py` (the per-epoch goodput
ledger: wall time classified into compile / input / step / checkpoint /
restore / eval / other buckets, with MFU against a per-platform peak
table) — docs/PERF.md "Goodput & MFU".  ISSUE 6 opens the `step` bucket
itself: `obs/devprof.py` + `obs/tracefmt.py` (the device flight
recorder — per-kernel device-time rollups from scheduled jax.profiler
windows, roofline attribution, HBM watermarks, and an anomaly-triggered
one-shot trace), rendered by `shifu-tpu trace`.
"""

from __future__ import annotations

from . import (aggregate, devprof, drift, goodput,  # noqa: F401
               introspect, journal, metrics, render, sketch, slo,
               spans, tracefmt, timeline, tracing)
from ._sinks import (ENV_METRICS_DIR, SCRAPE_FILE, configure,  # noqa: F401
                     configure_from_env, event, flush, get_journal,
                     metrics_dir, reset_for_tests, resolve_metrics_dir,
                     set_journal, shutdown)
from .journal import RunJournal, read_journal, tail_journal  # noqa: F401
from .metrics import (MetricsRegistry, counter, default_registry,  # noqa: F401
                      gauge, histogram)
from .spans import current_path, span  # noqa: F401
