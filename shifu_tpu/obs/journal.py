"""Structured run journal: an append-only JSONL event stream.

The machine-readable record of a run — run metadata, epoch records,
checkpoint saves/restores, supervisor restarts, cache hits, export/score
events, spans — one JSON object per line.  Successor of the reference's
Java-serialized TrainingIntermediateResult znodes (SURVEY.md section 5.5
flagged Java serialization as a quirk): grep-able, tail-able, no runtime
needed to read it.

Remote (gs:// hdfs:// mock://) journal paths write through data/fsio like
the console board does: object stores have no append, so the journal keeps
its lines in memory and rewrites the object on a batched cadence
(`flush_every` events + explicit flush/close), with a retained-line cap so
the rewrite cost stays bounded on long runs.  Local paths append with a
line-buffered handle — true O(1) appends.

`tail_journal` follows a journal (local stream / remote poll) yielding
decoded events — the tail_board of the structured stream.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Iterator, Optional

JOURNAL_FILE = "journal.jsonl"

# remote journals rewrite the whole object: bound the retained lines so an
# epochs=50k run cannot turn every flush into a multi-MB PUT
DEFAULT_MAX_REMOTE_LINES = 20_000


def _is_remote(path: Optional[str]) -> bool:
    if not path:
        return False
    try:
        from ..data import fsio
        return fsio.is_remote(path)
    except Exception:
        return False


def _clean(v):
    """NaN/Inf are not valid strict JSON; journal consumers get null."""
    if isinstance(v, float) and not math.isfinite(v):
        return None
    if isinstance(v, dict):
        return {k: _clean(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_clean(x) for x in v]
    return v


class RunJournal:
    """One journal stream.  `path=None` keeps events in memory only
    (`records`) — the bench's mode, where the breakdown is read back
    programmatically rather than from disk."""

    def __init__(self, path: Optional[str], flush_every: int = 16,
                 max_remote_lines: int = DEFAULT_MAX_REMOTE_LINES):
        self.path = path
        self.records: list[dict] = []  # memory mode retains decoded events
        self._seq = 0
        self._lock = threading.RLock()
        self._fh = None
        self._remote = _is_remote(path)
        self._lines: list[str] = []
        self._pending = 0
        self._flush_every = max(1, flush_every)
        self._max_remote_lines = max_remote_lines
        self._truncated = 0
        if path and not self._remote:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._fh = open(path, "a", buffering=1)
        elif self._remote:
            # seed from the existing object: remote flushes rewrite the
            # whole object from THIS writer's lines, so a restarted attempt
            # opening fresh would erase the previous attempt's history —
            # and restarting seq at 1 would make seq-tracking tails
            # (tail_journal --follow) silently discard the new attempt's
            # events.  One read at open keeps both monotonic.
            try:
                for rec in read_journal(path):
                    if rec.get("kind") == "journal_truncated":
                        # absorb the prior writer's drop count instead of
                        # retaining its marker as an ordinary line (the
                        # flush re-synthesizes ONE cumulative marker)
                        try:
                            self._truncated += int(rec.get("dropped") or 0)
                        except (TypeError, ValueError):
                            pass
                        continue
                    self._lines.append(json.dumps(rec, allow_nan=False))
                    try:
                        self._seq = max(self._seq, int(rec.get("seq") or 0))
                    except (TypeError, ValueError):
                        pass
                if len(self._lines) > self._max_remote_lines:
                    drop = len(self._lines) - self._max_remote_lines
                    del self._lines[:drop]
                    self._truncated += drop
            except FileNotFoundError:
                pass
            except Exception:
                pass  # unreadable prior object: start fresh, never fail

    def event(self, kind: str, **fields) -> dict:
        """Append one event; returns the record written (post-cleaning)."""
        with self._lock:
            self._seq += 1
            rec = {"ts": round(time.time(), 3), "seq": self._seq,
                   "kind": kind}
            rec.update({k: _clean(v) for k, v in fields.items()})
            if self.path is None:
                self.records.append(rec)
                return rec
            line = json.dumps(rec, allow_nan=False)
            if self._fh is not None:
                self._fh.write(line + "\n")  # line-buffered: flushed per line
            else:
                self._lines.append(line)
                if len(self._lines) > self._max_remote_lines:
                    drop = len(self._lines) - self._max_remote_lines
                    del self._lines[:drop]
                    self._truncated += drop
                self._pending += 1
                if self._pending >= self._flush_every:
                    self._flush_remote_locked()
            return rec

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    # chaos site "journal.flush": models the sink's disk /
                    # object store failing — the journal is observability,
                    # so the failure is absorbed, never the job's
                    from .. import chaos
                    chaos.maybe_fail("journal.flush", path=self.path)
                    self._fh.flush()
                except Exception:
                    pass
            elif self._remote and self._pending:
                self._flush_remote_locked()

    def _flush_remote_locked(self) -> None:
        # best-effort whole-object rewrite (the board's contract): a sink
        # failure must never fail the job the journal describes
        try:
            from .. import chaos
            chaos.maybe_fail("journal.flush", path=self.path)
            from ..data import fsio
            lines = self._lines
            if self._truncated:
                head = json.dumps({"ts": round(time.time(), 3), "seq": 0,
                                   "kind": "journal_truncated",
                                   "dropped": self._truncated})
                lines = [head] + lines
            fsio.write_bytes(self.path, ("\n".join(lines) + "\n").encode())
            self._pending = 0
        except Exception:
            pass

    def close(self) -> None:
        with self._lock:
            self.flush()
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def read_journal(path: str) -> list[dict]:
    """Decode every complete event of a journal (local or remote); corrupt
    or partial trailing lines are skipped, not fatal — a crash mid-append
    must not make the whole record unreadable."""
    if _is_remote(path):
        from ..data import fsio
        text = fsio.read_bytes(path).decode("utf-8", "replace")
    else:
        with open(path) as f:
            text = f.read()
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def tail_journal(path: str, from_start: bool = True,
                 poll_seconds: float = 0.2) -> Iterator[dict]:
    """Generator yielding journal events as they appear — the structured
    sibling of launcher.console.tail_board.  Local journals stream from the
    file handle; remote journals poll the object through fsio and yield the
    delta.  Stops when the journal is removed after having existed."""
    if _is_remote(path):
        yield from _tail_remote(path, from_start, poll_seconds)
        return
    while not os.path.exists(path):
        time.sleep(0.1)
    with open(path, "r") as f:
        if not from_start:
            f.seek(0, os.SEEK_END)
        buf = ""
        while True:
            chunk = f.readline()
            if chunk:
                buf += chunk
                if not buf.endswith("\n"):
                    continue  # partial line: complete it next read
                line, buf = buf, ""
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    yield rec
            else:
                if not os.path.exists(path):
                    return
                time.sleep(poll_seconds)


def _tail_remote(path: str, from_start: bool,
                 poll_seconds: float) -> Iterator[dict]:
    """Delta-tracking by `seq`, NOT line index: once the retained-line cap
    engages, every rewrite drops old lines (and prepends a truncation
    marker), so the object's line count plateaus and an index-based tail
    would stall forever / skip shifted lines.  seq is monotonic per
    journal, so new events are exactly those above the high-water mark."""
    from ..data import fsio

    last_seq = -1.0
    first = True
    missing_grace = True
    while True:
        try:
            text = fsio.read_bytes(path).decode("utf-8", "replace")
            missing_grace = False
        except FileNotFoundError:
            if missing_grace:
                time.sleep(poll_seconds)
                continue
            return
        except Exception:
            time.sleep(poll_seconds)
            continue
        # read only up to the last newline: a half-written final line
        # completes next poll (same contract as tail_board)
        complete = text[: text.rfind("\n") + 1]
        recs = []
        for line in complete.splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                recs.append(rec)
        if first and not from_start:
            last_seq = max((float(r.get("seq") or 0) for r in recs),
                           default=-1.0)
        first = False
        for rec in recs:
            seq = rec.get("seq")
            if isinstance(seq, (int, float)):
                if seq <= last_seq:
                    continue
                last_seq = max(last_seq, float(seq))
            yield rec
        time.sleep(poll_seconds)
