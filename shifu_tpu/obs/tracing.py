"""Distributed trace context for the serving fleet (docs/OBSERVABILITY.md
"Distributed tracing").

One request = one trace.  A compact trace id is minted at router ingress
(or accepted from the client's wire frame) and carried through every hop:
router -> member wire protocol (runtime/serve_wire.py version-2 frames)
-> the daemon's lifecycle stage chain (runtime/serve.py), so a hedged
retry becomes TWO `hop` spans under ONE trace — attempt index, member,
host, and outcome each — and the member-side `request_trace` events join
back to the router's `route_trace` by trace id.

The context is deliberately tiny and flat (no baggage, no parent-span
tree): 16 hex chars of id + an attempt ordinal + a sampled bit, 20 bytes
on the wire.  Sampling is decided ONCE at ingress; members force-sample
any request that arrives with `sampled=True` so a trace's hops never go
dark mid-path, and at `trace_sample=0` no context is ever minted — the
untraced hot path carries a single `is None` check.
"""

from __future__ import annotations

import dataclasses
import os
import struct
from typing import Optional

# trace extension block of a version-2 wire frame (serve_wire.py): the
# fixed header is unchanged; ver=2 means these 20 bytes sit between the
# header and the payload.  trace_id as raw ascii-hex (16 bytes), attempt
# u8, sampled u8, reserved u16 for a future flags word.
WIRE_EXT = struct.Struct("<16sBBH")
WIRE_EXT_BYTES = WIRE_EXT.size

_ID_LEN = 16  # hex chars (64 bits of id space)


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One request's trace identity as it crosses a hop boundary."""

    trace_id: str        # 16 lowercase hex chars
    attempt: int = 0     # hop ordinal at the router (0 primary, 1 hedge)
    sampled: bool = True  # journal this trace's spans?

    def pack(self) -> bytes:
        """The 20-byte wire extension of a version-2 frame."""
        return WIRE_EXT.pack(self.trace_id.encode("ascii"),
                             min(max(self.attempt, 0), 255),
                             1 if self.sampled else 0, 0)

    def with_attempt(self, attempt: int) -> "TraceContext":
        return dataclasses.replace(self, attempt=attempt)


def mint() -> TraceContext:
    """A fresh sampled trace context (router-ingress minting)."""
    return TraceContext(trace_id=os.urandom(8).hex())


def unpack(raw: bytes) -> Optional[TraceContext]:
    """Wire extension bytes -> TraceContext; a malformed block is None
    (the request still serves — tracing is telemetry, never a gate)."""
    if len(raw) != WIRE_EXT_BYTES:
        return None
    try:
        tid, attempt, sampled, _reserved = WIRE_EXT.unpack(raw)
        trace_id = tid.decode("ascii")
    except (struct.error, UnicodeDecodeError):
        return None
    if len(trace_id) != _ID_LEN or not all(
            c in "0123456789abcdef" for c in trace_id):
        return None
    return TraceContext(trace_id=trace_id, attempt=int(attempt),
                        sampled=bool(sampled))
