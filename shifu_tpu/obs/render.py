"""Render a job's telemetry (journal + scrape file) for the CLI.

`shifu-tpu metrics <dir>` lands here: `<dir>` may be a job dir (telemetry
lives under `<dir>/telemetry/`), the telemetry dir itself, or a direct
journal path — local or remote through data/fsio.  Output is a compact
human summary (run metadata, epoch table, event counts, key counters);
`--json` mode emits one machine-readable dict instead.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Optional

from . import _sinks, journal as journal_mod

TELEMETRY_DIRNAME = "telemetry"


def _exists(path: str) -> bool:
    try:
        from ..data import fsio
        if fsio.is_remote(path):
            try:
                fsio.file_info(path)
                return True
            except FileNotFoundError:
                return False
        return os.path.exists(path)
    except Exception:
        return os.path.exists(path)


def find_journal(path: str) -> Optional[str]:
    """Resolve a journal path from a job dir / telemetry dir / file path."""
    from ..data import fsio

    if path.endswith(".jsonl"):
        return path if _exists(path) else None
    candidates = (
        fsio.join(path, TELEMETRY_DIRNAME, journal_mod.JOURNAL_FILE),
        fsio.join(path, journal_mod.JOURNAL_FILE),
    )
    for c in candidates:
        if _exists(c):
            return c
    return None


def _read_scrape(journal_path: str) -> Optional[str]:
    # a bare relative journal filename (cwd = the telemetry dir) must
    # resolve to ITS directory, not to "/metrics.prom"
    if "/" in journal_path:
        prom = journal_path.rsplit("/", 1)[0] + "/" + _sinks.SCRAPE_FILE
    else:
        prom = _sinks.SCRAPE_FILE
    if not _exists(prom):
        return None
    try:
        from ..data import fsio
        if fsio.is_remote(prom):
            return fsio.read_bytes(prom).decode("utf-8", "replace")
        with open(prom) as f:
            return f.read()
    except Exception:
        return None


def parse_scrape_totals(text: str) -> dict[str, float]:
    """Per-metric totals from Prometheus text: counters/gauges sum across
    label sets; histograms report their `_count` total.  Enough for the
    summary view without a real Prometheus parser."""
    totals: dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = re.match(r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{.*\})?\s+(\S+)$", line)
        if not m:
            continue
        name, _labels, value = m.groups()
        if name.endswith("_bucket") or name.endswith("_sum"):
            continue
        try:
            v = float(value)
        except ValueError:
            continue
        key = name[:-6] if name.endswith("_count") else name
        totals[key] = totals.get(key, 0.0) + v
    return totals


def parse_scrape_histograms(text: str) -> dict:
    """Histogram series from Prometheus text: {metric_name: {label_key:
    {"bounds": [...], "counts": [per-bucket incl +Inf], "sum", "count"}}}
    where label_key is the sorted 'k=v;k=v' spelling WITHOUT `le`.  Enough
    for stage/latency percentile math (`quantile_from_counts`) from the
    scrape file alone — no live process needed."""
    series: dict = {}
    line_re = re.compile(
        r"^([A-Za-z_:][A-Za-z0-9_:]*)_(bucket|sum|count)(\{.*\})?\s+(\S+)$")
    pair_re = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
    for line in text.splitlines():
        m = line_re.match(line)
        if not m:
            continue
        name, part, labels_s, value_s = m.groups()
        labels = dict(pair_re.findall(labels_s or ""))
        le = labels.pop("le", None)
        key = ";".join(f"{k}={v}" for k, v in sorted(labels.items()))
        try:
            value = float(value_s)
        except ValueError:
            continue
        s = series.setdefault(name, {}).setdefault(
            key, {"le": {}, "sum": 0.0, "count": 0})
        if part == "bucket" and le is not None:
            bound = float("inf") if le == "+Inf" else float(le)
            s["le"][bound] = value
        elif part == "sum":
            s["sum"] = value
        elif part == "count":
            s["count"] = int(value)
    out: dict = {}
    for name, by_key in series.items():
        for key, s in by_key.items():
            if not s["le"]:
                continue  # a _sum/_count pair without buckets (summary)
            bounds = sorted(b for b in s["le"] if b != float("inf"))
            cum = [s["le"][b] for b in bounds]
            # a series whose only bucket is +Inf (legal exposition) has
            # no finite bounds: everything rides the +Inf count
            counts = ([int(cum[0])] + [int(cum[i] - cum[i - 1])
                                       for i in range(1, len(cum))]
                      if cum else [])
            counts.append(max(int(s["count"]) - int(cum[-1] if cum else 0),
                              0))  # +Inf bucket
            out.setdefault(name, {})[key] = {
                "bounds": bounds, "counts": counts,
                "sum": s["sum"], "count": s["count"]}
    return out


def _load_events(jpath: str) -> list[dict]:
    """One journal's events, with the supervisor's remote-dir sidecar
    journal merged when present (two writers on one remote object would
    erase each other — see obs/_sinks.configure); sort restores one
    timeline."""
    events = journal_mod.read_journal(jpath)
    sidecar = (jpath.rsplit("/", 1)[0] + "/journal-supervisor.jsonl"
               if "/" in jpath
               else os.path.join(os.path.dirname(jpath),
                                 "journal-supervisor.jsonl"))
    if sidecar != jpath and _exists(sidecar):
        try:
            events = sorted(events + journal_mod.read_journal(sidecar),
                            key=lambda r: (r.get("ts") or 0,
                                           r.get("seq") or 0))
        except Exception:
            pass
    return events


def summarize(path: str) -> Optional[dict]:
    """The telemetry summary dict for a job/telemetry dir, or None when no
    journal is found."""
    jpath = find_journal(path)
    if jpath is None:
        return None
    events = _load_events(jpath)
    kinds: dict[str, int] = {}
    epochs: list[dict] = []
    run: dict = {}
    spans: dict[str, float] = {}
    for rec in events:
        kind = str(rec.get("kind", "?"))
        kinds[kind] = kinds.get(kind, 0) + 1
        if kind == "epoch":
            epochs.append(rec)
        elif kind in ("run_start", "train_start") and not run:
            run = {k: v for k, v in rec.items()
                   if k not in ("seq", "kind")}
        elif kind == "span":
            name = str(rec.get("span", "?"))
            spans[name] = spans.get(name, 0.0) + float(rec.get("dur_s") or 0)
    out = {
        "journal": jpath,
        "events": len(events),
        "event_kinds": dict(sorted(kinds.items())),
        "run": run,
        "epochs": [
            {k: e.get(k) for k in ("epoch", "train_error", "valid_error",
                                   "valid_auc", "epoch_time", "valid_time")}
            for e in epochs],
        "span_totals_s": {k: round(v, 4)
                          for k, v in sorted(spans.items())},
    }
    if events:
        last = events[-1]
        out["last_event"] = {"kind": last.get("kind"), "ts": last.get("ts")}
    scrape = _read_scrape(jpath)
    if scrape is not None:
        out["metrics"] = {k: v for k, v in
                          sorted(parse_scrape_totals(scrape).items())}
    return out


def render_text(summary: dict) -> str:
    """Human-readable rendering of `summarize`'s dict."""
    lines = [f"journal: {summary['journal']} ({summary['events']} events)"]
    run = summary.get("run") or {}
    if run:
        desc = " ".join(f"{k}={v}" for k, v in run.items()
                        if k not in ("ts",) and v is not None)
        lines.append(f"run: {desc}")
    kinds = summary.get("event_kinds") or {}
    if kinds:
        lines.append("events: " + " ".join(f"{k}={v}"
                                           for k, v in kinds.items()))
    epochs = summary.get("epochs") or []
    if epochs:
        lines.append(f"{'epoch':>5} {'train_err':>10} {'valid_err':>10} "
                     f"{'auc':>7} {'time_s':>8} {'valid_s':>8}")
        for e in epochs:
            def f(v, spec):
                return format(v, spec) if isinstance(v, (int, float)) \
                    else "-"
            lines.append(f"{f(e.get('epoch'), 'd'):>5} "
                         f"{f(e.get('train_error'), '.6f'):>10} "
                         f"{f(e.get('valid_error'), '.6f'):>10} "
                         f"{f(e.get('valid_auc'), '.4f'):>7} "
                         f"{f(e.get('epoch_time'), '.2f'):>8} "
                         f"{f(e.get('valid_time'), '.2f'):>8}")
    spans = summary.get("span_totals_s") or {}
    if spans:
        lines.append("span totals (s): " + " ".join(
            f"{k}={v:g}" for k, v in spans.items()))
    metrics = summary.get("metrics")
    if metrics:
        lines.append(f"metrics ({len(metrics)} series totals):")
        for k, v in metrics.items():
            lines.append(f"  {k} {v:g}")
    last = summary.get("last_event")
    if last:
        lines.append(f"last event: {last.get('kind')} at ts "
                     f"{last.get('ts')}")
    return "\n".join(lines)


# -- `shifu-tpu profile`: the goodput / XLA-cost view ----------------------

def profile_summary(path: str) -> Optional[dict]:
    """The performance-profile dict for a job/telemetry dir: per-epoch
    goodput bucket records, compiled functions aggregated by cost, and
    the recovery tax (restore / fallback / preemption-grace seconds) —
    assembled purely from `goodput` / `xla_compile` / checkpoint journal
    events (docs/PERF.md "Goodput & MFU").  None when no journal."""
    jpath = find_journal(path)
    if jpath is None:
        return None
    events = _load_events(jpath)

    epochs: list[dict] = []
    compiles: dict[str, dict] = {}
    overlap_epochs: list[dict] = []
    ingests: list[dict] = []
    profiles: list[dict] = []
    hbm_peak = 0
    hbm_last: Optional[dict] = None
    anomalies = 0
    trace_fallbacks = 0
    tier_last: Optional[dict] = None
    tier_reports = 0
    dedup_last: Optional[dict] = None
    offload_fallbacks = 0
    aot_loads: list[dict] = []
    aot_fallbacks: list[dict] = []
    aot_packs: list[dict] = []
    prewarm_last: Optional[dict] = None
    skew_last: Optional[dict] = None
    skew_count = 0
    digest_disagreements = 0
    dcn_last: Optional[dict] = None
    dcn_saved_b = 0
    dcn_sync_saved_b = 0
    recovery = {"restore_s": 0.0, "restores": 0, "fallbacks": 0,
                "cache_fallbacks": 0, "preemption_graces": 0, "resumes": 0}
    for rec in events:
        kind = rec.get("kind")
        if kind == "goodput":
            epochs.append({k: rec.get(k) for k in
                           ("epoch", "wall_s", "buckets", "goodput_fraction",
                            "mfu", "achieved_tflops", "peak_tflops",
                            "compiles")})
        elif kind == "overlap_report":
            overlap_epochs.append({k: rec.get(k) for k in
                                   ("epoch", "tier", "overlap",
                                    "prefetch_depth", "input_exposed_s",
                                    "input_production_s", "input_hidden_s",
                                    "eval_s", "prefetched_chunks",
                                    "overlap_efficiency", "order_digest",
                                    "resident_format")})
        elif kind == "xla_compile":
            fn = str(rec.get("fn", "?"))
            c = compiles.setdefault(fn, {"compiles": 0, "compile_s": 0.0,
                                         "cache": {}})
            c["compiles"] += 1
            try:
                c["compile_s"] = round(
                    c["compile_s"] + float(rec.get("compile_s") or 0), 6)
            except (TypeError, ValueError):
                pass
            cache = str(rec.get("cache") or "off")
            c["cache"][cache] = c["cache"].get(cache, 0) + 1
            for k in ("flops", "bytes_accessed", "peak_bytes"):
                if rec.get(k) is not None:
                    c[k] = rec[k]  # last capture wins (latest signature)
        elif kind == "checkpoint_restore":
            recovery["restores"] += 1
            try:
                recovery["restore_s"] = round(
                    recovery["restore_s"] + float(rec.get("dur_s") or 0), 6)
            except (TypeError, ValueError):
                pass
        elif kind == "ingest_report":
            # the cold/warm ingest record (docs/OBSERVABILITY.md): pool
            # shape, phase split, which cache tier served (per_file capped
            # at the source — keep the rollup fields only here)
            ingests.append({k: rec.get(k) for k in
                            ("mode", "files", "pool_width", "wall_s",
                             "rows", "parse_s", "inflate_s", "write_s",
                             "source_bytes", "host_index", "tiers")})
        elif kind == "checkpoint_fallback":
            recovery["fallbacks"] += 1
        elif kind == "cache_fallback":
            recovery["cache_fallbacks"] += 1
        elif kind == "preemption_grace":
            recovery["preemption_graces"] += 1
        elif kind == "train_resume":
            recovery["resumes"] += 1
        elif kind == "device_profile":
            profiles.append(rec)
        elif kind == "hbm_watermark":
            hbm_last = rec
            try:
                hbm_peak = max(hbm_peak, int(rec.get("peak_bytes") or 0))
            except (TypeError, ValueError):
                pass
        elif kind == "anomaly":
            anomalies += 1
        elif kind == "trace_fallback":
            trace_fallbacks += 1
        elif kind == "embed_tier_report":
            tier_last = rec
            tier_reports += 1
        elif kind == "embed_dedup_report":
            dedup_last = rec
        elif kind == "embed_offload_fallback":
            offload_fallbacks += 1
        elif kind == "aot_load":
            aot_loads.append(rec)
        elif kind == "aot_fallback":
            aot_fallbacks.append(rec)
        elif kind == "aot_pack":
            aot_packs.append(rec)
        elif kind == "model_prewarm":
            prewarm_last = rec
        elif kind == "host_skew":
            skew_last = rec
            skew_count += 1
            if rec.get("order_digest_agree") is False:
                digest_disagreements += 1
            if rec.get("shard_digest_agree") is False:
                digest_disagreements += 1
        elif kind == "dcn_placement":
            dcn_last = rec
            try:
                dcn_saved_b += int(rec.get("input_dcn_saved_bytes") or 0)
                dcn_sync_saved_b += int(
                    rec.get("dcn_sync_saved_bytes") or 0)
            except (TypeError, ValueError):
                pass

    totals: dict[str, float] = {}
    fracs, mfus = [], []
    for e in epochs:
        for b, s in (e.get("buckets") or {}).items():
            if isinstance(s, (int, float)):
                totals[b] = round(totals.get(b, 0.0) + s, 6)
        if isinstance(e.get("goodput_fraction"), (int, float)):
            fracs.append(e["goodput_fraction"])
        if isinstance(e.get("mfu"), (int, float)):
            mfus.append(e["mfu"])
    # overlap engine rollup (docs/PERF.md "Overlap engine"): how much of
    # the epochs' host input work ran behind device compute
    hidden = sum(e["input_hidden_s"] for e in overlap_epochs
                 if isinstance(e.get("input_hidden_s"), (int, float)))
    exposed = sum(e["input_exposed_s"] for e in overlap_epochs
                  if isinstance(e.get("input_exposed_s"), (int, float)))
    overlap = None
    if overlap_epochs:
        overlap = {
            "epochs": overlap_epochs,
            "input_hidden_s": round(hidden, 6),
            "input_exposed_s": round(exposed, 6),
            "efficiency": (round(hidden / (hidden + exposed), 4)
                           if hidden + exposed > 0 else None),
        }
    out = {
        "journal": jpath,
        "epochs": epochs,
        "bucket_totals_s": totals,
        "goodput_fraction_mean": (round(sum(fracs) / len(fracs), 4)
                                  if fracs else None),
        "mfu_max": (round(max(mfus), 6) if mfus else None),
        "overlap": overlap,
        "ingest": ingests or None,
        # by cost: captured FLOPs first (the honest "expensive" ranking),
        # compile seconds as the tiebreak/no-capture fallback
        "compiled_functions": dict(sorted(
            compiles.items(),
            key=lambda kv: (-(kv[1].get("flops") or 0),
                            -kv[1]["compile_s"]))),
        "recovery": recovery,
    }
    # device flight recorder rollup (docs/PERF.md "Where the step time
    # goes"): the last device profile's top kernels next to the goodput
    # buckets they decompose, plus the HBM high water and anomaly count
    device: dict = {}
    if profiles:
        last = profiles[-1]
        device["profiles"] = len(profiles)
        device["last"] = {k: last.get(k) for k in
                          ("epoch", "trigger", "window_us",
                           "device_us_total", "device_fraction",
                           "kernel_count", "kernels")}
    if hbm_last is not None:
        device["hbm_peak_bytes"] = hbm_peak
        device["hbm_source"] = hbm_last.get("source")
        device["hbm_bytes_in_use"] = hbm_last.get("bytes_in_use")
    if anomalies:
        device["anomalies"] = anomalies
    if trace_fallbacks:
        device["trace_fallbacks"] = trace_fallbacks
    out["device"] = device or None
    # sparse embedding engine rollup (docs/EMBEDDING.md): the last tier
    # report (hot/cold traffic split), the last dedup report (rows
    # touched vs raw id cells), and how many cold reads hit the
    # journaled fallback chain
    embed: dict = {}
    if tier_last is not None:
        embed["tier_reports"] = tier_reports
        embed["tier"] = {k: tier_last.get(k) for k in
                         ("hit_rate", "hot_rows", "vocab", "lookups",
                          "hits", "misses", "cold_bytes", "cold_seconds",
                          "prefetch_hits", "fallbacks")}
    if dedup_last is not None:
        embed["dedup"] = {k: dedup_last.get(k) for k in
                          ("batches", "rows_touched", "raw_cells",
                           "dedup_ratio")}
    if offload_fallbacks:
        embed["offload_fallbacks"] = offload_fallbacks
    out["embed"] = embed or None
    # AOT serving-executable plane (docs/SERVING.md "Cold start & AOT
    # pack"): packed grids built, executables deserialized (the
    # zero-compile loads), and every fallback with its reason — a
    # fallback row here is the first place a fingerprint drift shows up
    aot: dict = {}
    if aot_packs:
        aot["packs"] = len(aot_packs)
        aot["pack_buckets"] = aot_packs[-1].get("buckets")
    if aot_loads:
        last = aot_loads[-1]
        aot["loads"] = len(aot_loads)
        aot["last_load"] = {k: last.get(k) for k in
                            ("path", "buckets", "bucket_ms", "wall_ms")}
    if aot_fallbacks:
        aot["fallbacks"] = len(aot_fallbacks)
        aot["last_fallback"] = {
            k: aot_fallbacks[-1].get(k) for k in ("path", "reason")}
    if prewarm_last is not None:
        aot["prewarm"] = {k: prewarm_last.get(k) for k in
                          ("engine", "buckets", "wall_ms")}
    out["aot"] = aot or None
    # pod data plane rollup (docs/DATA.md "Multi-host data plane"): the
    # last epoch's per-host skew table (with its ingest bytes/seconds
    # extras), whether the cross-host digest agreement ever broke, and the
    # DCN placement ledger's cumulative savings
    pod: dict = {}
    if skew_last is not None:
        pod["skew_epochs"] = skew_count
        pod["last_epoch"] = skew_last.get("epoch")
        pod["hosts"] = skew_last.get("hosts")
        pod["order_digest_agree"] = skew_last.get("order_digest_agree")
        pod["shard_digest_agree"] = skew_last.get("shard_digest_agree")
        pod["digest_disagreements"] = digest_disagreements
    if dcn_last is not None:
        pod["dcn"] = {k: dcn_last.get(k) for k in
                      ("epoch", "tier", "hosts", "slices",
                       "input_local_bytes", "input_dcn_bytes",
                       "local_sgd_window")}
        pod["dcn"]["input_dcn_saved_bytes_total"] = dcn_saved_b
        pod["dcn"]["dcn_sync_saved_bytes_total"] = dcn_sync_saved_b
    out["pod"] = pod or None
    return out


def render_profile_text(summary: dict) -> str:
    """Human rendering of `profile_summary`'s dict: the per-epoch bucket
    table, top compiled functions, and the recovery tax."""
    lines = [f"journal: {summary['journal']}"]
    epochs = summary.get("epochs") or []
    if not epochs:
        lines.append("no goodput events (run predates the ledger, or no "
                     "epoch completed)")
    else:
        hdr = (f"{'epoch':>5} {'wall_s':>8} {'compile':>8} {'input':>8} "
               f"{'step':>8} {'ckpt':>8} {'restore':>8} {'eval':>8} "
               f"{'other':>8} {'goodput':>8} {'mfu':>8}")
        lines.append(hdr)

        def f(v, spec="0.3f"):
            return format(v, spec) if isinstance(v, (int, float)) else "-"

        for e in epochs:
            b = e.get("buckets") or {}
            lines.append(
                f"{f(e.get('epoch'), 'd'):>5} {f(e.get('wall_s')):>8} "
                f"{f(b.get('compile')):>8} {f(b.get('input')):>8} "
                f"{f(b.get('step')):>8} {f(b.get('checkpoint')):>8} "
                f"{f(b.get('restore')):>8} {f(b.get('eval')):>8} "
                f"{f(b.get('other')):>8} "
                f"{f(e.get('goodput_fraction'), '.1%'):>8} "
                f"{f(e.get('mfu'), '.4f'):>8}")
        mean_frac = summary.get("goodput_fraction_mean")
        mfu_max = summary.get("mfu_max")
        tail = [f"goodput mean {mean_frac:.1%}"
                if isinstance(mean_frac, (int, float)) else "goodput mean -"]
        if isinstance(mfu_max, (int, float)):
            tail.append(f"mfu max {mfu_max:.4f}")
        lines.append("  ".join(tail))
    overlap = summary.get("overlap")
    if overlap:
        eff = overlap.get("efficiency")
        lines.append(
            f"overlap engine: input hidden {overlap['input_hidden_s']:g}s "
            f"exposed {overlap['input_exposed_s']:g}s"
            + (f" ({eff:.1%} hidden)" if isinstance(eff, (int, float))
               else ""))
        for e in overlap.get("epochs") or []:
            if not e.get("overlap"):
                continue
            eeff = e.get("overlap_efficiency")
            lines.append(
                f"  epoch {e.get('epoch')}: tier={e.get('tier')} "
                + (f"[{e['resident_format']}] "
                   if e.get("resident_format") else "")
                + f"depth={e.get('prefetch_depth')} "
                f"hidden={e.get('input_hidden_s')}s "
                f"exposed={e.get('input_exposed_s')}s "
                f"eval={e.get('eval_s')}s "
                f"prefetched_next={e.get('prefetched_chunks')}"
                + (f" eff={eeff:.1%}"
                   if isinstance(eeff, (int, float)) else ""))
    for ing in summary.get("ingest") or []:
        tiers = ing.get("tiers") or {}
        tier_s = " ".join(f"{k}={v}" for k, v in sorted(tiers.items()))
        src_b = ing.get("source_bytes")
        lines.append(
            f"ingest[{ing.get('mode')}]: {ing.get('files')} files "
            f"x{ing.get('pool_width')} pool in {ing.get('wall_s')}s "
            + (f"[host {ing.get('host_index')}: {src_b:,}B source] "
               if isinstance(src_b, (int, float)) and src_b else "")
            + f"(inflate {ing.get('inflate_s')}s parse {ing.get('parse_s')}s "
            f"write {ing.get('write_s')}s; {tier_s})")
    pod = summary.get("pod") or {}
    if pod.get("hosts"):
        agree = pod.get("order_digest_agree")
        shard = pod.get("shard_digest_agree")
        dis = pod.get("digest_disagreements") or 0
        lines.append(
            f"pod data plane: {len(pod['hosts'])} hosts, "
            f"{pod.get('skew_epochs')} skew epoch(s), order digest "
            + ("agree" if agree else "-" if agree is None else "DISAGREE")
            + ", shard digest "
            + ("agree" if shard else "-" if shard is None else "DISAGREE")
            + (f" ({dis} disagreement(s) across run)" if dis else ""))
        for r in pod["hosts"]:
            ib = r.get("ingest_bytes")
            lines.append(
                f"  host {r.get('host', '?')}[{r.get('rank', '?')}]: "
                f"input {r.get('input_s')}s"
                + (f" ingest {ib:,}B/{r.get('ingest_s')}s"
                   if isinstance(ib, (int, float)) else ""))
    dcn = pod.get("dcn") or {}
    if dcn:
        lines.append(
            f"dcn placement: {dcn.get('hosts')} hosts x "
            f"{dcn.get('slices')} slice(s), per-host input "
            f"{dcn.get('input_local_bytes'):,}B local / "
            f"{dcn.get('input_dcn_bytes'):,}B cross-DCN; saved "
            f"{dcn.get('input_dcn_saved_bytes_total'):,}B input + "
            f"{dcn.get('dcn_sync_saved_bytes_total'):,}B sync "
            f"(local-SGD window {dcn.get('local_sgd_window')})")
    comp = summary.get("compiled_functions") or {}
    if comp:
        lines.append("compiled functions (by cost):")
        for fn, c in comp.items():
            parts = [f"  {fn}: {c['compiles']} compile(s) "
                     f"{c['compile_s']:.3f}s"]
            if c.get("flops") is not None:
                parts.append(f"flops/dispatch {c['flops']:.3g}")
            if c.get("bytes_accessed") is not None:
                parts.append(f"bytes {c['bytes_accessed']:.3g}")
            if c.get("peak_bytes") is not None:
                parts.append(f"peak {c['peak_bytes']:.3g}B")
            cache = c.get("cache") or {}
            if cache:
                parts.append("cache " + "/".join(
                    f"{k}={v}" for k, v in sorted(cache.items())))
            lines.append(" ".join(parts))
    aot = summary.get("aot") or {}
    if aot:
        bits = []
        if aot.get("packs"):
            bits.append(f"{aot['packs']} pack(s) built "
                        f"(buckets {aot.get('pack_buckets')})")
        last_load = aot.get("last_load") or {}
        if aot.get("loads"):
            bits.append(
                f"{aot['loads']} zero-compile load(s), last "
                f"{last_load.get('wall_ms')} ms over buckets "
                f"{last_load.get('buckets')}")
        if aot.get("fallbacks"):
            lf = aot.get("last_fallback") or {}
            bits.append(f"{aot['fallbacks']} FALLBACK(s) to jit, last: "
                        f"{lf.get('reason')}")
        if bits:
            lines.append("aot executables: " + "; ".join(bits))
        pw = aot.get("prewarm") or {}
        if pw:
            lines.append(
                f"  pre-warm [{pw.get('engine')}]: ladder "
                f"{pw.get('buckets')} in {pw.get('wall_ms')} ms")
    device = summary.get("device") or {}
    if device:
        bits = []
        if device.get("hbm_peak_bytes") is not None:
            bits.append(f"hbm peak {device['hbm_peak_bytes']:,} B "
                        f"({device.get('hbm_source')})")
        if device.get("profiles"):
            bits.append(f"{device['profiles']} device profile(s)")
        if device.get("anomalies"):
            bits.append(f"{device['anomalies']} anomaly(ies)")
        if device.get("trace_fallbacks"):
            bits.append(f"{device['trace_fallbacks']} trace fallback(s)")
        if bits:
            lines.append("device: " + ", ".join(bits)
                         + "  (`shifu-tpu trace` for the kernel table)")
        last = device.get("last") or {}
        for k in (last.get("kernels") or [])[:5]:
            frac = k.get("fraction")
            lines.append(
                f"  kernel {k.get('name')}: {k.get('device_us')}us"
                + (f" ({frac:.1%} of window)"
                   if isinstance(frac, (int, float)) else "")
                + (f" [{k['bound']}-bound]" if k.get("bound") else ""))
    embed = summary.get("embed") or {}
    if embed:
        tier = embed.get("tier") or {}
        if tier:
            hr = tier.get("hit_rate")
            cb = tier.get("cold_bytes")
            lines.append(
                "embed tier: hit rate "
                + (format(hr, ".1%") if isinstance(hr, (int, float))
                   else "-")
                + f" ({tier.get('hot_rows')} hot rows of "
                f"{tier.get('vocab')} vocab), cold "
                + (f"{cb / 1e6:.1f} MB" if isinstance(cb, (int, float))
                   else "-")
                + f" in {tier.get('cold_seconds')}s host reads"
                + (f", {tier.get('prefetch_hits')} prefetch hit(s)"
                   if tier.get("prefetch_hits") else ""))
        dd = embed.get("dedup") or {}
        if dd:
            dr = dd.get("dedup_ratio")
            lines.append(
                f"embed dedup: {dd.get('rows_touched')} rows touched / "
                f"{dd.get('raw_cells')} raw id cells over "
                f"{dd.get('batches')} batch(es)"
                + (f" ({dr:.1%} of cells)"
                   if isinstance(dr, (int, float)) else ""))
        if embed.get("offload_fallbacks"):
            lines.append(f"embed offload: {embed['offload_fallbacks']} "
                         "cold-read fault(s) served by the fallback chain")
    rec = summary.get("recovery") or {}
    if any(rec.get(k) for k in ("restores", "fallbacks",
                                "preemption_graces", "resumes")):
        lines.append(
            f"recovery: {rec.get('restores', 0)} restore(s) "
            f"{rec.get('restore_s', 0.0):.3f}s, "
            f"{rec.get('fallbacks', 0)} fallback(s), "
            f"{rec.get('preemption_graces', 0)} preemption grace(s), "
            f"{rec.get('resumes', 0)} resume(s)")
    return "\n".join(lines)


# -- `shifu-tpu trace`: the device flight-recorder view ---------------------

def trace_summary(path: str) -> Optional[dict]:
    """The device flight-recorder dict for a job/telemetry dir: every
    `device_profile` rollup (scheduled windows + anomaly one-shots), the
    anomaly log with its ring context, HBM watermark trajectory, and
    trace fallbacks — assembled purely from journal events
    (obs/devprof.py writes them).  None when no journal is found."""
    jpath = find_journal(path)
    if jpath is None:
        return None
    events = _load_events(jpath)
    profiles: list[dict] = []
    anomalies: list[dict] = []
    watermarks: list[dict] = []
    fallbacks: list[dict] = []
    for rec in events:
        kind = rec.get("kind")
        if kind == "device_profile":
            profiles.append({k: rec.get(k) for k in
                             ("epoch", "trigger", "trace_dir", "window_us",
                              "device_us_total", "device_fraction", "lanes",
                              "kernel_count", "kernels", "other_us",
                              "modules", "peak_tflops", "peak_hbm_gbps",
                              "capture_wall_s")})
        elif kind == "anomaly":
            anomalies.append({k: rec.get(k) for k in
                              ("epoch", "chunk", "step_s", "median_s",
                               "mad_s", "zscore", "window", "ring")})
        elif kind == "hbm_watermark":
            watermarks.append({k: rec.get(k) for k in
                               ("epoch", "source", "bytes_in_use",
                                "peak_bytes", "bytes_limit",
                                "device_count")})
        elif kind == "trace_fallback":
            fallbacks.append({k: rec.get(k) for k in
                              ("epoch", "stage", "error")})
    peaks = [w.get("peak_bytes") for w in watermarks
             if isinstance(w.get("peak_bytes"), (int, float))]
    return {
        "journal": jpath,
        "profiles": profiles,
        "anomalies": anomalies,
        "watermarks": watermarks,
        "hbm_peak_bytes": max(peaks) if peaks else None,
        "trace_fallbacks": fallbacks,
    }


def render_trace_text(summary: dict) -> str:
    """Human rendering of `trace_summary`: per-capture kernel tables,
    the anomaly log, and the HBM watermark trajectory."""
    lines = [f"journal: {summary['journal']}"]
    profiles = summary.get("profiles") or []
    if not profiles:
        lines.append("no device_profile events — enable trace capture "
                     "with obs.trace_epochs (shifu.obs.trace-epochs), "
                     "e.g. 'first' (docs/OBSERVABILITY.md)")
    for p in profiles:
        frac = p.get("device_fraction")
        lines.append(
            f"device profile: epoch {p.get('epoch')} "
            f"trigger={p.get('trigger')} window {p.get('window_us')}us "
            f"device {p.get('device_us_total')}us"
            + (f" ({frac:.1%} busy)" if isinstance(frac, (int, float))
               else "")
            + f" kernels={p.get('kernel_count')}")
        kernels = p.get("kernels") or []
        if kernels:
            lines.append(f"  {'kernel':<40} {'calls':>6} {'device_us':>12} "
                         f"{'frac':>7} {'bound':>8}")
        for k in kernels:
            kfrac = k.get("fraction")
            lines.append(
                f"  {str(k.get('name'))[:40]:<40} {k.get('calls', 0):>6} "
                f"{k.get('device_us', 0):>12} "
                f"{(format(kfrac, '.2%') if isinstance(kfrac, (int, float)) else '-'):>7} "
                f"{(k.get('bound') or '-'):>8}")
        other = p.get("other_us")
        if other:
            lines.append(f"  (+{other}us across "
                         f"{p.get('kernel_count', 0) - len(kernels)} more "
                         f"kernels)")
    for a in summary.get("anomalies") or []:
        lines.append(
            f"anomaly: epoch {a.get('epoch')} chunk {a.get('chunk')} "
            f"step {a.get('step_s')}s vs median {a.get('median_s')}s "
            f"(z={a.get('zscore')}, ring of {len(a.get('ring') or [])})")
    wm = summary.get("watermarks") or []
    if wm:
        last = wm[-1]
        peak = summary.get("hbm_peak_bytes")
        lines.append(
            f"hbm: peak {peak:,} B" if isinstance(peak, (int, float))
            else "hbm: peak -")
        lines[-1] += (f" in-use {last.get('bytes_in_use'):,} B "
                      f"source={last.get('source')} "
                      f"({len(wm)} watermark(s))"
                      if isinstance(last.get("bytes_in_use"), (int, float))
                      else f" source={last.get('source')} "
                           f"({len(wm)} watermark(s))")
    for f in summary.get("trace_fallbacks") or []:
        lines.append(f"trace fallback: epoch {f.get('epoch')} "
                     f"stage={f.get('stage')} error={f.get('error')}")
    return "\n".join(lines)


# -- `shifu-tpu top`: the live serving/train operator view -------------------

# journal kinds that mark a telemetry dir as a serving daemon's (or a
# loadtest run against one)
_SERVING_KINDS = ("serve_start", "serving_report", "loadtest_report")

# a `top` frame reads the journal TAIL, not the whole file: a long-lived
# daemon's journal grows without bound, and a 2s-refresh streaming view
# must not pay O(run-length) reads per frame.  4 MiB holds hours of
# report-cadence events; everything a frame shows (latest report, alert
# states newest-wins, scrape histograms) is tail-derivable.
_TOP_TAIL_BYTES = 4 << 20


def _load_events_tail(jpath: str, tail_bytes: int = _TOP_TAIL_BYTES
                      ) -> tuple[list[dict], int, bool]:
    """(events parsed from the journal's last `tail_bytes`, event count
    of what was read, truncated?) — the bounded read behind `top`
    frames: ONE seek + ONE tail-sized read, never a whole-file pass (a
    2 GB journal must not be re-read every refresh).  Falls back to the
    full read for remote paths (fsio reads are whole-object anyway)."""
    import json as json_mod
    try:
        from ..data import fsio
        remote = fsio.is_remote(jpath)
    except Exception:
        remote = False
    if remote:
        events = _load_events(jpath)
        return events, len(events), False
    try:
        size = os.path.getsize(jpath)
        with open(jpath, "rb") as f:
            truncated = size > tail_bytes
            if truncated:
                f.seek(size - tail_bytes)
            tail = f.read(tail_bytes)
            if truncated:
                # the window may open mid-line: drop the torn first line
                nl = tail.find(b"\n")
                tail = tail[nl + 1:] if nl >= 0 else b""
    except OSError:
        return [], 0, False
    events = []
    for line in tail.splitlines():
        try:
            rec = json_mod.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            events.append(rec)
    return events, len(events), truncated


def _read_lease_nearby(journal_path: str) -> Optional[dict]:
    """The fleet membership lease (runtime/fleet.py `lease.json`) next to
    a journal, tolerantly: torn/absent/garbage is None — the top frame
    then falls back to journal-event freshness alone.

    Routed through data/fsio so a REMOTE (gs://-style) fleet telemetry
    dir answers too: with the old local-open-only read, every remote
    member rendered always-fresh — a dead member on shared storage never
    showed DOWN (`--stale-after` satellite fix)."""
    try:
        from ..data import fsio
        if fsio.is_remote(journal_path):
            parent = journal_path.rsplit("/", 1)[0]
            raw = fsio.read_bytes(fsio.join(parent, "lease.json"))
            rec = json.loads(raw.decode())
        else:
            with open(os.path.join(os.path.dirname(journal_path),
                                   "lease.json")) as f:
                rec = json.load(f)
        return rec if isinstance(rec, dict) else None
    except Exception:
        return None


def top_summary(path: str,
                stale_after_s: Optional[float] = None) -> Optional[dict]:
    """One `shifu-tpu top` frame for a job/telemetry dir: journal tail +
    scrape file ONLY (no jax import, bounded reads — safe to refresh
    against a live long-lived daemon).

    Serving dirs render rate / p50 / p99 / queue depth / batch shape, the
    per-stage lifecycle breakdown (always-on `serve_stage_seconds`
    histograms in the scrape file), active SLO alerts (firing `slo_alert`
    events not yet resolved), and sampled `request_trace` / one-shot
    `device_profile` counts.  Train dirs render epoch progress, goodput /
    MFU, and the last event — ONE command tops both planes.  None when no
    journal is found.

    Staleness: a dir whose freshest signal (fleet lease beat or last
    journal event) is older than `stale_after_s` — or than the lease's
    own ttl when a lease is present — gets `down: True` + `stale_s`
    instead of rendering its last report as live forever (a killed
    daemon must READ as dead, not as its final healthy frame)."""
    jpath = find_journal(path)
    if jpath is None:
        return None
    events, total_events, tail_only = _load_events_tail(jpath)
    reports: list[dict] = []
    alerts: list[dict] = []
    epochs: list[dict] = []
    goodput: Optional[dict] = None
    serve_start: Optional[dict] = None
    loadtests: list[dict] = []
    traces = 0
    route_traces = 0
    hedges = 0
    slo_profiles = 0
    tier_last: Optional[dict] = None
    dedup_last: Optional[dict] = None
    drift_last: Optional[dict] = None
    drift_alerts: list[dict] = []
    aot_load_last: Optional[dict] = None
    aot_loads = 0
    aot_fallback_last: Optional[dict] = None
    aot_fallbacks = 0
    mode = "train"
    for rec in events:
        kind = rec.get("kind")
        if kind == "serving_report":
            reports.append(rec)
        elif kind == "slo_alert":
            alerts.append(rec)
        elif kind == "drift_report":
            drift_last = rec
        elif kind == "drift_alert":
            drift_alerts.append(rec)
        elif kind == "serve_start":
            serve_start = rec
        elif kind == "loadtest_report":
            loadtests.append(rec)
        elif kind == "request_trace":
            traces += 1
        elif kind == "route_trace":
            route_traces += 1
            if rec.get("hedged"):
                hedges += 1
        elif kind == "device_profile" and rec.get("trigger") == "slo":
            slo_profiles += 1
        elif kind == "epoch":
            epochs.append(rec)
        elif kind == "goodput":
            goodput = rec
        elif kind == "embed_tier_report":
            tier_last = rec
        elif kind == "embed_dedup_report":
            dedup_last = rec
        elif kind == "aot_load":
            aot_load_last = rec
            aot_loads += 1
        elif kind == "aot_fallback":
            aot_fallback_last = rec
            aot_fallbacks += 1
    if serve_start is not None or reports or loadtests:
        mode = "serving"
    out: dict = {"journal": jpath, "mode": mode, "events": total_events}
    if tail_only:
        out["events_tail_only"] = True  # counts cover the 4 MiB tail
    if events:
        out["last_event"] = {"kind": events[-1].get("kind"),
                             "ts": events[-1].get("ts")}

    # staleness verdict: freshest of (lease beat, last event) vs the
    # caller's threshold or the lease's self-declared ttl
    lease = _read_lease_nearby(jpath)
    now = time.time()
    freshest: Optional[float] = None
    for ts in ((lease or {}).get("ts"),
               (out.get("last_event") or {}).get("ts")):
        if isinstance(ts, (int, float)):
            freshest = ts if freshest is None else max(freshest, ts)
    threshold = stale_after_s
    if threshold is None and lease is not None \
            and isinstance(lease.get("ttl_s"), (int, float)):
        threshold = float(lease["ttl_s"])
    if lease is not None:
        out["lease"] = {"member": lease.get("member"),
                        "ttl_s": lease.get("ttl_s")}
        if lease.get("host"):
            out["lease"]["host"] = lease.get("host")
    if threshold is not None and threshold > 0 and freshest is not None:
        age = max(0.0, now - freshest)
        if age > threshold:
            out["down"] = True
            out["stale_s"] = round(age, 1)

    scrape = _read_scrape(jpath)
    if mode == "serving":
        last = reports[-1] if reports else {}
        if not last and loadtests:
            # a loadtest-only dir (socket run's own telemetry): render
            # the last run's achieved numbers in the serving frame
            lt = loadtests[-1]
            last = {"requests": lt.get("completed"),
                    "rejected": lt.get("rejected"),
                    "errors": lt.get("errors"),
                    "p50_ms": lt.get("p50_ms"),
                    "p99_ms": lt.get("p99_ms"),
                    "engine": lt.get("engine"),
                    "scores_per_sec": lt.get("achieved_scores_per_sec"),
                    "stages": lt.get("stages")}
        out["serving"] = {k: last.get(k) for k in
                          ("requests", "rejected", "errors", "queue_depth",
                           "batch_mean", "p50_ms", "p99_ms", "engine",
                           "version", "model", "uptime_s", "scores_per_sec",
                           "window_s")}
        if out["serving"].get("scores_per_sec") is None and len(reports) >= 2:
            # no windowed report (final-only journal): derive the rate
            # from the last two reports' cumulative request counts
            a, b = reports[-2], reports[-1]
            try:
                dt = float(b.get("ts", 0)) - float(a.get("ts", 0))
                dr = int(b.get("requests", 0)) - int(a.get("requests", 0))
                if dt > 0:
                    out["serving"]["scores_per_sec"] = round(dr / dt, 1)
            except (TypeError, ValueError):
                pass
        if serve_start is not None:
            out["serving"]["path"] = serve_start.get("path")
            out["serving"]["port"] = serve_start.get("port")
        # stage decomposition from the scrape file's always-on histograms
        # — a corrupt/truncated scrape must degrade to no breakdown, not
        # kill the whole frame (the journal half already parsed fine)
        if scrape:
            try:
                out["stages"] = _stage_breakdown_from_scrape(scrape)
            except Exception:
                out["stages"] = None
                out["scrape_error"] = True
        # the daemon's own lifetime-windowed view wins when present (a
        # shared metrics dir can hold more than one daemon's histograms)
        if last.get("stages"):
            out["stages"] = last["stages"]
        out["slo"] = _slo_state_from_alerts(alerts, last.get("slo"))
        # drift observatory row: the last drift_report's worst offender +
        # live AUC decay, and the currently-firing drift objectives
        # (newest transition wins — same discipline as slo alerts)
        if drift_last is not None or drift_alerts:
            firing: dict[str, dict] = {}
            for a in drift_alerts:
                obj = str(a.get("objective", "?"))
                if a.get("state") == "firing":
                    firing[obj] = a
                elif a.get("state") == "resolved":
                    firing.pop(obj, None)
            dr = drift_last or {}
            out["drift"] = {
                "worst": dr.get("worst_psi"),
                "worst_feature": ((dr.get("worst") or [{}])[0]
                                  .get("feature")),
                "score_kl": dr.get("score_kl"),
                "auc_live": dr.get("auc_live"),
                "auc_decay": dr.get("auc_decay"),
                "rows_fast": dr.get("rows_fast"),
                "baseline_digest": dr.get("baseline_digest"),
                "firing": sorted(firing),
                "alerts_total": sum(1 for a in drift_alerts
                                    if a.get("state") == "firing"),
            }
        # AOT executable rows (ISSUE 19): zero-compile loads vs journaled
        # fallbacks — read straight from the journal tail, no jax needed
        if aot_loads or aot_fallbacks:
            out["aot"] = {"loads": aot_loads, "fallbacks": aot_fallbacks}
            if aot_load_last is not None:
                out["aot"]["buckets"] = aot_load_last.get("buckets")
                out["aot"]["load_ms"] = aot_load_last.get("wall_ms")
            if aot_fallback_last is not None:
                out["aot"]["last_fallback_reason"] = \
                    aot_fallback_last.get("reason")
        out["request_traces"] = traces
        if route_traces:
            out["route_traces"] = route_traces
            out["hedges"] = hedges
        if slo_profiles:
            out["slo_device_profiles"] = slo_profiles
    else:
        if epochs:
            e = epochs[-1]
            out["epoch"] = {k: e.get(k) for k in
                            ("epoch", "train_error", "valid_error",
                             "valid_auc", "epoch_time")}
        if goodput is not None:
            out["goodput"] = {k: goodput.get(k) for k in
                              ("epoch", "goodput_fraction", "mfu")}
        # sparse embedding engine: the live tier/dedup story from the
        # journal tail (docs/EMBEDDING.md)
        embed: dict = {}
        if tier_last is not None:
            embed.update({k: tier_last.get(k) for k in
                          ("hit_rate", "hot_rows", "vocab", "cold_bytes",
                           "fallbacks")})
        if dedup_last is not None:
            embed["dedup_ratio"] = dedup_last.get("dedup_ratio")
        if embed:
            out["embed"] = embed
    # incident digest from the same tail: failover / SLO / degraded-swap
    # episodes stitched by obs/timeline.py (lazy import; `shifu-tpu
    # timeline` holds the full records with causal chains + traces)
    if any(rec.get("kind") in ("fleet_failover", "fleet_swap_degraded",
                               "slo_alert") for rec in events):
        try:
            from . import timeline as timeline_mod
            inc = timeline_mod.reconstruct_incidents(
                timeline_mod.merge_sources([(events, "")]))
        except Exception:
            inc = []
        if inc:
            out["incidents"] = {
                "total": len(inc),
                "open": sum(1 for i in inc if not i["resolved"]),
                "last": {"id": inc[-1]["id"], "kind": inc[-1]["kind"],
                         "resolved": inc[-1]["resolved"],
                         "recovery_s": inc[-1]["recovery_s"]}}
    return out


def _stage_breakdown_from_scrape(scrape_text: str) -> Optional[dict]:
    """{stage: {mean_ms, p99_ms, count, share}} from the scrape file's
    `serve_stage_seconds` histograms — same shape as loadtest/stats()
    (the ONE decomposition helper, obs/slo.stage_stats)."""
    from .slo import stage_stats

    hists = parse_scrape_histograms(scrape_text).get("serve_stage_seconds")
    if not hists:
        return None
    per_stage: dict = {}
    for key, s in hists.items():
        stage = dict(kv.split("=", 1) for kv in key.split(";")
                     if "=" in kv).get("stage")
        if not stage:
            continue
        per_stage[stage] = (s["bounds"], s["counts"], s["sum"], s["count"])
    return stage_stats(per_stage) or None


def _slo_state_from_alerts(alerts: list[dict],
                           live_state: Optional[dict]) -> dict:
    """Active (firing, not yet resolved) alerts from the journaled
    `slo_alert` transitions, plus the last serving_report's live burn
    snapshot when present."""
    firing: dict[str, dict] = {}
    for a in alerts:
        obj = str(a.get("objective", "?"))
        if a.get("state") == "firing":
            firing[obj] = a
        elif a.get("state") == "resolved":
            firing.pop(obj, None)
    out = {
        "alerts_total": sum(1 for a in alerts
                            if a.get("state") == "firing"),
        "active": [
            {k: a.get(k) for k in
             ("objective", "burn_fast", "burn_slow", "observed_p99_ms",
              "observed_error_rate", "observed_availability", "ts")}
            for a in firing.values()],
    }
    if isinstance(live_state, dict):
        out["burns"] = live_state.get("burns")
        out["objectives"] = live_state.get("objectives")
    return out


def render_top_text(summary: dict) -> str:
    """One `shifu-tpu top` frame as text."""
    lines = [f"[{summary.get('mode')}] {summary['journal']} "
             f"({summary.get('events')} events)"]
    if summary.get("down"):
        lines.append(f"DOWN — no heartbeat/journal activity for "
                     f"{summary.get('stale_s')}s (showing last frame)")
    sv = summary.get("serving")
    if sv:
        rate = sv.get("scores_per_sec")
        lines.append(
            "rate "
            + (f"{rate:,.0f}/s" if isinstance(rate, (int, float)) else "-")
            + f"  p50 {sv.get('p50_ms')} ms  p99 {sv.get('p99_ms')} ms  "
            f"queue {sv.get('queue_depth')}  batch {sv.get('batch_mean')}  "
            f"engine {sv.get('engine')} v{sv.get('version')}")
        lines.append(
            f"requests {sv.get('requests')}  rejected {sv.get('rejected')}"
            f"  errors {sv.get('errors')}  uptime {sv.get('uptime_s')}s")
    stages = summary.get("stages")
    if stages:
        lines.append(f"  {'stage':<10} {'mean_ms':>9} {'p99_ms':>9} "
                     f"{'share':>7}")
        order = ("admission", "queue", "coalesce", "dispatch", "device",
                 "reply")
        for stage in order:
            s = stages.get(stage)
            if not s:
                continue
            share = s.get("share")
            lines.append(
                f"  {stage:<10} {s.get('mean_ms', '-'):>9} "
                f"{(s.get('p99_ms') if s.get('p99_ms') is not None else '-'):>9} "
                f"{(format(share, '.1%') if isinstance(share, (int, float)) else '-'):>7}")
    slo = summary.get("slo")
    if slo is not None:
        active = slo.get("active") or []
        if active:
            for a in active:
                obs_bits = [f"{k.replace('observed_', '')}="
                            f"{a[k]}" for k in
                            ("observed_p99_ms", "observed_error_rate",
                             "observed_availability") if a.get(k) is not None]
                lines.append(
                    f"ALERT {a.get('objective')}: burn fast "
                    f"{a.get('burn_fast')} / slow {a.get('burn_slow')}"
                    + (f"  ({' '.join(obs_bits)})" if obs_bits else ""))
        else:
            objectives = slo.get("objectives")
            lines.append("slo: ok"
                         + (f" (objectives: "
                            f"{', '.join(sorted(objectives))})"
                            if objectives else
                            f" ({slo.get('alerts_total', 0)} alert(s) "
                            "this run)"))
    dr = summary.get("drift")
    if dr:
        worst = dr.get("worst")
        decay = dr.get("auc_decay")
        bits = ["drift: "
                + ("PSI "
                   + (format(worst, ".3f")
                      if isinstance(worst, (int, float)) else "-")
                   + (f" ({dr.get('worst_feature')})"
                      if dr.get("worst_feature") else ""))]
        if dr.get("score_kl") is not None:
            bits.append(f"score KL {dr['score_kl']}")
        if isinstance(decay, (int, float)):
            bits.append(f"auc live {dr.get('auc_live')} "
                        f"(decay {decay:+.4f})")
        if dr.get("firing"):
            bits.append("FIRING " + ",".join(dr["firing"]))
        lines.append("  ".join(bits))
    aot = summary.get("aot")
    if aot:
        bits = []
        if aot.get("loads"):
            bits.append(
                f"{aot['loads']} zero-compile load(s)"
                + (f" of buckets {aot.get('buckets')}"
                   if aot.get("buckets") else "")
                + (f" in {aot.get('load_ms')} ms"
                   if aot.get("load_ms") is not None else ""))
        if aot.get("fallbacks"):
            bits.append(f"{aot['fallbacks']} FALLBACK(s) to jit"
                        + (f" ({aot.get('last_fallback_reason')})"
                           if aot.get("last_fallback_reason") else ""))
        lines.append("aot: " + "  ".join(bits))
    if summary.get("request_traces"):
        lines.append(f"sampled request traces: "
                     f"{summary['request_traces']}"
                     + (f"  slo device profiles: "
                        f"{summary['slo_device_profiles']}"
                        if summary.get("slo_device_profiles") else ""))
    if summary.get("route_traces"):
        lines.append(f"route traces: {summary['route_traces']}"
                     + (f"  hedged: {summary['hedges']}"
                        if summary.get("hedges") else ""))
    inc = summary.get("incidents")
    if inc:
        last = inc.get("last") or {}
        lines.append(
            f"incidents: {inc.get('total')} ({inc.get('open')} open)"
            + (f"  last: {last.get('kind')}"
               + (f" recovered in {last.get('recovery_s')}s"
                  if last.get("recovery_s") is not None else
                  ("" if last.get("resolved") else " OPEN"))
               if last else "")
            + "  — `shifu-tpu timeline` for causal chains")
    ep = summary.get("epoch")
    if ep:
        lines.append(
            f"epoch {ep.get('epoch')}  train_err {ep.get('train_error')}  "
            f"valid_err {ep.get('valid_error')}  auc {ep.get('valid_auc')}  "
            f"epoch_s {ep.get('epoch_time')}")
    gp = summary.get("goodput")
    if gp:
        frac = gp.get("goodput_fraction")
        mfu = gp.get("mfu")
        lines.append(
            "goodput "
            + (format(frac, ".1%") if isinstance(frac, (int, float))
               else "-")
            + ("  mfu " + format(mfu, ".4f")
               if isinstance(mfu, (int, float)) else ""))
    em = summary.get("embed")
    if em:
        hr = em.get("hit_rate")
        dr = em.get("dedup_ratio")
        cb = em.get("cold_bytes")
        bits = []
        if hr is not None:
            bits.append("tier hit "
                        + (format(hr, ".1%")
                           if isinstance(hr, (int, float)) else str(hr))
                        + f" ({em.get('hot_rows')}/{em.get('vocab')} hot)")
        if isinstance(cb, (int, float)) and cb:
            bits.append(f"cold {cb / 1e6:.1f} MB")
        if em.get("fallbacks"):
            bits.append(f"{em['fallbacks']} offload fallback(s)")
        if dr is not None:
            bits.append("dedup "
                        + (format(dr, ".1%")
                           if isinstance(dr, (int, float)) else str(dr)))
        lines.append("embed: " + "  ".join(bits))
    last = summary.get("last_event")
    if last:
        lines.append(f"last event: {last.get('kind')} at ts "
                     f"{last.get('ts')}")
    return "\n".join(lines)


# -- `shifu-tpu drift`: the model-quality / data-drift view ------------------

def drift_summary(path: str, model: Optional[str] = None,
                  feature: Optional[str] = None) -> Optional[dict]:
    """One `shifu-tpu drift` frame for a serving telemetry dir — journal
    tail ONLY (no jax, bounded read; the same contract as `top`): per
    model, the latest `drift_report` (per-feature PSI table, score KL,
    live AUC vs the frozen baseline's), the currently-firing drift
    objectives (newest `drift_alert` transition wins), and the alert
    history.  Train dirs answer too: the journaled `baseline_profile`
    summary renders when no serving reports exist yet.

    `model` filters to one model_id; `feature` filters the PSI table to
    one named feature (exact match).  None when no journal is found."""
    jpath = find_journal(path)
    if jpath is None:
        return None
    events, total_events, tail_only = _load_events_tail(jpath)
    reports: dict[str, dict] = {}        # model -> latest drift_report
    alerts: dict[str, list] = {}         # model -> [drift_alert ...]
    invalid: list[dict] = []
    baseline: Optional[dict] = None
    for rec in events:
        kind = rec.get("kind")
        if kind == "drift_report":
            reports[str(rec.get("model", "default"))] = rec
        elif kind == "drift_alert":
            alerts.setdefault(str(rec.get("model", "default")),
                              []).append(rec)
        elif kind == "baseline_profile":
            baseline = rec
        elif kind == "drift_baseline_invalid":
            invalid.append(rec)
    models: dict[str, dict] = {}
    for mid in sorted(set(reports) | set(alerts)):
        if model is not None and mid != model:
            continue
        rep = reports.get(mid) or {}
        firing: dict[str, dict] = {}
        for a in alerts.get(mid, []):
            obj = str(a.get("objective", "?"))
            if a.get("state") == "firing":
                firing[obj] = a
            elif a.get("state") == "resolved":
                firing.pop(obj, None)
        worst = rep.get("worst") or []
        if feature is not None:
            worst = [w for w in worst if w.get("feature") == feature]
        models[mid] = {
            "report": {k: rep.get(k) for k in
                       ("ts", "version", "baseline_digest", "rows_fast",
                        "rows_slow", "feedback_rows_fast", "worst_psi",
                        "score_kl", "mean_shift_max",
                        "mean_shift_feature", "auc_live", "auc_decay",
                        "train_auc")} if rep else None,
            "worst": worst,
            "firing": [
                {k: a.get(k) for k in
                 ("objective", "ts", "features", "score_kl")}
                for a in firing.values()],
            "alerts_total": sum(1 for a in alerts.get(mid, [])
                                if a.get("state") == "firing"),
        }
    out: dict = {"journal": jpath, "events": total_events,
                 "models": models}
    if tail_only:
        out["events_tail_only"] = True
    if baseline is not None:
        out["baseline"] = {k: baseline.get(k) for k in
                           ("epoch", "rows", "num_features", "train_auc",
                            "train_error", "score_mean")}
    if invalid:
        out["baseline_invalid"] = len(invalid)
    return out


def render_drift_text(summary: dict) -> str:
    """Human rendering of `drift_summary`: per-model drift panel — the
    PSI offender table, score divergence, and the live-AUC decay row."""
    lines = [f"journal: {summary['journal']} "
             f"({summary.get('events')} events)"]
    base = summary.get("baseline")
    if base:
        lines.append(
            f"baseline: epoch {base.get('epoch')}  rows {base.get('rows')}"
            f"  features {base.get('num_features')}"
            + (f"  train_auc {base.get('train_auc')}"
               if base.get("train_auc") is not None else ""))
    if summary.get("baseline_invalid"):
        lines.append(f"WARNING: {summary['baseline_invalid']} invalid "
                     "baseline-profile load(s) — drift dormant there")
    models = summary.get("models") or {}
    if not models:
        lines.append("no drift reports — daemon without a baseline "
                     "profile, drift disabled (shifu.drift.enabled), or "
                     "nothing served yet")
    for mid, m in models.items():
        rep = m.get("report")
        firing = m.get("firing") or []
        head = f"model {mid}"
        if rep:
            head += (f" v{rep.get('version')}  baseline "
                     f"{rep.get('baseline_digest')}  rows "
                     f"{rep.get('rows_fast')}/{rep.get('rows_slow')} "
                     "(fast/slow)")
        lines.append(head + ("  FIRING "
                             + ",".join(sorted(a.get("objective", "?")
                                               for a in firing))
                             if firing else "  ok"))
        if rep:
            kl = rep.get("score_kl")
            bits = ["  score KL "
                    + (format(kl, ".4f")
                       if isinstance(kl, (int, float)) else "-")]
            if rep.get("mean_shift_max") is not None:
                bits.append(f"mean shift {rep['mean_shift_max']} sigma "
                            f"({rep.get('mean_shift_feature')})")
            lines.append("  ".join(bits))
            if rep.get("auc_live") is not None:
                decay = rep.get("auc_decay")
                lines.append(
                    f"  auc live {rep.get('auc_live')}"
                    + (f" vs train {rep.get('train_auc')}"
                       if rep.get("train_auc") is not None else "")
                    + (f"  decay {decay:+.4f}"
                       if isinstance(decay, (int, float)) else "")
                    + f"  ({rep.get('feedback_rows_fast')} labeled rows "
                    "in window)")
            elif rep.get("feedback_rows_fast") is not None:
                lines.append("  auc live: - (no labeled feedback in "
                             "window — wire FEEDBACK frames or "
                             "ServeClient.feedback())")
        worst = m.get("worst") or []
        if worst:
            lines.append(f"  {'feature':<24} {'psi_fast':>9} "
                         f"{'psi_slow':>9}")
            for w in worst:
                def f(v):
                    return (format(v, ".4f")
                            if isinstance(v, (int, float)) else "-")
                lines.append(f"  {str(w.get('feature'))[:24]:<24} "
                             f"{f(w.get('psi_fast')):>9} "
                             f"{f(w.get('psi_slow')):>9}")
        for a in firing:
            feats = [f.get("feature") for f in (a.get("features") or [])]
            lines.append(
                f"  ALERT {a.get('objective')}"
                + (f": {', '.join(map(str, feats))}" if feats else "")
                + (f" (score KL {a.get('score_kl')})"
                   if a.get("score_kl") is not None else ""))
    return "\n".join(lines)


def render_top_fleet_text(rollup: dict) -> str:
    """The multi-daemon `shifu-tpu top` frame (obs/aggregate.py
    serving_rollup): fleet totals + one row per daemon."""
    fleet = rollup.get("fleet") or {}
    down = fleet.get("down") or 0
    lines = [
        f"fleet: {fleet.get('daemons')} daemon(s)"
        + (f" ({down} DOWN)" if down else "")
        + "  rate "
        + (f"{fleet['scores_per_sec']:,.0f}/s"
           if isinstance(fleet.get("scores_per_sec"), (int, float))
           else "-")
        + f"  worst p99 {fleet.get('worst_p99_ms')} ms  "
        f"active alerts {fleet.get('active_alerts')}"]
    if fleet.get("route_traces") or fleet.get("incidents"):
        lines.append(
            f"  route traces {fleet.get('route_traces', 0)}"
            f"  hedged {fleet.get('hedges', 0)}"
            f"  incidents {fleet.get('incidents', 0)}"
            f" ({fleet.get('incidents_open', 0)} open)")
    dw = fleet.get("drift_worst")
    if dw or fleet.get("drift_firing"):
        lines.append(
            "  drift: worst PSI "
            + (f"{dw['psi']:.3f} ({dw.get('feature')} @ "
               f"{str(dw.get('dir'))[-28:]})" if dw else "-")
            + (("  FIRING " + ",".join(fleet["drift_firing"]))
               if fleet.get("drift_firing") else ""))
    hosts = fleet.get("hosts") or {}
    if [h for h in hosts if h != "-"]:
        # the cross-host view: one cell per placement, dark hosts loud
        cells = []
        for h in sorted(hosts):
            slot = hosts[h]
            n, dn = slot.get("members", 0), slot.get("down", 0)
            cells.append(f"{h}:{n - dn}/{n}"
                         + (" DOWN" if dn and dn == n else ""))
        lines.append("  hosts: " + "  ".join(cells))
    lines.append(f"  {'daemon':<28} {'rate/s':>10} {'p99_ms':>8} "
                 f"{'queue':>6} {'alerts':>7} {'psi':>7} {'slo':>8}")
    for d in rollup.get("daemons") or []:
        sv = d.get("serving") or {}
        active = (d.get("slo") or {}).get("active") or []
        dr = d.get("drift") or {}
        psi = dr.get("worst")
        psi_s = (format(psi, ".3f") if isinstance(psi, (int, float))
                 else "-") + ("!" if dr.get("firing") else "")
        rate = sv.get("scores_per_sec")
        if d.get("down"):
            # the stale-frame fix: a dead member renders DOWN with its
            # lease age, never its last healthy numbers as if live
            lines.append(
                f"  {str(d.get('dir'))[-28:]:<28} "
                f"{'-':>10} {'-':>8} {'-':>6} {len(active):>7} "
                f"{'-':>7} {'DOWN':>8}  (stale {d.get('stale_s')}s)")
            continue
        lines.append(
            f"  {str(d.get('dir'))[-28:]:<28} "
            + (f"{rate:>10,.0f}" if isinstance(rate, (int, float))
               else f"{'-':>10}")
            + f" {sv.get('p99_ms') if sv.get('p99_ms') is not None else '-':>8}"
            f" {sv.get('queue_depth') if sv.get('queue_depth') is not None else '-':>6}"
            f" {len(active):>7}"
            f" {psi_s:>7}"
            f" {'FIRING' if active else 'ok':>8}")
    return "\n".join(lines)
