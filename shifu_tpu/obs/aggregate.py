"""Cross-host telemetry aggregation: the per-host skew table.

The SPMD successor of the reference AM's slowest-first worker sort
(appmaster/TensorflowSession.java:515-549, every worker's
TrainingIntermediateResult collected and sorted into one log line): each
host encodes a small JSON summary of its local telemetry, ONE
`multihost_utils.process_allgather` moves all of them, and every host
decodes the full set — the chief renders/journals the skew table.

COLLECTIVE: every process must call `gather_host_summaries` together
(the train loop does, once per epoch under multihost).  Single-process
callers get their own summary back without touching jax collectives, so
the same code path serves tests and real pods.
"""

from __future__ import annotations

import json
import os
from typing import Optional

# one fixed-size row per host: JSON padded with NULs so the allgathered
# array is rectangular.  4 KiB holds a generous summary; oversized
# payloads degrade to a marker rather than desyncing the gather.
DEFAULT_MAX_BYTES = 4096


def host_summary(input_seconds: float = 0.0, epoch_seconds: float = 0.0,
                 valid_seconds: float = 0.0, **extra) -> dict:
    """This host's skew-table row: identity + the per-host-attributable
    timings (host-side input production is what a degraded disk/NIC shows
    up in first — SURVEY section 5.1), plus any caller extras."""
    import jax

    row = {
        "host": os.uname().nodename,
        "rank": jax.process_index(),
        "input_s": round(float(input_seconds), 4),
        "epoch_s": round(float(epoch_seconds), 4),
        "valid_s": round(float(valid_seconds), 4),
    }
    row.update(extra)
    return row


def gather_host_summaries(summary: dict,
                          max_bytes: int = DEFAULT_MAX_BYTES
                          ) -> list[dict]:
    """All-gather one small dict per host; returns every host's decoded
    dict (rank order).  Single-process: [summary], no collectives."""
    import jax

    if jax.process_count() <= 1:
        return [dict(summary)]

    import numpy as np
    from jax.experimental import multihost_utils

    payload = json.dumps(summary).encode()
    if len(payload) > max_bytes:
        payload = json.dumps({"host": summary.get("host", "?"),
                              "rank": summary.get("rank", -1),
                              "_truncated": True}).encode()[:max_bytes]
    buf = np.zeros((max_bytes,), np.uint8)
    buf[: len(payload)] = np.frombuffer(payload, np.uint8)
    gathered = np.asarray(multihost_utils.process_allgather(buf))
    rows = []
    for r in range(gathered.shape[0]):
        raw = gathered[r].tobytes().rstrip(b"\0")
        try:
            rec = json.loads(raw)
        except ValueError:
            rec = {"rank": r, "_undecodable": True}
        if isinstance(rec, dict):
            rec.setdefault("rank", r)
            rows.append(rec)
    return rows


def skew_line(epoch: int, rows: list[dict],
              sort_key: str = "input_s") -> str:
    """One console line, hosts slowest-first by `sort_key` — the same
    operator read the reference AM printed, under SPMD semantics (input
    seconds are the per-host-attributable cost; epoch wall converges)."""
    ordered = sorted(rows, key=lambda r: -float(r.get(sort_key, 0.0)))

    def _one(r: dict) -> str:
        s = (f"{r.get('host', '?')}[{r.get('rank', '?')}] "
             f"input {float(r.get('input_s', 0.0)):.2f}s "
             f"(epoch {float(r.get('epoch_s', 0.0)):.2f}s, "
             f"valid {float(r.get('valid_s', 0.0)):.2f}s)")
        if r.get("ingest_bytes") is not None:
            # pod data plane: cumulative source ingest per host — a host
            # rereading more than its ~1/N slice, or grinding on a slow
            # disk, is named right here in the straggler line
            s += (f" ingest {float(r['ingest_bytes']) / 1e6:.1f}MB"
                  f"/{float(r.get('ingest_s', 0.0)):.1f}s")
        return s

    return (f"Epoch {epoch} hosts by input time (slowest first): "
            + " | ".join(_one(r) for r in ordered))


def digest_agreement(rows: list[dict], key: str) -> Optional[bool]:
    """Do all hosts agree on digest `key`?  None when NO row carries the
    field (pre-field journals stay un-audited, not failing); False when
    any host disagrees or is missing it while others have it."""
    values = [r.get(key) for r in rows]
    present = [v for v in values if v is not None]
    if not present:
        return None
    return len(present) == len(values) and len(set(present)) == 1


def epoch_skew(epoch: int, input_seconds: float, epoch_seconds: float,
               valid_seconds: float, console=None,
               journal: bool = True,
               extra: Optional[dict] = None) -> Optional[list[dict]]:
    """The per-epoch cross-host skew: gather every host's summary, print
    the slowest-first line on the chief, journal a `host_skew` event.
    COLLECTIVE under multihost (every rank must call); returns the rows on
    the chief, None elsewhere.

    Caller `extra` fields (pod data plane: ingest_bytes / ingest_s /
    order_digest / shard_digest) ride each host's row through the ONE
    allgather; when the digests are present the chief also journals
    per-epoch cross-host agreement (`order_digest_agree` /
    `shard_digest_agree`) in the host_skew row — the allgather-of-digests
    close that `pod-verify` audits."""
    import jax

    if jax.process_count() <= 1:
        return None
    # per-host HBM high water rides the same gather: a host leaking
    # device memory shows up as a named outlier in the skew table, the
    # multihost complement of the chief-local hbm_watermark event
    fields = dict(extra or {})
    try:
        from . import devprof
        snap = devprof.hbm_snapshot()
        if snap.get("peak_bytes"):
            fields["hbm_peak_bytes"] = int(snap["peak_bytes"])
    except Exception:
        pass
    rows = gather_host_summaries(host_summary(
        input_seconds, epoch_seconds, valid_seconds, **fields))
    if jax.process_index() != 0:
        return None
    if console is not None:
        console(skew_line(epoch, rows))
    if journal:
        from . import _sinks
        _sinks.event("host_skew", epoch=epoch, hosts=rows,
                     order_digest_agree=digest_agreement(
                         rows, "order_digest"),
                     shard_digest_agree=digest_agreement(
                         rows, "shard_digest"))
    return rows


def pod_ingest_rollup(events: list) -> dict:
    """Fold a pod run's merged journal events (obs/timeline.load_merged —
    one journal per rank) into the per-host ingest ledger: source bytes
    and ingest seconds per host, plus pod totals and the max/min byte
    imbalance.  Pure event fold — no jax, no collectives; the training
    plane's sibling of `serving_rollup`.

    Per-host identity: the event's `host` stamp when journals carry one,
    else the merge's `src` index (rank order for per-rank pod journals).
    Sources folded, newest-wins per host: `ingest_report` rows (per-phase
    seconds summed), `host_skew` rows' cumulative ingest extras, and
    dryrun `ingest_source_bytes_total` stamps."""
    hosts: dict = {}

    def slot(key) -> dict:
        return hosts.setdefault(str(key), {
            "ingest_bytes": 0, "ingest_s": 0.0, "files": 0, "reports": 0})

    for ev in events:
        kind = ev.get("kind")
        key = ev.get("host") or f"rank{ev.get('src', 0)}"
        if kind == "ingest_report":
            s = slot(key)
            s["reports"] += 1
            s["files"] += int(ev.get("files") or 0)
            s["ingest_s"] += sum(
                float(ev.get(k) or 0.0)
                for k in ("parse_s", "inflate_s", "write_s"))
            if ev.get("source_bytes") is not None:
                s["ingest_bytes"] += int(ev["source_bytes"])
        elif kind == "host_skew":
            # cumulative per-host counters gathered at epoch close:
            # newest event wins (totals, not deltas)
            for r in ev.get("hosts") or []:
                if r.get("ingest_bytes") is None:
                    continue
                s = slot(r.get("host") or f"rank{r.get('rank', 0)}")
                s["ingest_bytes"] = int(r["ingest_bytes"])
                s["ingest_s"] = float(r.get("ingest_s") or 0.0)
    total_b = sum(h["ingest_bytes"] for h in hosts.values())
    loads = [h["ingest_bytes"] for h in hosts.values()
             if h["ingest_bytes"] > 0]
    return {
        "hosts": {k: hosts[k] for k in sorted(hosts)},
        "pod": {
            "hosts": len(hosts),
            "ingest_bytes_total": total_b,
            "ingest_s_total": round(
                sum(h["ingest_s"] for h in hosts.values()), 3),
            "imbalance": (round(max(loads) / max(min(loads), 1), 3)
                          if loads else None),
        },
    }


# -- multi-daemon serving rollup (pod scale-out prep) ------------------------


def serving_rollup(paths: list,
                   stale_after_s: Optional[float] = None) -> dict:
    """Join N serving telemetry dirs into one fleet view — journal/scrape
    reads only (obs/render.top_summary per dir), no jax, no collectives:
    the rollup runs on any machine that can read the dirs, the serving
    analog of the training plane's host_skew table.

    A daemon whose freshest signal (fleet lease or journal tail) is
    older than `stale_after_s` — or than its own lease ttl — is DOWN:
    excluded from the live rate / p99 / queue / alert totals (its last
    frame is history, not throughput) and counted in `fleet.down`.

    Returns {"daemons": [per-dir top summaries + "dir"],
    "fleet": {daemons, down, scores_per_sec (sum of live rates),
    worst_p99_ms, queue_depth (sum), active_alerts,
    firing (objective names)}} — rendered by
    `shifu-tpu top <dir> <dir> ...` (render.render_top_fleet_text)."""
    from . import render

    daemons: list[dict] = []
    for p in paths:
        try:
            s = render.top_summary(str(p), stale_after_s=stale_after_s)
        except Exception as e:  # noqa: BLE001 — one bad dir, not the view
            s = {"error": f"{type(e).__name__}: {e}"[:200]}
        if s is None:
            s = {"dir": str(p), "error": "no telemetry journal"}
        else:
            s["dir"] = str(p)
        daemons.append(s)
    rates = []
    p99s = []
    queue = 0
    down = 0
    active: list[dict] = []
    firing: set = set()
    drift_worst: Optional[dict] = None  # fleet-wide worst PSI + where
    drift_firing: set = set()
    route_traces = 0
    hedges = 0
    incidents = 0
    incidents_open = 0
    for d in daemons:
        # tracing/incident counts are historical, not live capacity —
        # a DOWN member's journal still tells the incident story
        route_traces += int(d.get("route_traces") or 0)
        hedges += int(d.get("hedges") or 0)
        inc = d.get("incidents") or {}
        incidents += int(inc.get("total") or 0)
        incidents_open += int(inc.get("open") or 0)
        if d.get("down"):
            down += 1
            continue  # a dead member's last frame is not live capacity
        sv = d.get("serving") or {}
        if isinstance(sv.get("scores_per_sec"), (int, float)):
            rates.append(sv["scores_per_sec"])
        if isinstance(sv.get("p99_ms"), (int, float)):
            p99s.append(sv["p99_ms"])
        if isinstance(sv.get("queue_depth"), (int, float)):
            queue += int(sv["queue_depth"])
        for a in (d.get("slo") or {}).get("active") or []:
            active.append(a)
            if a.get("objective"):
                firing.add(str(a["objective"]))
        dr = d.get("drift") or {}
        if isinstance(dr.get("worst"), (int, float)) and (
                drift_worst is None or dr["worst"] > drift_worst["psi"]):
            drift_worst = {"psi": dr["worst"],
                           "feature": dr.get("worst_feature"),
                           "dir": d.get("dir")}
        for obj in dr.get("firing") or []:
            drift_firing.add(str(obj))
    # per-host grouping off the lease's host stamp (the cross-host fleet
    # writes it; dirs without one group under "-"): live/down counts per
    # placement, so a whole-host loss reads as ONE row going dark
    hosts: dict = {}
    for d in daemons:
        host = str((d.get("lease") or {}).get("host") or "-")
        slot = hosts.setdefault(host, {"members": 0, "down": 0})
        slot["members"] += 1
        if d.get("down"):
            slot["down"] += 1
    return {
        "daemons": daemons,
        "fleet": {
            "daemons": len(daemons),
            "down": down,
            "scores_per_sec": round(sum(rates), 1) if rates else None,
            "worst_p99_ms": max(p99s) if p99s else None,
            "queue_depth": queue,
            "active_alerts": len(active),
            "firing": sorted(firing),
            "drift_worst": drift_worst,
            "drift_firing": sorted(drift_firing),
            "route_traces": route_traces,
            "hedges": hedges,
            "incidents": incidents,
            "incidents_open": incidents_open,
            "hosts": {h: hosts[h] for h in sorted(hosts)},
        },
    }
