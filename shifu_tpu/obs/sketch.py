"""Mergeable streaming distribution sketches for the drift observatory
(docs/OBSERVABILITY.md "Drift observatory").

The reference pipeline's `stats` step freezes the feature distributions
the model is normalized against (PAPER.md §0) but nothing downstream
ever re-checks them; ROADMAP item 3 names drift metrics vs that frozen
epoch as the prerequisite observability for online learning.  These
sketches are the substrate: the train loop builds a reference profile
from the training partition, `export/artifact.save_artifact` freezes it
into the artifact as ``baseline_profile.json``, and the scoring daemon
accumulates the SAME sketch shape over live traffic so obs/drift.py can
diff the two (PSI per feature, mean shift, score KL).

Two deliberate properties:

- **Fixed grid, not data-derived.**  Feature histograms ride the
  cache-v2 int8 wire grid (data/pipeline.wire_params: a STATIC affine
  grid, ``q = round((x - offset)/scale)`` saturated to [-127, 127]) —
  the same 255-bucket axis on the training host, in the artifact, and
  in every serving replica, so histograms from different processes are
  directly addable and directly comparable.  When the serving wire
  already carries int8 feature bytes the sketch histogram is literally
  ``np.bincount`` over bytes on the wire — no dequantization.

- **One flattened bincount per batch.**  All F features bin in a single
  ``np.bincount`` over ``(q + 127) + feature_index * 255`` — no
  per-feature and certainly no per-row Python loop; the always-on
  serving cost the drift overhead-guard test pins.

Every sketch's state is ADDITIVE (counts + moment sums), which buys
both `merge` (fleet rollups, shard-parallel baselines — the classic
parallel/Chan-Welford combine reduces to summing (n, sum, sumsq)) and
trailing windows by cumulative-snapshot subtraction (obs/drift.py).
Mean/variance derive from the grid histogram itself — exact for int8
wire traffic, grid-rounded (|err| <= scale/2 per value) for f32 — so
the per-batch cost stays the one bincount.

Everything here is numpy-only: no jax import, safe in journal-tail CLI
renderers and jax-masked subprocesses.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

# the int8 wire grid: values live on [-127, 127] -> 255 buckets
N_BUCKETS = 255
# PSI rebins the 255 fine buckets into coarse groups (255 = 17 * 15):
# fine enough to localize a shift, coarse enough that a healthy window
# populates every group and the epsilon smoothing stays negligible
PSI_GROUPS = 17
_PSI_FOLD = N_BUCKETS // PSI_GROUPS  # 15

# score-distribution sketch: sigmoid outputs on [0, 1]
SCORE_BINS = 64

_EPS = 1e-6

PROFILE_KIND = "shifu_tpu_baseline_profile"
PROFILE_VERSION = 1


def default_grid(num_features: int,
                 clip: float = 8.0) -> tuple[np.ndarray, np.ndarray]:
    """The static per-feature (scale, offset) of the int8 wire grid —
    the same pure-function-of-config grid data/pipeline.wire_params
    builds (scale = clip/127, offset = 0), duplicated here so sketches
    stay importable without the data plane (serving daemons and CLI
    renderers never touch DataSchema)."""
    f = int(num_features)
    scale = np.full((f,), float(clip) / 127.0, np.float32)
    offset = np.zeros((f,), np.float32)
    return scale, offset


class FeatureSketch:
    """Per-feature streaming distribution sketch on the int8 wire grid.

    State: one (F, 255) count matrix.  `update` takes a (B, F) batch —
    int8 wire bytes bin directly, float features quantize through the
    SAME grid first (one vectorized pass) — and costs one flattened
    bincount.  Moments (`moments()`) derive from the histogram: exact
    for int8 input, within scale/2 per value for floats.  NOT
    thread-safe; callers serialize (the daemon's dispatch worker is the
    only writer, snapshots copy under the daemon's drift lock)."""

    def __init__(self, num_features: int,
                 scale: Optional[np.ndarray] = None,
                 offset: Optional[np.ndarray] = None):
        self.num_features = int(num_features)
        if scale is None or offset is None:
            scale, offset = default_grid(self.num_features)
        self.scale = np.asarray(scale, np.float32).reshape(-1)
        self.offset = np.asarray(offset, np.float32).reshape(-1)
        if self.scale.shape[0] != self.num_features \
                or self.offset.shape[0] != self.num_features:
            raise ValueError(
                f"grid shape mismatch: {self.scale.shape[0]} scales / "
                f"{self.offset.shape[0]} offsets for "
                f"{self.num_features} features")
        self.hist = np.zeros((self.num_features, N_BUCKETS), np.int64)
        self.rows = 0
        # flattened-bincount index offset, built once: feature j's bucket
        # q lands at j*255 + (q+127)
        self._feat_base = (np.arange(self.num_features, dtype=np.int64)
                           * N_BUCKETS)

    # -- accumulation --------------------------------------------------

    def update(self, x: np.ndarray) -> None:
        """Accumulate a (B, F) batch — int8 bins as-is (the bytes on the
        wire ARE the bucket ids), anything else quantizes through the
        grid first.  One bincount for all F features."""
        x = np.asarray(x)
        if x.ndim == 1:
            x = x[None, :]
        if x.shape[1] != self.num_features:
            raise ValueError(f"batch has {x.shape[1]} features, sketch "
                             f"has {self.num_features}")
        if x.shape[0] == 0:
            return
        if x.dtype == np.int8:
            q = x.astype(np.int64)
        else:
            xf = np.asarray(x, np.float32)
            q = np.clip(np.rint((xf - self.offset) * (1.0 / self.scale)),
                        -127, 127).astype(np.int64)
        idx = (q + 127) + self._feat_base  # (B, F), values < F*255
        flat = np.bincount(idx.ravel(),
                           minlength=self.num_features * N_BUCKETS)
        self.hist += flat.reshape(self.num_features, N_BUCKETS)
        self.rows += int(x.shape[0])

    def merge(self, other: "FeatureSketch") -> "FeatureSketch":
        """Add another sketch's counts into this one (same grid)."""
        if other.num_features != self.num_features:
            raise ValueError("cannot merge sketches with different "
                             f"feature counts ({self.num_features} vs "
                             f"{other.num_features})")
        if not (np.allclose(self.scale, other.scale)
                and np.allclose(self.offset, other.offset)):
            raise ValueError("cannot merge sketches on different grids")
        self.hist += other.hist
        self.rows += other.rows
        return self

    # -- readouts ------------------------------------------------------

    def grid_values(self) -> np.ndarray:
        """(F, 255) feature value at each bucket center:
        q*scale + offset for q in [-127, 127]."""
        q = np.arange(-127, 128, dtype=np.float64)
        return (q[None, :] * self.scale[:, None].astype(np.float64)
                + self.offset[:, None].astype(np.float64))

    def moments(self) -> tuple[np.ndarray, np.ndarray]:
        """Per-feature (mean, variance) from the grid histogram — the
        streaming-moments readout (additive across merges by
        construction: summed counts ARE the parallel-Welford combine)."""
        n = self.hist.sum(axis=1).astype(np.float64)
        safe_n = np.maximum(n, 1.0)
        v = self.grid_values()
        s = (self.hist * v).sum(axis=1)
        ss = (self.hist * v * v).sum(axis=1)
        mean = s / safe_n
        var = np.maximum(ss / safe_n - mean * mean, 0.0)
        mean = np.where(n > 0, mean, 0.0)
        var = np.where(n > 1, var, 0.0)
        return mean, var

    # -- serialization -------------------------------------------------

    def to_dict(self) -> dict:
        mean, var = self.moments()
        return {
            "num_features": self.num_features,
            "rows": int(self.rows),
            "scale": [round(float(s), 8) for s in self.scale],
            "offset": [round(float(o), 8) for o in self.offset],
            "hist": self.hist.tolist(),
            "mean": [round(float(m), 6) for m in mean],
            "var": [round(float(v), 6) for v in var],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FeatureSketch":
        sk = cls(int(d["num_features"]),
                 scale=np.asarray(d["scale"], np.float32),
                 offset=np.asarray(d["offset"], np.float32))
        hist = np.asarray(d["hist"], np.int64)
        if hist.shape != sk.hist.shape:
            raise ValueError(f"histogram shape {hist.shape} does not "
                             f"match ({sk.num_features}, {N_BUCKETS})")
        sk.hist = hist
        sk.rows = int(d.get("rows", hist.sum(axis=1).max(initial=0)))
        return sk


class ScoreSketch:
    """Streaming sketch of the score distribution: a fixed-bin histogram
    over [0, 1] (sigmoid outputs) plus exact additive moments — the
    serving side of the score-KL drift axis and the profile's record of
    what the model's output looked like on the frozen epoch."""

    def __init__(self, bins: int = SCORE_BINS):
        self.bins = int(bins)
        self.hist = np.zeros(self.bins, np.int64)
        self.n = 0
        self.sum = 0.0
        self.sumsq = 0.0

    def update(self, scores: np.ndarray) -> None:
        s = np.asarray(scores, np.float64).ravel()
        if s.size == 0:
            return
        idx = np.clip((s * self.bins).astype(np.int64), 0, self.bins - 1)
        self.hist += np.bincount(idx, minlength=self.bins)
        self.n += int(s.size)
        self.sum += float(s.sum())
        self.sumsq += float((s * s).sum())

    def merge(self, other: "ScoreSketch") -> "ScoreSketch":
        if other.bins != self.bins:
            raise ValueError(f"cannot merge score sketches with "
                             f"different bins ({self.bins} vs "
                             f"{other.bins})")
        self.hist += other.hist
        self.n += other.n
        self.sum += other.sum
        self.sumsq += other.sumsq
        return self

    def mean(self) -> float:
        return self.sum / self.n if self.n else 0.0

    def var(self) -> float:
        if self.n < 2:
            return 0.0
        m = self.mean()
        return max(self.sumsq / self.n - m * m, 0.0)

    def to_dict(self) -> dict:
        return {"bins": self.bins, "n": int(self.n),
                "sum": round(self.sum, 6), "sumsq": round(self.sumsq, 6),
                "hist": self.hist.tolist()}

    @classmethod
    def from_dict(cls, d: dict) -> "ScoreSketch":
        sk = cls(int(d["bins"]))
        hist = np.asarray(d["hist"], np.int64)
        if hist.shape != sk.hist.shape:
            raise ValueError(f"score histogram has {hist.shape[0]} bins, "
                             f"expected {sk.bins}")
        sk.hist = hist
        sk.n = int(d.get("n", hist.sum()))
        sk.sum = float(d.get("sum", 0.0))
        sk.sumsq = float(d.get("sumsq", 0.0))
        return sk


# ------------------------------------------------------ divergence math


def _normalize(counts: np.ndarray) -> np.ndarray:
    """Counts -> epsilon-smoothed probabilities along the last axis."""
    c = np.asarray(counts, np.float64)
    total = c.sum(axis=-1, keepdims=True)
    p = c / np.maximum(total, 1.0) + _EPS
    return p / p.sum(axis=-1, keepdims=True)


def psi(expected_counts: np.ndarray, actual_counts: np.ndarray,
        groups: int = PSI_GROUPS) -> np.ndarray:
    """Population Stability Index per feature over rebinned buckets.

    Both inputs are (..., 255) fine-grid counts; the 255 buckets fold
    into `groups` coarse groups (255 = 17*15) before the classic
    ``sum((p - q) * ln(p / q))`` with epsilon smoothing — the smoothing
    bounds a group empty on one side instead of blowing up to inf.
    Returns a (...,) array (scalar-shaped for a single feature).  The
    conventional reading: < 0.1 stable, 0.1-0.25 moderate shift,
    > 0.25 significant."""
    e = np.asarray(expected_counts, np.float64)
    a = np.asarray(actual_counts, np.float64)
    if e.shape[-1] != a.shape[-1]:
        raise ValueError(f"bucket counts differ: {e.shape[-1]} vs "
                         f"{a.shape[-1]}")
    nb = e.shape[-1]
    if groups > 1 and nb % groups == 0:
        fold = nb // groups
        e = e.reshape(e.shape[:-1] + (groups, fold)).sum(axis=-1)
        a = a.reshape(a.shape[:-1] + (groups, fold)).sum(axis=-1)
    p = _normalize(e)
    q = _normalize(a)
    return ((q - p) * np.log(q / p)).sum(axis=-1)


def kl_divergence(p_counts: np.ndarray, q_counts: np.ndarray) -> float:
    """KL(p || q) over two same-shape count vectors with epsilon
    smoothing — the score-distribution drift axis (baseline || live)."""
    p = _normalize(np.asarray(p_counts, np.float64).ravel())
    q = _normalize(np.asarray(q_counts, np.float64).ravel())
    return float((p * np.log(p / q)).sum())


def mean_shift_sigmas(base_mean: np.ndarray, base_var: np.ndarray,
                      live_mean: np.ndarray) -> np.ndarray:
    """|live_mean - base_mean| in units of the baseline's per-feature
    std — the first-moment drift axis (cheap, interpretable, catches a
    pure translation even when PSI is diluted across buckets)."""
    sd = np.sqrt(np.maximum(np.asarray(base_var, np.float64), 0.0))
    sd = np.maximum(sd, _EPS)
    return np.abs(np.asarray(live_mean, np.float64)
                  - np.asarray(base_mean, np.float64)) / sd


# --------------------------------------------------- the frozen profile


def build_profile(features: FeatureSketch, score: ScoreSketch,
                  feature_names: Optional[Sequence[str]] = None,
                  train_auc: Optional[float] = None,
                  train_error: Optional[float] = None,
                  epoch: Optional[int] = None) -> dict:
    """The ``baseline_profile.json`` payload: the frozen stats epoch the
    drift engine diffs live traffic against.  JSON-serializable, fully
    self-describing (grid + histograms + moments + score sketch +
    training AUC), rebuildable into sketches via `profile_sketches`."""
    prof = {
        "kind": PROFILE_KIND,
        "version": PROFILE_VERSION,
        "num_features": features.num_features,
        "rows": int(features.rows),
        "features": features.to_dict(),
        "score": score.to_dict(),
    }
    if feature_names is not None:
        names = [str(n) for n in feature_names]
        if len(names) == features.num_features:
            prof["feature_names"] = names
    if train_auc is not None and not np.isnan(train_auc):
        prof["train_auc"] = round(float(train_auc), 6)
    if train_error is not None and not np.isnan(train_error):
        prof["train_error"] = round(float(train_error), 6)
    if epoch is not None:
        prof["epoch"] = int(epoch)
    return prof


def validate_profile(profile: dict) -> dict:
    """Structural check on a loaded baseline profile; returns it.
    Raises ValueError with a precise reason — the caller (drift plane)
    degrades to drift-disabled, never serves garbage comparisons."""
    if not isinstance(profile, dict):
        raise ValueError("baseline profile is not a JSON object")
    if profile.get("kind") != PROFILE_KIND:
        raise ValueError(f"not a baseline profile (kind="
                         f"{profile.get('kind')!r})")
    if int(profile.get("version", 0)) > PROFILE_VERSION:
        raise ValueError(f"baseline profile version "
                         f"{profile.get('version')} is newer than this "
                         f"reader ({PROFILE_VERSION})")
    for key in ("features", "score"):
        if key not in profile:
            raise ValueError(f"baseline profile missing {key!r}")
    return profile


def profile_sketches(profile: dict) -> tuple[FeatureSketch, ScoreSketch]:
    """Rebuild the (FeatureSketch, ScoreSketch) pair from a profile."""
    validate_profile(profile)
    return (FeatureSketch.from_dict(profile["features"]),
            ScoreSketch.from_dict(profile["score"]))


def profile_summary(profile: dict) -> dict:
    """Compact journal-safe summary of a profile (the per-epoch
    `baseline_profile` event body: no histograms, bounded bytes)."""
    feats = profile.get("features") or {}
    score = profile.get("score") or {}
    out = {
        "rows": int(profile.get("rows", 0)),
        "num_features": int(profile.get("num_features", 0)),
        "score_mean": round(float(score.get("sum", 0.0))
                            / max(int(score.get("n", 0)), 1), 6),
    }
    if "train_auc" in profile:
        out["train_auc"] = profile["train_auc"]
    if "train_error" in profile:
        out["train_error"] = profile["train_error"]
    if "epoch" in profile:
        out["epoch"] = profile["epoch"]
    means = feats.get("mean")
    if means:
        out["feature_mean_min"] = round(float(min(means)), 6)
        out["feature_mean_max"] = round(float(max(means)), 6)
    return out
