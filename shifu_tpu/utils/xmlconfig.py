"""Hadoop-style XML configuration ingestion (`shifu.*` key namespace).

Config-system parity with the reference (SURVEY.md section 5.6): the reference
layers baked-in `global-default.xml` <- user `-globalconfig` XML <-
programmatic keys, serializes `global-final.xml`, and ships it to every
container (reference: yarn/client/TensorflowClient.java:211-224,389-403; key
namespace yarn/util/GlobalConfigurationKeys.java:22-155).  Here the same XML
files parse into a flat dict and map onto the typed JobConfig; unknown keys
are preserved for forward-compat and re-serialized into the job dir's
`global-final.xml` equivalent.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from typing import Any, Mapping, Optional


def parse_bool(value: Any) -> bool:
    """Config bools arrive string-typed from XML and Shifu JSON params:
    'false'/'0'/'no' must read as False (bool('false') would be True)."""
    if isinstance(value, str):
        return value.strip().lower() in ("true", "1", "yes")
    return bool(value)

# reference key namespace (GlobalConfigurationKeys.java)
KEY_EPOCHS = "shifu.application.epochs"
KEY_TIMEOUT = "shifu.application.timeout"
KEY_TRAINING_DATA_PATH = "shifu.application.training-data-path"
KEY_TMP_MODEL_PATH = "shifu.application.tmp-model-path"
KEY_FINAL_MODEL_PATH = "shifu.application.final-model-path"
KEY_APP_NAME = "shifu.application.name"
KEY_WORKER_INSTANCES = "shifu.worker.instances"
KEY_PS_INSTANCES = "shifu.ps.instances"
KEY_BACKUP_INSTANCES = "shifu.worker.instances.backup"
KEY_BATCH_SIZE = "shifu.application.batch-size"
KEY_MAX_RESTARTS = "shifu.application.max-restarts"
# time-based checkpoint cadence (reference parity: Supervisor
# save_model_secs — ssgd.py:124-128)
KEY_CKPT_SAVE_SECONDS = "shifu.checkpoint.save-seconds"
KEY_HEARTBEAT_INTERVAL = "shifu.task.heartbeat-interval-ms"
KEY_MAX_MISSED_HEARTBEATS = "shifu.task.max-missed-heartbeats"
# supervisor hang detection: board-progress window in seconds (successor of
# the AM heartbeat monitor, TensorflowApplicationMaster.java:63-112).  The
# reference heartbeat pair above is deliberately NOT mapped here: its
# semantics (1s task heartbeat x misses) don't transfer to a per-epoch
# board heartbeat — a migrated config carrying the reference defaults
# (1000ms x 25) would false-kill any epoch longer than 25s
KEY_LIVENESS_SECONDS = "shifu.liveness.seconds"
# elastic reshape floor: drop a permanently failing pod host and restart
# the gang smaller, down to this many hosts (RuntimeConfig.min_hosts;
# successor of the reference's >=95%-of-workers degraded start,
# TensorflowApplicationMaster.java:230-338)
KEY_MIN_HOSTS = "shifu.pod.min-hosts"
# device mesh topology (successor of shifu.{ps,worker}.instances container
# counts: the logical axes the one SPMD program shards over)
KEY_MESH_DATA = "shifu.mesh.data"
KEY_MESH_MODEL = "shifu.mesh.model"
KEY_MESH_SEQ = "shifu.mesh.seq"
KEY_MESH_PIPE = "shifu.mesh.pipe"
# input-pipeline knobs (no reference analog: its loader was fixed-function)
# secured-HDFS auth (successor of the reference's Kerberos delegation
# tokens, TensorflowClient.java:481-502)
KEY_KERBEROS_PRINCIPAL = "shifu.security.kerberos.principal"
KEY_KERBEROS_KEYTAB = "shifu.security.kerberos.keytab"
# custom parameter sharding (tensor parallelism from config):
# "path-regex=axis[,axis...]" entries joined by ";"; axis "none"/"" = that
# dim unsharded.  Example: ".*hidden_layer0.*kernel.*=none,model"
KEY_SHARDING_RULES = "shifu.sharding.rules"
KEY_DATA_CACHE_DIR = "shifu.data.cache-dir"
# cache entry format generation (DataConfig.cache_format): 0 = latest
# (v2 wire-format entries), 1 pins the legacy v1 layout for mixed-version
# cache dirs (data/cache.py)
KEY_DATA_CACHE_FORMAT = "shifu.data.cache-format"
KEY_DATA_OUT_OF_CORE = "shifu.data.out-of-core"
KEY_DATA_STAGED = "shifu.data.staged"
KEY_DATA_READ_THREADS = "shifu.data.read-threads"
# cold-ingest parse pool width (DataConfig.ingest_workers; 0 = auto —
# one worker per file capped at cpu_count)
KEY_DATA_INGEST_WORKERS = "shifu.data.ingest-workers"
# HBM budget for the device-resident input tier (bytes); datasets above it
# use the staged-blocks tier
KEY_DATA_RESIDENT_BYTES = "shifu.data.device-resident-bytes"
# features-on-the-wire dtype: auto / float32 / bfloat16 / int8 (int8 = the
# quantized wire, data/pipeline.wire_params; clip in normalized units)
KEY_DATA_WIRE_DTYPE = "shifu.data.wire-dtype"
KEY_DATA_WIRE_INT8_CLIP = "shifu.data.wire-int8-clip"
# compact target/weight wire: label auto/uint8/float32, weight
# auto/elide/float32 (DataConfig.wire_label_dtype / wire_weight_mode)
KEY_DATA_WIRE_LABEL_DTYPE = "shifu.data.wire-label-dtype"
KEY_DATA_WIRE_WEIGHT_MODE = "shifu.data.wire-weight-mode"
# in-HBM format of the device-resident tier: auto / wire / int8
# (DataConfig.resident_format; int8 quantizes resident feature blocks to
# the wire_params grid — ops/pallas_int8_matmul fuses the dequant)
KEY_DATA_RESIDENT_FORMAT = "shifu.data.resident-format"
# fused transformer block for ft_transformer: auto / on / off
# (ModelSpec.fused_block, ops/pallas_ft_block)
KEY_MODEL_FUSED_BLOCK = "shifu.model.fused-block"
# host-side input-feeder queue depth (DataConfig.prefetch_depth; 0 = auto —
# resized per epoch from the goodput ledger's exposed-input measurement)
KEY_DATA_PREFETCH_DEPTH = "shifu.data.prefetch-depth"
# cross-epoch overlap engine on/off (DataConfig.overlap_epochs)
KEY_DATA_OVERLAP_EPOCHS = "shifu.data.overlap-epochs"
# rows-touched-only embedding optimizer updates: auto / on / off
# (TrainConfig.sparse_embedding_update, train/sparse_embed.py)
KEY_TRAIN_SPARSE_EMBED = "shifu.train.sparse-embedding-update"
# pod data plane: host shard-assignment mode auto / static / rotate
# (DataConfig.host_shard, data/pipeline.host_shard_assignment)
KEY_DATA_HOST_SHARD = "shifu.data.host-shard"
# minimum train_scaling_efficiency accepted by the pod scaling sweep
# (TrainConfig.scaling_gate; 0 disables)
KEY_TRAIN_SCALING_GATE = "shifu.train.scaling-gate"
# device flight recorder (ObsConfig — obs/devprof.py, docs/OBSERVABILITY.md
# "Device flight recorder"): trace-window schedule
# (off/first/every:N/comma-list), capture dir, rollup size, HBM watermark
# polling, and the anomaly detector's ring/threshold
KEY_EMBED_DEDUP = "shifu.embed.dedup"
KEY_EMBED_TIERING = "shifu.embed.tiering"
KEY_EMBED_TIER_DTYPE = "shifu.embed.tier-dtype"
KEY_EMBED_HOT_ROWS = "shifu.embed.hot-rows"
KEY_EMBED_HOT_FRACTION = "shifu.embed.hot-fraction"
KEY_EMBED_COLD_DIR = "shifu.embed.cold-dir"
KEY_EMBED_PREFETCH = "shifu.embed.prefetch"
KEY_OBS_TRACE_EPOCHS = "shifu.obs.trace-epochs"
KEY_OBS_TRACE_DIR = "shifu.obs.trace-dir"
KEY_OBS_TRACE_TOP_K = "shifu.obs.trace-top-k"
KEY_OBS_HBM_WATERMARKS = "shifu.obs.hbm-watermarks"
KEY_OBS_ANOMALY_WINDOW = "shifu.obs.anomaly-window"
KEY_OBS_ANOMALY_ZSCORE = "shifu.obs.anomaly-zscore"
# serving plane (ServingConfig — runtime/serve.py, docs/SERVING.md):
# the scoring daemon's engine tier, micro-batcher knobs (latency budget /
# batch bounds / padded-bucket floor), admission limit, worker count,
# report cadence, and the wire server's bind address.  Standalone config
# (serving_config_from_conf), not a JobConfig overlay: serving is driven
# from an export artifact, not a training job.
KEY_SERVING_ENGINE = "shifu.serving.engine"
KEY_SERVING_LATENCY_BUDGET_MS = "shifu.serving.latency-budget-ms"
KEY_SERVING_MAX_BATCH = "shifu.serving.max-batch"
KEY_SERVING_MIN_BATCH_BUCKET = "shifu.serving.min-batch-bucket"
KEY_SERVING_QUEUE_LIMIT = "shifu.serving.queue-limit"
KEY_SERVING_WORKERS = "shifu.serving.workers"
KEY_SERVING_REPORT_EVERY_S = "shifu.serving.report-every-s"
KEY_SERVING_PORT = "shifu.serving.port"
KEY_SERVING_HOST = "shifu.serving.host"
# serving SLO engine (obs/slo.py, docs/OBSERVABILITY.md "Serving SLO
# engine"): request_trace sampling stride (1-in-N, 0 off), the three
# objectives (p99 ms / error-rate fraction / availability fraction, 0
# disables each), and the multiwindow burn-rate knobs
KEY_SERVING_TRACE_SAMPLE = "shifu.serving.trace-sample"
# distributed tracing (obs/tracing.py): p99-exemplar count the loadtest
# report carries (trace_ids of the N slowest requests)
KEY_SERVING_TRACE_EXEMPLARS = "shifu.serving.trace-exemplars"
KEY_SERVING_SLO_P99_MS = "shifu.serving.slo.p99-ms"
KEY_SERVING_SLO_ERROR_RATE = "shifu.serving.slo.error-rate"
KEY_SERVING_SLO_AVAILABILITY = "shifu.serving.slo.availability"
KEY_SERVING_SLO_FAST_WINDOW_S = "shifu.serving.slo.fast-window-s"
KEY_SERVING_SLO_SLOW_WINDOW_S = "shifu.serving.slo.slow-window-s"
KEY_SERVING_SLO_BURN_THRESHOLD = "shifu.serving.slo.burn-threshold"
# cold-start plane (export/aot.py, docs/SERVING.md "Cold start & AOT
# pack"): export-time AOT executable packing opt-in, and the
# full-ladder pre-warm a load/swap runs before its pointer flips
KEY_SERVING_AOT_PACK = "shifu.serving.aot-pack"
KEY_SERVING_PREWARM_LADDER = "shifu.serving.prewarm-ladder"
# drift observatory (DriftConfig nested under ServingConfig —
# obs/drift.py, docs/OBSERVABILITY.md "Drift observatory"): kill
# switch, fast/slow trailing windows, per-feature PSI + score-KL
# thresholds, worst-feature fan-out, minimum-rows gate, and the
# labeled-feedback (live AUC) path
KEY_DRIFT_ENABLED = "shifu.drift.enabled"
KEY_DRIFT_FAST_WINDOW_S = "shifu.drift.fast-window-s"
KEY_DRIFT_SLOW_WINDOW_S = "shifu.drift.slow-window-s"
KEY_DRIFT_PSI_THRESHOLD = "shifu.drift.psi-threshold"
KEY_DRIFT_SCORE_KL_THRESHOLD = "shifu.drift.score-kl-threshold"
KEY_DRIFT_TOP_K = "shifu.drift.top-k"
KEY_DRIFT_MIN_ROWS = "shifu.drift.min-rows"
KEY_DRIFT_FEEDBACK = "shifu.drift.feedback"
KEY_DRIFT_FEEDBACK_BINS = "shifu.drift.feedback-bins"
# serving fleet (FleetConfig — runtime/fleet.py, docs/SERVING.md "Fleet"):
# member/standby counts, heartbeat lease cadence + miss tolerance, the
# router's per-request/connect timeouts + reconnect backoff + overload
# shed threshold, and the burn-rate scale loop's windows and bounds
KEY_FLEET_N_DAEMONS = "shifu.fleet.n-daemons"
KEY_FLEET_STANDBYS = "shifu.fleet.standbys"
KEY_FLEET_HEARTBEAT_EVERY_S = "shifu.fleet.heartbeat-every-s"
KEY_FLEET_HEARTBEAT_MISSES = "shifu.fleet.heartbeat-misses"
KEY_FLEET_ROUTE_TIMEOUT_MS = "shifu.fleet.route-timeout-ms"
KEY_FLEET_CONNECT_TIMEOUT_MS = "shifu.fleet.connect-timeout-ms"
KEY_FLEET_SHED_BURN = "shifu.fleet.shed-burn"
KEY_FLEET_BACKOFF_BASE_MS = "shifu.fleet.backoff-base-ms"
KEY_FLEET_BACKOFF_CAP_MS = "shifu.fleet.backoff-cap-ms"
KEY_FLEET_SCALE_EVERY_S = "shifu.fleet.scale-every-s"
KEY_FLEET_SCALE_UP_BURN = "shifu.fleet.scale-up-burn"
KEY_FLEET_SCALE_DOWN_BURN = "shifu.fleet.scale-down-burn"
KEY_FLEET_SCALE_COOLDOWN_S = "shifu.fleet.scale-cooldown-s"
KEY_FLEET_MIN_DAEMONS = "shifu.fleet.min-daemons"
KEY_FLEET_MAX_DAEMONS = "shifu.fleet.max-daemons"
KEY_FLEET_VNODES = "shifu.fleet.vnodes"
KEY_FLEET_HOSTS = "shifu.fleet.hosts"
KEY_FLEET_MEMBER_MODE = "shifu.fleet.member-mode"
KEY_FLEET_MEMBER_PORT_BASE = "shifu.fleet.member-port-base"
KEY_FLEET_SYNC_ARTIFACTS = "shifu.fleet.sync-artifacts"
KEY_FLEET_REJOIN_STANDBY = "shifu.fleet.rejoin-standby"
# fleet timeline (obs/timeline.py): skew-corrected journal merge on/off
# and the clamp on any single host's estimated clock offset
KEY_FLEET_TIMELINE_SKEW_CORRECT = "shifu.fleet.timeline-skew-correct"
KEY_FLEET_TIMELINE_MAX_OFFSET_S = "shifu.fleet.timeline-max-offset-s"


def parse_sharding_rules(value: str) -> tuple:
    """Parse KEY_SHARDING_RULES: ';'-joined "regex=axis[,axis...]" entries
    into ((regex, (axis|None, ...)), ...) for RuntimeConfig.param_sharding_rules.

    '=' may appear inside the regex — the LAST '=' splits pattern from axes.
    Axis 'none' (any case) or '' means that dimension stays unsharded.
    """
    rules = []
    for entry in value.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise ValueError(
                f"sharding rule {entry!r}: expected 'regex=axis[,axis...]'")
        pattern, _, axes_s = entry.rpartition("=")
        axes = tuple(None if a.strip().lower() in ("", "none") else a.strip()
                     for a in axes_s.split(","))
        rules.append((pattern.strip(), axes))
    return tuple(rules)


def parse_configuration_xml(path: str) -> dict[str, str]:
    """Parse one Hadoop `<configuration><property><name/><value/>` file.

    Tolerates the reference's quirk of concatenated XML documents in one file
    (global-default-bk.xml:183-188 contains two) by parsing only the first
    document and ignoring trailing garbage.
    """
    with open(path, "r") as f:
        text = f.read()
    # first <configuration>...</configuration> document only
    start = text.find("<configuration")
    if start < 0:
        raise ValueError(f"{path}: no <configuration> element")
    end = text.find("</configuration>", start)
    if end < 0:
        raise ValueError(f"{path}: unterminated <configuration>")
    doc = text[start:end + len("</configuration>")]
    root = ET.fromstring(doc)
    out: dict[str, str] = {}
    for prop in root.iter("property"):
        name = prop.findtext("name")
        value = prop.findtext("value")
        if name is not None and value is not None:
            out[name.strip()] = value.strip()
    return out


def layer_configs(*dicts: Mapping[str, str]) -> dict[str, str]:
    """Later dicts win — the reference's default <- user <- programmatic order."""
    merged: dict[str, str] = {}
    for d in dicts:
        merged.update(d)
    return merged


def configuration_xml_bytes(config: Mapping[str, str]) -> bytes:
    """The serialized XML as bytes — for remote (fsio) job dirs."""
    import io
    root = ET.Element("configuration")
    for name in sorted(config):
        prop = ET.SubElement(root, "property")
        ET.SubElement(prop, "name").text = name
        ET.SubElement(prop, "value").text = str(config[name])
    tree = ET.ElementTree(root)
    ET.indent(tree)
    buf = io.BytesIO()
    tree.write(buf, encoding="utf-8", xml_declaration=True)
    return buf.getvalue()


def write_configuration_xml(config: Mapping[str, str], path: str) -> None:
    """Serialize the merged config (the `global-final.xml` the reference wrote
    and localized into every container, TensorflowClient.java:389-403)."""
    with open(path, "wb") as f:
        f.write(configuration_xml_bytes(config))


def serving_config_from_conf(conf: Mapping[str, str], base: Any = None) -> Any:
    """ServingConfig from `shifu.serving.*` keys over `base` (default: the
    dataclass defaults) — the serving-plane sibling of apply_to_job, used
    by `shifu-tpu serve` with CLI flags layered on top."""
    import dataclasses

    from ..config.schema import ServingConfig

    base = base or ServingConfig()
    kw: dict[str, Any] = {}
    if KEY_SERVING_ENGINE in conf:
        kw["engine"] = conf[KEY_SERVING_ENGINE].strip().lower()
    if KEY_SERVING_LATENCY_BUDGET_MS in conf:
        kw["latency_budget_ms"] = float(conf[KEY_SERVING_LATENCY_BUDGET_MS])
    if KEY_SERVING_MAX_BATCH in conf:
        kw["max_batch"] = int(conf[KEY_SERVING_MAX_BATCH])
    if KEY_SERVING_MIN_BATCH_BUCKET in conf:
        kw["min_batch_bucket"] = int(conf[KEY_SERVING_MIN_BATCH_BUCKET])
    if KEY_SERVING_QUEUE_LIMIT in conf:
        kw["queue_limit"] = int(conf[KEY_SERVING_QUEUE_LIMIT])
    if KEY_SERVING_WORKERS in conf:
        kw["workers"] = int(conf[KEY_SERVING_WORKERS])
    if KEY_SERVING_REPORT_EVERY_S in conf:
        kw["report_every_s"] = float(conf[KEY_SERVING_REPORT_EVERY_S])
    if KEY_SERVING_PORT in conf:
        kw["port"] = int(conf[KEY_SERVING_PORT])
    if KEY_SERVING_HOST in conf:
        kw["host"] = conf[KEY_SERVING_HOST].strip()
    if KEY_SERVING_TRACE_SAMPLE in conf:
        kw["trace_sample"] = int(conf[KEY_SERVING_TRACE_SAMPLE])
    if KEY_SERVING_TRACE_EXEMPLARS in conf:
        kw["trace_exemplars"] = int(conf[KEY_SERVING_TRACE_EXEMPLARS])
    if KEY_SERVING_SLO_P99_MS in conf:
        kw["slo_p99_ms"] = float(conf[KEY_SERVING_SLO_P99_MS])
    if KEY_SERVING_SLO_ERROR_RATE in conf:
        kw["slo_error_rate"] = float(conf[KEY_SERVING_SLO_ERROR_RATE])
    if KEY_SERVING_SLO_AVAILABILITY in conf:
        kw["slo_availability"] = float(conf[KEY_SERVING_SLO_AVAILABILITY])
    if KEY_SERVING_SLO_FAST_WINDOW_S in conf:
        kw["slo_fast_window_s"] = float(conf[KEY_SERVING_SLO_FAST_WINDOW_S])
    if KEY_SERVING_SLO_SLOW_WINDOW_S in conf:
        kw["slo_slow_window_s"] = float(conf[KEY_SERVING_SLO_SLOW_WINDOW_S])
    if KEY_SERVING_SLO_BURN_THRESHOLD in conf:
        kw["slo_burn_threshold"] = float(
            conf[KEY_SERVING_SLO_BURN_THRESHOLD])
    if KEY_SERVING_AOT_PACK in conf:
        kw["aot_pack"] = parse_bool(conf[KEY_SERVING_AOT_PACK])
    if KEY_SERVING_PREWARM_LADDER in conf:
        kw["prewarm_ladder"] = parse_bool(conf[KEY_SERVING_PREWARM_LADDER])
    drift = drift_config_from_conf(conf, base.drift)
    if drift is not base.drift:
        kw["drift"] = drift
    return dataclasses.replace(base, **kw) if kw else base


def drift_config_from_conf(conf: Mapping[str, str], base: Any = None) -> Any:
    """DriftConfig from `shifu.drift.*` keys over `base` (default: the
    dataclass defaults) — called by serving_config_from_conf so serve,
    fleet members and loadtest all see the same drift knobs."""
    import dataclasses

    from ..config.schema import DriftConfig

    base = base or DriftConfig()
    kw: dict[str, Any] = {}
    _float_keys = {KEY_DRIFT_FAST_WINDOW_S: "fast_window_s",
                   KEY_DRIFT_SLOW_WINDOW_S: "slow_window_s",
                   KEY_DRIFT_PSI_THRESHOLD: "psi_threshold",
                   KEY_DRIFT_SCORE_KL_THRESHOLD: "score_kl_threshold"}
    _int_keys = {KEY_DRIFT_TOP_K: "top_k",
                 KEY_DRIFT_MIN_ROWS: "min_rows",
                 KEY_DRIFT_FEEDBACK_BINS: "feedback_bins"}
    _bool_keys = {KEY_DRIFT_ENABLED: "enabled",
                  KEY_DRIFT_FEEDBACK: "feedback"}
    for key, field in _float_keys.items():
        if key in conf:
            kw[field] = float(conf[key])
    for key, field in _int_keys.items():
        if key in conf:
            kw[field] = int(conf[key])
    for key, field in _bool_keys.items():
        if key in conf:
            kw[field] = parse_bool(conf[key])
    return dataclasses.replace(base, **kw) if kw else base


def fleet_config_from_conf(conf: Mapping[str, str], base: Any = None) -> Any:
    """FleetConfig from `shifu.fleet.*` keys over `base` (default: the
    dataclass defaults) — `shifu-tpu fleet` layers CLI flags on top of
    this exactly like serve does with serving_config_from_conf."""
    import dataclasses

    from ..config.schema import FleetConfig

    base = base or FleetConfig()
    kw: dict[str, Any] = {}
    _int_keys = {KEY_FLEET_N_DAEMONS: "n_daemons",
                 KEY_FLEET_STANDBYS: "standbys",
                 KEY_FLEET_HEARTBEAT_MISSES: "heartbeat_misses",
                 KEY_FLEET_MIN_DAEMONS: "min_daemons",
                 KEY_FLEET_MAX_DAEMONS: "max_daemons",
                 KEY_FLEET_VNODES: "vnodes",
                 KEY_FLEET_MEMBER_PORT_BASE: "member_port_base"}
    _float_keys = {KEY_FLEET_HEARTBEAT_EVERY_S: "heartbeat_every_s",
                   KEY_FLEET_ROUTE_TIMEOUT_MS: "route_timeout_ms",
                   KEY_FLEET_CONNECT_TIMEOUT_MS: "connect_timeout_ms",
                   KEY_FLEET_SHED_BURN: "shed_burn",
                   KEY_FLEET_BACKOFF_BASE_MS: "backoff_base_ms",
                   KEY_FLEET_BACKOFF_CAP_MS: "backoff_cap_ms",
                   KEY_FLEET_SCALE_EVERY_S: "scale_every_s",
                   KEY_FLEET_SCALE_UP_BURN: "scale_up_burn",
                   KEY_FLEET_SCALE_DOWN_BURN: "scale_down_burn",
                   KEY_FLEET_SCALE_COOLDOWN_S: "scale_cooldown_s",
                   KEY_FLEET_TIMELINE_MAX_OFFSET_S:
                       "timeline_max_offset_s"}
    for key, field in _int_keys.items():
        if key in conf:
            kw[field] = int(conf[key])
    _str_keys = {KEY_FLEET_HOSTS: "hosts",
                 KEY_FLEET_MEMBER_MODE: "member_mode"}
    _bool_keys = {KEY_FLEET_SYNC_ARTIFACTS: "sync_artifacts",
                  KEY_FLEET_REJOIN_STANDBY: "rejoin_standby",
                  KEY_FLEET_TIMELINE_SKEW_CORRECT:
                      "timeline_skew_correct"}
    for key, field in _float_keys.items():
        if key in conf:
            kw[field] = float(conf[key])
    for key, field in _str_keys.items():
        if key in conf:
            kw[field] = str(conf[key]).strip()
    for key, field in _bool_keys.items():
        if key in conf:
            kw[field] = parse_bool(conf[key])
    return dataclasses.replace(base, **kw) if kw else base


def apply_to_job(job: Any, conf: Mapping[str, str]) -> Any:
    """Overlay `shifu.*` keys onto a JobConfig (returns a new JobConfig)."""
    from ..config.schema import CheckpointConfig, RuntimeConfig

    train = job.train
    data = job.data
    runtime = job.runtime

    if KEY_EPOCHS in conf:
        import dataclasses
        # replace, not field-by-field reconstruction: an explicit list here
        # silently dropped newer TrainConfig fields (early stopping) when
        # the epochs key was set
        train = dataclasses.replace(train, epochs=int(conf[KEY_EPOCHS]))
    if KEY_BATCH_SIZE in conf:
        import dataclasses
        data = dataclasses.replace(data, batch_size=int(conf[KEY_BATCH_SIZE]))
    if KEY_TRAINING_DATA_PATH in conf and not data.paths:
        import dataclasses
        data = dataclasses.replace(
            data, paths=tuple(conf[KEY_TRAINING_DATA_PATH].split(",")))
    if KEY_DATA_CACHE_DIR in conf:
        import dataclasses
        data = dataclasses.replace(data, cache_dir=conf[KEY_DATA_CACHE_DIR])
    if KEY_DATA_CACHE_FORMAT in conf:
        import dataclasses
        data = dataclasses.replace(
            data, cache_format=int(conf[KEY_DATA_CACHE_FORMAT]))
    if KEY_DATA_INGEST_WORKERS in conf:
        import dataclasses
        data = dataclasses.replace(
            data, ingest_workers=int(conf[KEY_DATA_INGEST_WORKERS]))
    if KEY_DATA_OUT_OF_CORE in conf:
        import dataclasses
        data = dataclasses.replace(
            data, out_of_core=parse_bool(conf[KEY_DATA_OUT_OF_CORE]))
    if KEY_DATA_RESIDENT_BYTES in conf:
        import dataclasses
        data = dataclasses.replace(
            data, device_resident_bytes=int(conf[KEY_DATA_RESIDENT_BYTES]))
    if KEY_DATA_STAGED in conf:
        import dataclasses
        data = dataclasses.replace(
            data, staged=parse_bool(conf[KEY_DATA_STAGED]))
    if KEY_DATA_READ_THREADS in conf:
        import dataclasses
        data = dataclasses.replace(
            data, read_threads=int(conf[KEY_DATA_READ_THREADS]))
    if KEY_DATA_WIRE_DTYPE in conf:
        import dataclasses
        data = dataclasses.replace(
            data, wire_dtype=conf[KEY_DATA_WIRE_DTYPE].strip().lower())
    if KEY_DATA_WIRE_INT8_CLIP in conf:
        import dataclasses
        data = dataclasses.replace(
            data, wire_int8_clip=float(conf[KEY_DATA_WIRE_INT8_CLIP]))
    if KEY_DATA_WIRE_LABEL_DTYPE in conf:
        import dataclasses
        data = dataclasses.replace(
            data,
            wire_label_dtype=conf[KEY_DATA_WIRE_LABEL_DTYPE].strip().lower())
    if KEY_DATA_WIRE_WEIGHT_MODE in conf:
        import dataclasses
        data = dataclasses.replace(
            data,
            wire_weight_mode=conf[KEY_DATA_WIRE_WEIGHT_MODE].strip().lower())
    if KEY_DATA_RESIDENT_FORMAT in conf:
        import dataclasses
        data = dataclasses.replace(
            data,
            resident_format=conf[KEY_DATA_RESIDENT_FORMAT].strip().lower())
    if KEY_DATA_PREFETCH_DEPTH in conf:
        import dataclasses
        data = dataclasses.replace(
            data, prefetch_depth=int(conf[KEY_DATA_PREFETCH_DEPTH]))
    if KEY_DATA_OVERLAP_EPOCHS in conf:
        import dataclasses
        data = dataclasses.replace(
            data, overlap_epochs=parse_bool(conf[KEY_DATA_OVERLAP_EPOCHS]))
    if KEY_TRAIN_SPARSE_EMBED in conf:
        import dataclasses
        train = dataclasses.replace(
            train, sparse_embedding_update=(
                conf[KEY_TRAIN_SPARSE_EMBED].strip().lower()))
    if KEY_DATA_HOST_SHARD in conf:
        import dataclasses
        data = dataclasses.replace(
            data, host_shard=conf[KEY_DATA_HOST_SHARD].strip().lower())
    if KEY_TRAIN_SCALING_GATE in conf:
        import dataclasses
        train = dataclasses.replace(
            train, scaling_gate=float(conf[KEY_TRAIN_SCALING_GATE]))

    import dataclasses
    obs_kw: dict[str, Any] = {}
    if KEY_OBS_TRACE_EPOCHS in conf:
        obs_kw["trace_epochs"] = conf[KEY_OBS_TRACE_EPOCHS].strip().lower()
    if KEY_OBS_TRACE_DIR in conf:
        obs_kw["trace_dir"] = conf[KEY_OBS_TRACE_DIR]
    if KEY_OBS_TRACE_TOP_K in conf:
        obs_kw["trace_top_k"] = int(conf[KEY_OBS_TRACE_TOP_K])
    if KEY_OBS_HBM_WATERMARKS in conf:
        obs_kw["hbm_watermarks"] = parse_bool(conf[KEY_OBS_HBM_WATERMARKS])
    if KEY_OBS_ANOMALY_WINDOW in conf:
        obs_kw["anomaly_window"] = int(conf[KEY_OBS_ANOMALY_WINDOW])
    if KEY_OBS_ANOMALY_ZSCORE in conf:
        obs_kw["anomaly_zscore"] = float(conf[KEY_OBS_ANOMALY_ZSCORE])
    embed_kw: dict[str, Any] = {}
    if KEY_EMBED_DEDUP in conf:
        embed_kw["dedup"] = conf[KEY_EMBED_DEDUP].strip().lower()
    if KEY_EMBED_TIERING in conf:
        embed_kw["tiering"] = conf[KEY_EMBED_TIERING].strip().lower()
    if KEY_EMBED_TIER_DTYPE in conf:
        embed_kw["tier_dtype"] = conf[KEY_EMBED_TIER_DTYPE].strip().lower()
    if KEY_EMBED_HOT_ROWS in conf:
        embed_kw["hot_rows"] = int(conf[KEY_EMBED_HOT_ROWS])
    if KEY_EMBED_HOT_FRACTION in conf:
        embed_kw["hot_fraction"] = float(conf[KEY_EMBED_HOT_FRACTION])
    if KEY_EMBED_COLD_DIR in conf:
        embed_kw["cold_dir"] = conf[KEY_EMBED_COLD_DIR]
    if KEY_EMBED_PREFETCH in conf:
        embed_kw["prefetch"] = parse_bool(conf[KEY_EMBED_PREFETCH])
    rt_kw: dict[str, Any] = {}
    if KEY_TIMEOUT in conf:
        # reference timeout is milliseconds (client-side kill,
        # TensorflowClient.java:625-658)
        rt_kw["timeout_seconds"] = int(int(conf[KEY_TIMEOUT]) / 1000)
    if KEY_APP_NAME in conf:
        rt_kw["app_name"] = conf[KEY_APP_NAME]
    if KEY_FINAL_MODEL_PATH in conf:
        rt_kw["final_model_path"] = conf[KEY_FINAL_MODEL_PATH]
    if KEY_TMP_MODEL_PATH in conf:
        rt_kw["tmp_model_path"] = conf[KEY_TMP_MODEL_PATH]
        ck = dataclasses.replace(runtime.checkpoint,
                                 directory=conf[KEY_TMP_MODEL_PATH])
        rt_kw["checkpoint"] = ck
    if KEY_MAX_RESTARTS in conf:
        rt_kw["max_restarts"] = int(conf[KEY_MAX_RESTARTS])
    if KEY_MIN_HOSTS in conf:
        rt_kw["min_hosts"] = int(conf[KEY_MIN_HOSTS])
    if KEY_LIVENESS_SECONDS in conf:
        rt_kw["liveness_seconds"] = float(conf[KEY_LIVENESS_SECONDS])
    if KEY_CKPT_SAVE_SECONDS in conf:
        ck = rt_kw.get("checkpoint", runtime.checkpoint)
        rt_kw["checkpoint"] = dataclasses.replace(
            ck, save_every_seconds=int(conf[KEY_CKPT_SAVE_SECONDS]))
    if KEY_KERBEROS_PRINCIPAL in conf:
        rt_kw["kerberos_principal"] = conf[KEY_KERBEROS_PRINCIPAL]
    if KEY_KERBEROS_KEYTAB in conf:
        rt_kw["kerberos_keytab"] = conf[KEY_KERBEROS_KEYTAB]
    if KEY_SHARDING_RULES in conf:
        rt_kw["param_sharding_rules"] = parse_sharding_rules(
            conf[KEY_SHARDING_RULES])
    if (KEY_MESH_DATA in conf or KEY_MESH_MODEL in conf
            or KEY_MESH_SEQ in conf or KEY_MESH_PIPE in conf):
        rt_kw["mesh"] = dataclasses.replace(
            runtime.mesh,
            data=int(conf.get(KEY_MESH_DATA, runtime.mesh.data)),
            model=int(conf.get(KEY_MESH_MODEL, runtime.mesh.model)),
            seq=int(conf.get(KEY_MESH_SEQ, runtime.mesh.seq)),
            pipe=int(conf.get(KEY_MESH_PIPE, runtime.mesh.pipe)))
    if rt_kw:
        runtime = dataclasses.replace(runtime, **rt_kw)

    extra_kw: dict[str, Any] = {}
    if KEY_MODEL_FUSED_BLOCK in conf:
        import dataclasses
        extra_kw["model"] = dataclasses.replace(
            job.model,
            fused_block=conf[KEY_MODEL_FUSED_BLOCK].strip().lower())
    if obs_kw:
        # only touch `obs` when an obs key is actually set: job-shaped
        # stubs (and older serialized configs) without the field keep
        # working through the no-obs path
        from ..config.schema import ObsConfig
        base = getattr(job, "obs", None)
        extra_kw["obs"] = (dataclasses.replace(base, **obs_kw)
                           if base is not None else ObsConfig(**obs_kw))
    if embed_kw:
        # same pattern for the sparse embedding engine's group
        from ..config.schema import EmbedConfig
        base = getattr(job, "embed", None)
        extra_kw["embed"] = (dataclasses.replace(base, **embed_kw)
                             if base is not None else EmbedConfig(**embed_kw))
    return job.replace(train=train, data=data, runtime=runtime, **extra_kw)
