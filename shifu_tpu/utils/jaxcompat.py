"""Compatibility shims across the jax versions this repo meets in the wild.

`shard_map` graduated from `jax.experimental.shard_map` (kw `check_rep`)
to top-level `jax.shard_map` (kw `check_vma`); images pinned to jax 0.4.x
only carry the experimental spelling, and calling the missing top-level
name raises AttributeError deep inside model build.  One resolver keeps
every call site on the modern signature.
"""

from __future__ import annotations


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    import jax

    native = getattr(jax, "shard_map", None)
    if native is not None:
        return native(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as legacy
    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)
