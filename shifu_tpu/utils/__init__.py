from . import xmlconfig

__all__ = ["xmlconfig"]
