"""Persistent XLA compilation cache for job processes.

Every `train` CLI invocation (and every supervisor restart attempt — the
checkpoint-restart fault-tolerance story launches a fresh process per
attempt) retraces and recompiles the same programs; the reference paid the
same tax re-building its TF graph on every container start.  Pointing JAX's
persistent compilation cache at a stable directory turns those repeat
compiles into sub-second deserializations (measured ~3.1s -> ~1.5s for the
staged epoch program on a v5e chip).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

ENV_DISABLE = "SHIFU_TPU_NO_COMPILE_CACHE"
ENV_DIR = "JAX_COMPILATION_CACHE_DIR"
DEFAULT_DIR = "~/.cache/shifu_tpu/xla"

# persistent-cache observation state (obs/introspect.py classifies each
# XLA compile as hit/miss from the entry-set delta): the directory the
# cache was enabled at, and the entries seen at the last observation
_lock = threading.Lock()
_active_dir: Optional[str] = None
_seen_entries: frozenset[str] = frozenset()


def active_dir() -> Optional[str]:
    """The persistent-cache directory in use this process, or None."""
    return _active_dir


def _list_entries(path: str) -> frozenset[str]:
    try:
        return frozenset(os.listdir(path))
    except OSError:
        return frozenset()


def observe_compile() -> str:
    """Classify the XLA compile that just finished against the
    persistent cache: "off" (cache disabled), "miss" (a new cache entry
    appeared — this compile was real work, now persisted), or "hit"
    (no new entry: either deserialized from the cache or below the
    persistence thresholds — small/fast programs are never written, so
    "hit" is an upper bound; docs/OBSERVABILITY.md).  Updates the seen
    set so back-to-back compiles classify independently."""
    global _seen_entries
    if _active_dir is None:
        return "off"
    with _lock:
        now = _list_entries(_active_dir)
        fresh = now - _seen_entries
        _seen_entries = now
    return "miss" if fresh else "hit"


def enable_persistent_cache(directory: str | None = None,
                            min_compile_time_secs: float = 0.5
                            ) -> str | None:
    """Enable JAX's persistent compilation cache (idempotent, best-effort).

    Precedence: explicit `directory` > JAX_COMPILATION_CACHE_DIR env >
    the default under ~/.cache.  SHIFU_TPU_NO_COMPILE_CACHE=1 disables.
    Returns the directory in use, or None when disabled/unavailable.

    `min_compile_time_secs` is the persistence floor: compiles faster
    than this are never written.  The 0.5s default fits the TRAIN path
    (multi-second epoch programs; skipping tiny helper jits keeps the
    cache dir from filling with entries that cost more to look up than
    to recompile).  The SERVING paths pass 0: the padded-bucket scorer
    programs compile in tens of milliseconds each, exactly the band the
    default silently skips — and a fleet member's cold-start is the sum
    of those "too small to persist" compiles.  Tradeoff of 0: every
    compile writes an entry, so the cache dir grows with each distinct
    shape; acceptable for the bounded bucket ladder, wasteful for
    unbounded-shape workloads."""
    if os.environ.get(ENV_DISABLE):
        return None
    path = directory or os.environ.get(ENV_DIR) or os.path.expanduser(
        DEFAULT_DIR)
    global _active_dir, _seen_entries
    try:
        os.makedirs(path, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", path)
        # default thresholds skip small/fast programs; job programs are the
        # multi-second compiles this cache exists for, serving bucket
        # programs the sub-second ones (callers pick the floor)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_secs))
        with _lock:
            _active_dir = path
            _seen_entries = _list_entries(path)
    except Exception:
        return None  # cache is an optimization, never a failure
    try:
        from .. import obs
        # registry-only (sinks are usually configured later in run_train):
        # the scrape file records whether repeat compiles could deserialize
        obs.gauge("compile_cache_enabled",
                  "1 when the persistent XLA compile cache is active").set(1)
        obs.event("compile_cache", directory=path)
    except Exception:
        pass
    return path
