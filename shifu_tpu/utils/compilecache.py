"""Persistent XLA compilation cache for job processes.

Every `train` CLI invocation (and every supervisor restart attempt — the
checkpoint-restart fault-tolerance story launches a fresh process per
attempt) retraces and recompiles the same programs; the reference paid the
same tax re-building its TF graph on every container start.  Pointing JAX's
persistent compilation cache at a stable directory turns those repeat
compiles into sub-second deserializations (measured ~3.1s -> ~1.5s for the
staged epoch program on a v5e chip).
"""

from __future__ import annotations

import os

ENV_DISABLE = "SHIFU_TPU_NO_COMPILE_CACHE"
ENV_DIR = "JAX_COMPILATION_CACHE_DIR"
DEFAULT_DIR = "~/.cache/shifu_tpu/xla"


def enable_persistent_cache(directory: str | None = None) -> str | None:
    """Enable JAX's persistent compilation cache (idempotent, best-effort).

    Precedence: explicit `directory` > JAX_COMPILATION_CACHE_DIR env >
    the default under ~/.cache.  SHIFU_TPU_NO_COMPILE_CACHE=1 disables.
    Returns the directory in use, or None when disabled/unavailable.
    """
    if os.environ.get(ENV_DISABLE):
        return None
    path = directory or os.environ.get(ENV_DIR) or os.path.expanduser(
        DEFAULT_DIR)
    try:
        os.makedirs(path, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", path)
        # default thresholds skip small/fast programs; job programs are the
        # multi-second compiles this cache exists for
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        return None  # cache is an optimization, never a failure
    try:
        from .. import obs
        # registry-only (sinks are usually configured later in run_train):
        # the scrape file records whether repeat compiles could deserialize
        obs.gauge("compile_cache_enabled",
                  "1 when the persistent XLA compile cache is active").set(1)
        obs.event("compile_cache", directory=path)
    except Exception:
        pass
    return path
