"""Detached job submission: jobs that survive the submitting client.

A reference job ran under YARN and outlived its client — the client merely
polled the application report every 10s and tailed the progress log
(yarn/client/TensorflowClient.java:625-658,829-841); an operator could
disconnect and come back.  The pod/ssh gang here is deliberately tethered
to its dispatcher (parent death tears the gang down), so `train --detach`
re-launches the dispatcher as a session-leader daemon whose stdout goes to
`<job>/supervisor.log`, records `<job>/job.json`, and returns immediately;
the daemon writes `<job>/job.status` when the job ends.  `status`,
`attach`, and `kill` drive the job from its directory afterwards — the
poll/tail/kill surface the reference client had.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Optional, Sequence

# marks the daemonized dispatcher so run_train records its final status
ENV_DETACHED = "SHIFU_TPU_DETACHED_JOB_DIR"

JOB_FILE = "job.json"
STATUS_FILE = "job.status"
LOG_FILE = "supervisor.log"
BOARD_FILE = "console.board"


def submit(child_argv: Sequence[str], out_dir: str, echo=print) -> int:
    """Launch `python -m shifu_tpu.launcher.cli <child_argv>` as a detached
    session leader and return immediately (exit 0 = submitted)."""
    try:
        from ..data import fsio
        if fsio.is_remote(out_dir):
            echo("--detach needs a LOCAL job dir (job.json/pid live beside "
                 "the daemon); use a local --output whose board/checkpoint "
                 "paths may still be remote", )
            return 1
    except Exception:
        pass
    os.makedirs(out_dir, exist_ok=True)
    log_path = os.path.join(out_dir, LOG_FILE)
    env = dict(os.environ)
    env[ENV_DETACHED] = out_dir
    with open(log_path, "ab") as log:
        proc = subprocess.Popen(
            [sys.executable, "-m", "shifu_tpu.launcher.cli", *child_argv],
            stdout=log, stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL,
            start_new_session=True,  # survives the client's session/terminal
            env=env, cwd=os.getcwd())
    with open(os.path.join(out_dir, JOB_FILE), "w") as f:
        json.dump({"pid": proc.pid, "argv": list(child_argv),
                   "submitted_at": time.time(),
                   "host": os.uname().nodename}, f)
    echo(f"submitted: pid {proc.pid}, job dir {out_dir}")
    echo(f"  follow:  shifu-tpu attach {out_dir}")
    echo(f"  status:  shifu-tpu status {out_dir}")
    echo(f"  stop:    shifu-tpu kill {out_dir}")
    return 0


def write_status(out_dir: str, exit_code: int) -> None:
    """Called by the daemonized dispatcher when the job ends (job.status is
    the 'application report' a later `status` reads).

    Guarded by pid: ENV_DETACHED inherits into the dispatcher's whole tree
    (supervisor attempts, gang ranks), and a worker exiting mid-restart
    must not record ITS code as the job's terminal state — only the
    process `submit` recorded may write."""
    job = _read_json(os.path.join(out_dir, JOB_FILE))
    if not job or job.get("pid") != os.getpid():
        return
    try:
        with open(os.path.join(out_dir, STATUS_FILE), "w") as f:
            json.dump({"exit": int(exit_code), "finished_at": time.time()}, f)
    except OSError:
        pass


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _is_our_job(pid: int, job: Optional[dict]) -> bool:
    """Guard against stale/recycled pids and wrong-machine job dirs: the
    recorded pid must belong to a shifu_tpu dispatcher ON the recording
    host — an unclean daemon death followed by pid reuse must not make
    `kill` SIGKILL an innocent process tree.  Both spellings are matched:
    `python -m shifu_tpu...` AND the installed `shifu-tpu` console script
    (whose cmdline carries only the hyphenated form)."""
    if job and job.get("host") and job["host"] != os.uname().nodename:
        return False
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmd = f.read()
        return b"shifu_tpu" in cmd or b"shifu-tpu" in cmd
    except OSError:
        # no /proc (or no permission): fall back to pid liveness alone
        return True


def job_state(out_dir: str) -> dict:
    """One dict describing the job: RUNNING / FINISHED(exit) / FAILED /
    UNKNOWN, plus the last board line when there is one."""
    job = _read_json(os.path.join(out_dir, JOB_FILE))
    status = _read_json(os.path.join(out_dir, STATUS_FILE))
    out: dict = {"job_dir": out_dir}
    if job:
        out.update(pid=job.get("pid"), submitted_at=job.get("submitted_at"))
    try:  # surface an acquired-but-unreleased slice (provision.json)
        from .provision import read_marker
        marker = read_marker(out_dir)
        if marker and marker.get("name"):
            out["provisioned_slice"] = marker["name"]
    except Exception:
        pass
    if status is not None:
        rc = int(status.get("exit", 1))
        out.update(state="FINISHED" if rc == 0 else "FAILED", exit=rc,
                   finished_at=status.get("finished_at"))
    elif (job and isinstance(job.get("pid"), int) and _alive(job["pid"])
          and _is_our_job(job["pid"], job)):
        out["state"] = "RUNNING"
    elif job:
        # pid gone with no status file: the daemon was killed uncleanly
        out.update(state="DEAD", exit=None)
    else:
        out["state"] = "UNKNOWN"
    board = os.path.join(out_dir, BOARD_FILE)
    try:
        with open(board) as f:
            lines = f.read().splitlines()
        if lines:
            out["last_progress"] = lines[-1]
    except OSError:
        pass
    try:  # telemetry summary when the job dir carries a run journal (obs/)
        tele = _telemetry_quick_summary(
            os.path.join(out_dir, "telemetry", "journal.jsonl"))
        if tele:
            out["telemetry"] = tele
    except Exception:
        pass
    try:  # checkpoint retention: kept steps + GC'd totals (recovery ladder)
        ckpt = _checkpoint_summary(out_dir)
        if ckpt:
            out["checkpoints"] = ckpt
    except Exception:
        pass
    return out


def _checkpoint_summary(out_dir: str) -> Optional[dict]:
    """Kept checkpoint steps (the recovery ladder's rungs) from the job's
    default tmp_model dir, plus GC'd-step totals from the scrape file —
    bounded work (one listing + one small file), fit for status polls."""
    ckpt_dir = os.path.join(out_dir, "tmp_model")
    if not os.path.isdir(ckpt_dir):
        return None
    kept = sorted(int(n) for n in os.listdir(ckpt_dir)
                  if n.isdigit() and os.path.isdir(os.path.join(ckpt_dir, n)))
    verified = sum(
        1 for s in kept
        if os.path.exists(os.path.join(ckpt_dir, f"manifest-{s}.json")))
    summary = {"kept_steps": kept, "manifests": verified}
    prom = os.path.join(out_dir, "telemetry", "metrics.prom")
    try:
        from ..obs.render import parse_scrape_totals
        with open(prom) as f:
            totals = parse_scrape_totals(f.read())
        if "checkpoint_gc_total" in totals:
            summary["gc_steps"] = int(totals["checkpoint_gc_total"])
        if "checkpoint_gc_bytes_total" in totals:
            summary["gc_freed_bytes"] = int(
                totals["checkpoint_gc_bytes_total"])
    except OSError:
        pass
    return summary


def _telemetry_quick_summary(jpath: str) -> Optional[dict]:
    """Bounded journal probe for `status` polls: count newlines in one
    chunked pass and json-decode ONLY the last complete line — a long run
    journals tens of thousands of events, and a status poll must not pay
    an O(run-length) decode each call (`shifu-tpu metrics` does the full
    parse on demand)."""
    if not os.path.exists(jpath):
        return None
    n = 0
    tail = b""
    with open(jpath, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            n += chunk.count(b"\n")
            # 64 KiB window: a host_skew event on a large pod can exceed
            # 4 KiB in ONE line, and a tail that holds only a mid-line
            # fragment would report last_event=null on a healthy journal
            tail = (tail + chunk)[-65536:]
    last_kind = None
    goodput = None
    hbm = None
    serving = None
    slo_firing: dict = {}
    slo_seen: set = set()
    for line in reversed(tail.splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if not isinstance(rec, dict):
            continue
        if last_kind is None:
            last_kind = rec.get("kind")
        if serving is None and rec.get("kind") == "serving_report":
            # latest serving_report within the tail: the at-a-glance
            # daemon health next to goodput (docs/SERVING.md telemetry)
            serving = {"requests": rec.get("requests"),
                       "scores_per_sec": rec.get("scores_per_sec"),
                       "p99_ms": rec.get("p99_ms"),
                       "queue_depth": rec.get("queue_depth"),
                       "errors": rec.get("errors")}
        if rec.get("kind") == "slo_alert":
            # walk is newest-first: the FIRST state seen per objective is
            # its current one — firing objectives are the active alerts
            obj = str(rec.get("objective", "?"))
            if obj not in slo_seen:
                slo_seen.add(obj)
                if rec.get("state") == "firing":
                    slo_firing[obj] = {
                        "burn_fast": rec.get("burn_fast"),
                        "observed_p99_ms": rec.get("observed_p99_ms")}
        if goodput is None and rec.get("kind") == "goodput":
            # latest goodput ledger record within the tail window: the
            # at-a-glance "is the job actually stepping" numbers
            # (docs/PERF.md "Goodput & MFU"); a run that never emitted
            # one (pre-ledger journal) just omits the key
            goodput = {"epoch": rec.get("epoch"),
                       "goodput_fraction": rec.get("goodput_fraction"),
                       "mfu": rec.get("mfu")}
        if hbm is None and rec.get("kind") == "hbm_watermark":
            # latest HBM watermark (obs/devprof.py): the at-a-glance
            # "how close to the memory cliff" number next to goodput
            hbm = {"epoch": rec.get("epoch"),
                   "peak_bytes": rec.get("peak_bytes"),
                   "bytes_in_use": rec.get("bytes_in_use"),
                   "source": rec.get("source")}
        if last_kind is not None and goodput is not None and hbm is not None:
            break
    out = {"events": n, "last_event": last_kind}
    if goodput is not None:
        out["goodput"] = goodput
    if hbm is not None:
        out["hbm"] = hbm
    if serving is not None:
        out["serving"] = serving
    if slo_seen:
        out["slo"] = {"firing": sorted(slo_firing),
                      "alerts": slo_firing}
    return out


def run_status(out_dir: str, echo=print) -> int:
    st = job_state(out_dir)
    echo(json.dumps(st))
    if st["state"] == "UNKNOWN":
        return 1
    return 0


def attach(out_dir: str, echo=print, poll_seconds: float = 0.5,
           from_start: bool = True) -> int:
    """Follow the job's console board until it finishes — the reference
    client's TailThread over the HDFS progress file
    (TensorflowClient.java:829-841).  Returns the job's exit code."""
    try:
        from ..data import fsio
        if fsio.is_remote(out_dir):
            # remote job dir: follow the board object from ANY machine that
            # can read it (no local pid/status to consult — ^C to stop)
            from .console import tail_board
            for line in tail_board(fsio.join(out_dir, BOARD_FILE),
                                   from_start=from_start):
                echo(line)
            return 0
    except KeyboardInterrupt:
        return 0
    board = os.path.join(out_dir, BOARD_FILE)
    pos = 0
    if not from_start and os.path.exists(board):
        pos = os.path.getsize(board)
    try:
        while True:
            if os.path.exists(board):
                with open(board) as f:
                    f.seek(pos)
                    chunk = f.read()
                    pos = f.tell()
                for line in chunk.splitlines():
                    echo(line)
            st = job_state(out_dir)
            if st["state"] in ("FINISHED", "FAILED"):
                # drain anything written between the read and the status
                if os.path.exists(board):
                    with open(board) as f:
                        f.seek(pos)
                        for line in f.read().splitlines():
                            echo(line)
                echo(f"job {st['state'].lower()} (exit {st.get('exit')})")
                return int(st.get("exit") or 0)
            if st["state"] in ("DEAD", "UNKNOWN"):
                echo(f"job state: {st['state']}")
                return 1
            time.sleep(poll_seconds)
    except KeyboardInterrupt:
        return 0  # stop following; the job keeps running


def _release_slice(out_dir: str, echo, force: bool = False,
                   killed_pid: Optional[int] = None) -> bool:
    """Best-effort release of a provisioned slice the job dir records —
    killing the application frees its compute (YARN-RM parity), and an
    unclean dispatcher death must not leave a billing TPU behind.

    Guarded at THIS level so every kill() branch gets it: when the marker
    records a LIVE provisioning dispatcher (a foreground `--provision` run
    — it writes no job.json, so a stale job.json in the same dir must not
    bypass the check) or was written on another host (this host's pid
    table proves nothing), refuse unless `force`.  Returns False when the
    release was refused."""
    try:
        from .provision import read_marker, release_from_marker
        marker = read_marker(out_dir)
        if marker and not force:
            mpid = marker.get("pid")
            mhost = marker.get("host")
            if mhost and mhost != os.uname().nodename:
                echo(f"provision marker was written on {mhost!r} — run kill "
                     "there (its pid table can check dispatcher liveness) "
                     "or re-run with --force")
                return False
            # A detached --provision job's marker pid IS the job pid; when
            # kill() just signalled that exact pid, _alive can still answer
            # True for a just-SIGKILLed (or zombie) process — that is not a
            # live foreground dispatcher, so the guard must not fire.
            if (isinstance(mpid, int) and mpid != killed_pid
                    and _alive(mpid) and _is_our_job(mpid, marker)):
                echo(f"provision marker records a LIVE dispatcher (pid "
                     f"{mpid}) — a foreground --provision run is still "
                     "using the slice; SIGTERM that process (or re-run "
                     "with --force) instead")
                return False
        release_from_marker(out_dir, echo=echo)
        return True
    except Exception as e:
        echo(f"provision: release check failed ({e}); see provision.json "
             f"in {out_dir}")
        return True


def kill(out_dir: str, echo=print, grace_seconds: float = 10.0,
         force: bool = False) -> int:
    """SIGTERM the detached dispatcher's process group (it is a session
    leader, so the whole supervisor->gang tree drains), escalating to
    SIGKILL; the client-side 'kill application' the reference had.  Also
    releases a provisioned slice the job dir records (provision.json) —
    including one left behind by an earlier unclean daemon death."""
    job = _read_json(os.path.join(out_dir, JOB_FILE))
    if not job or not isinstance(job.get("pid"), int):
        echo(f"no submitted job under {out_dir}")
        # a FOREGROUND --provision run writes no job.json but may have
        # left a provision.json trail (unclean dispatcher death) — the
        # rescue release must still run.  _release_slice refuses when the
        # marker records a LIVE dispatcher or a foreign host (a stray
        # `kill` must not delete the slice under a live gang).
        _release_slice(out_dir, echo, force=force)
        return 1
    pid = job["pid"]
    if not _alive(pid):
        echo(f"job pid {pid} is not running")
        # exit 1 when a recorded slice was deliberately NOT released (live
        # foreground dispatcher / foreign host): the operator must act
        return 0 if _release_slice(out_dir, echo, force=force) else 1
    if not _is_our_job(pid, job):
        echo(f"pid {pid} is not this job's dispatcher (recycled pid or a "
             f"different host — job.json says {job.get('host')!r}); "
             "refusing to signal it")
        if not (job.get("host") and job["host"] != os.uname().nodename):
            # same host, recycled pid: the dispatcher is truly gone — a
            # recorded slice can still be released safely (the marker
            # guard in _release_slice still protects a separate live
            # foreground run sharing this dir)
            _release_slice(out_dir, echo, force=force)
        return 1
    try:
        os.killpg(pid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            os.kill(pid, signal.SIGTERM)
        except OSError:
            pass
    deadline = time.monotonic() + grace_seconds
    while time.monotonic() < deadline:
        if not _alive(pid):
            echo(f"job pid {pid} terminated")
            return 0 if _release_slice(out_dir, echo, force=force) else 1
        time.sleep(0.2)
    try:
        os.killpg(pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        pass
    # give the kernel a beat to reap: _alive() answers True for a
    # just-SIGKILLed or zombie process, which would trip the live-
    # dispatcher guard on the marker we are about to release
    reap_deadline = time.monotonic() + 2.0
    while time.monotonic() < reap_deadline and _alive(pid):
        time.sleep(0.1)
    echo(f"job pid {pid} killed")
    return 0 if _release_slice(out_dir, echo, force=force,
                               killed_pid=pid) else 1
