"""Console board: progress lines to stdout + an append-only board file.

Successor of the reference's 4-hop metrics pipeline (python socket ->
SocketServer -> ZooKeeper -> AM aggregate -> HDFS ClientConsoleBoard file ->
client TailThread; SURVEY.md section 5.5).  Under SPMD there is one program,
so the board is written directly: every line goes to stdout immediately and
is appended (flushed) to a board file that an external tail — or the
supervisor's liveness monitor — can follow.

Remote job dirs are first-class (the reference's board LIVED on HDFS,
yarn/util/CommonUtils.java:426-458): a gs:// hdfs:// mock:// board path
writes through data/fsio — object stores have no append, so the board
keeps its lines in memory and rewrites the (small, per-epoch-cadence)
object on every line — and `tail_board` polls the remote object,
yielding only the new lines, so an operator on ANOTHER machine can follow
a running job (TensorflowClient.java:829-841 parity).
"""

from __future__ import annotations

import os
import sys
import time
from typing import Optional


def _is_remote(path: Optional[str]) -> bool:
    if not path:
        return False
    try:
        from ..data import fsio
        return fsio.is_remote(path)
    except Exception:
        return False


class ConsoleBoard:
    def __init__(self, board_path: Optional[str] = None, echo: bool = True):
        self.board_path = board_path
        self.echo = echo
        self._fh = None
        self._remote = _is_remote(board_path)
        self._lines: list[str] = []
        if board_path and not self._remote:
            os.makedirs(os.path.dirname(os.path.abspath(board_path)),
                        exist_ok=True)
            self._fh = open(board_path, "a", buffering=1)

    def __call__(self, line: str) -> None:
        stamped = f"[{time.strftime('%Y-%m-%d %H:%M:%S')}] {line}"
        if self.echo:
            print(stamped, flush=True)
        if self._fh is not None:
            self._fh.write(stamped + "\n")
            self._fh.flush()
        elif self._remote:
            self._lines.append(stamped)
            self._flush_remote()

    def _flush_remote(self) -> None:
        # whole-object rewrite: appends don't exist on object stores, and
        # the board is small (one line per epoch) — best-effort, the lines
        # already reached stdout
        try:
            from ..data import fsio
            fsio.write_bytes(self.board_path,
                             ("\n".join(self._lines) + "\n").encode())
        except Exception as e:  # noqa: BLE001 - board is observability
            print(f"board write failed ({e}); continuing",
                  file=sys.stderr, flush=True)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def tail_board(board_path: str, from_start: bool = True,
               poll_seconds: float = 0.2):
    """Generator yielding board lines as they appear (the reference client's
    TailThread, TensorflowClient.java:829-841).  Local boards stream from
    the file handle; remote (gs:// hdfs:// mock://) boards poll the object
    through fsio and yield the delta — follow a running job from any
    machine that can read the job dir.  Stops when the board is removed;
    callers normally run it in a thread."""
    if _is_remote(board_path):
        yield from _tail_remote(board_path, from_start, poll_seconds)
        return
    pos = 0
    while not os.path.exists(board_path):
        time.sleep(0.1)
    with open(board_path, "r") as f:
        if not from_start:
            f.seek(0, os.SEEK_END)
        while True:
            line = f.readline()
            if line:
                yield line.rstrip("\n")
            else:
                if not os.path.exists(board_path):
                    return
                time.sleep(poll_seconds)


def _tail_remote(board_path: str, from_start: bool, poll_seconds: float):
    from ..data import fsio

    seen = 0
    first = True
    missing_grace = True
    while True:
        try:
            text = fsio.read_bytes(board_path).decode("utf-8", "replace")
            missing_grace = False
        except FileNotFoundError:
            if missing_grace:  # not yet written: keep waiting for the job
                time.sleep(poll_seconds)
                continue
            return  # existed once, now gone: the board was removed
        except Exception:
            time.sleep(poll_seconds)
            continue
        # the board rewrite is not atomic on every store: only count lines
        # up to the last newline, so a partially-written final line is
        # neither emitted truncated nor marked seen (it completes next poll)
        complete = text[:text.rfind("\n") + 1]
        lines = complete.splitlines()
        if first and not from_start:
            seen = len(lines)
        first = False
        for line in lines[seen:]:
            yield line
        seen = max(seen, len(lines))
        time.sleep(poll_seconds)
