"""Console board: progress lines to stdout + an append-only board file.

Successor of the reference's 4-hop metrics pipeline (python socket ->
SocketServer -> ZooKeeper -> AM aggregate -> HDFS ClientConsoleBoard file ->
client TailThread; SURVEY.md section 5.5).  Under SPMD there is one program,
so the board is written directly: every line goes to stdout immediately and
is appended (flushed) to a board file that an external tail — or the
supervisor's liveness monitor — can follow.

Remote job dirs are first-class (the reference's board LIVED on HDFS,
yarn/util/CommonUtils.java:426-458): a gs:// hdfs:// mock:// board path
writes through data/fsio — object stores have no append, so the board
keeps its lines in memory and rewrites the object — and `tail_board`
polls the remote object, yielding only the new lines, so an operator on
ANOTHER machine can follow a running job (TensorflowClient.java:829-841
parity).  Two bounds keep the rewrite cost from growing with job length:
retained lines are capped (SHIFU_TPU_BOARD_MAX_LINES, default 2000 —
truncation drops the OLDEST lines, is journaled once as a warning, and
leaves a marker line in the object) and rewrites within
SHIFU_TPU_BOARD_FLUSH_SECONDS (default 0.2s) of the previous one batch
into a single deferred write instead of one PUT per line.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Optional

ENV_BOARD_MAX_LINES = "SHIFU_TPU_BOARD_MAX_LINES"
DEFAULT_MAX_REMOTE_LINES = 2000
ENV_BOARD_FLUSH_SECONDS = "SHIFU_TPU_BOARD_FLUSH_SECONDS"
DEFAULT_FLUSH_SECONDS = 0.2


def _env_number(name: str, default, cast):
    try:
        raw = os.environ.get(name)
        return cast(raw) if raw else default
    except ValueError:
        return default


def _is_remote(path: Optional[str]) -> bool:
    if not path:
        return False
    try:
        from ..data import fsio
        return fsio.is_remote(path)
    except Exception:
        return False


class ConsoleBoard:
    def __init__(self, board_path: Optional[str] = None, echo: bool = True,
                 max_remote_lines: Optional[int] = None,
                 flush_seconds: Optional[float] = None):
        self.board_path = board_path
        self.echo = echo
        self._fh = None
        self._remote = _is_remote(board_path)
        self._lines: list[str] = []
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()  # serializes remote PUTs
        self._gen = 0          # snapshot generation (under _lock)
        self._written_gen = 0  # newest generation PUT (under _io_lock)
        self._dirty = False
        self._timer: Optional[threading.Timer] = None
        self._last_flush = 0.0  # epoch-0 monotonic: first line flushes now
        self._truncated = 0
        self._warned = False
        self._max_lines = (max_remote_lines
                           if max_remote_lines is not None
                           else _env_number(ENV_BOARD_MAX_LINES,
                                            DEFAULT_MAX_REMOTE_LINES, int))
        self._flush_seconds = (flush_seconds
                               if flush_seconds is not None
                               else _env_number(ENV_BOARD_FLUSH_SECONDS,
                                                DEFAULT_FLUSH_SECONDS,
                                                float))
        if board_path and not self._remote:
            os.makedirs(os.path.dirname(os.path.abspath(board_path)),
                        exist_ok=True)
            self._fh = open(board_path, "a", buffering=1)

    def __call__(self, line: str) -> None:
        stamped = f"[{time.strftime('%Y-%m-%d %H:%M:%S')}] {line}"
        if self.echo:
            print(stamped, flush=True)
        if self._fh is not None:
            try:
                # chaos site "board.flush": a failing board disk/volume must
                # degrade to stdout-only, never kill the training it narrates
                from .. import chaos
                chaos.maybe_fail("board.flush", path=self.board_path)
                self._fh.write(stamped + "\n")
                self._fh.flush()
            except Exception as e:  # noqa: BLE001 - board is observability
                print(f"board write failed ({e}); continuing",
                      file=sys.stderr, flush=True)
        elif self._remote:
            with self._lock:
                self._lines.append(stamped)
                overflow = len(self._lines) - max(self._max_lines, 1)
                if overflow > 0:
                    # the remote board is a whole-object rewrite: without a
                    # cap a 50k-epoch job turns every line into a multi-MB
                    # PUT.  Drop the OLDEST lines (they already reached
                    # stdout and the journal) and say so — once — through
                    # the journal and stderr.
                    del self._lines[:overflow]
                    self._truncated += overflow
                    if not self._warned:
                        self._warned = True
                        try:
                            from .. import obs
                            obs.event("board_truncated",
                                      path=self.board_path,
                                      line_cap=self._max_lines)
                        except Exception:
                            pass
                        print(f"board line cap ({self._max_lines}) reached: "
                              f"older lines drop from the remote object "
                              f"(stdout and the run journal keep them)",
                              file=sys.stderr, flush=True)
                self._dirty = True
            self._maybe_flush_remote()

    def _maybe_flush_remote(self) -> None:
        """Rewrite the remote object now, or defer: lines arriving within
        `flush_seconds` of the previous rewrite batch into ONE deferred
        write (a daemon timer) instead of one PUT per line."""
        with self._lock:
            if not self._dirty:
                return
            wait = self._flush_seconds - (time.monotonic() - self._last_flush)
            if wait > 0:
                if self._timer is None:
                    self._timer = threading.Timer(wait, self._timer_fire)
                    self._timer.daemon = True
                    self._timer.start()
                return
            lines = list(self._lines)
            truncated = self._truncated
            self._gen += 1
            gen = self._gen
            self._dirty = False
            self._last_flush = time.monotonic()
        self._write_remote(lines, truncated, gen)

    def _timer_fire(self) -> None:
        with self._lock:
            self._timer = None
        self._maybe_flush_remote()

    def _write_remote(self, lines: list, truncated: int, gen: int) -> None:
        # whole-object rewrite: appends don't exist on object stores —
        # best-effort, the lines already reached stdout.  PUTs are
        # serialized under _io_lock and generation-guarded: a slow write
        # overlapping a newer one (timer thread vs direct flush) must not
        # land LAST and regress the object to an older snapshot — the
        # stale generation is simply skipped.
        if truncated:
            lines = [f"[... {truncated} earlier lines dropped "
                     f"(board line cap {self._max_lines}) ...]"] + lines
        with self._io_lock:
            if gen <= self._written_gen:
                return  # a newer snapshot already reached the store
            try:
                from .. import chaos
                chaos.maybe_fail("board.flush", path=self.board_path)
                from ..data import fsio
                fsio.write_bytes(self.board_path,
                                 ("\n".join(lines) + "\n").encode())
                self._written_gen = gen
            except Exception as e:  # noqa: BLE001 - board is observability
                print(f"board write failed ({e}); continuing",
                      file=sys.stderr, flush=True)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        if self._remote:
            with self._lock:
                timer, self._timer = self._timer, None
                lines = list(self._lines) if self._dirty else None
                truncated = self._truncated
                self._gen += 1
                gen = self._gen
                self._dirty = False
            if timer is not None:
                timer.cancel()
            if lines is not None:  # pending batched lines must not be lost
                self._write_remote(lines, truncated, gen)


def tail_board(board_path: str, from_start: bool = True,
               poll_seconds: float = 0.2):
    """Generator yielding board lines as they appear (the reference client's
    TailThread, TensorflowClient.java:829-841).  Local boards stream from
    the file handle; remote (gs:// hdfs:// mock://) boards poll the object
    through fsio and yield the delta — follow a running job from any
    machine that can read the job dir.  Stops when the board is removed;
    callers normally run it in a thread."""
    if _is_remote(board_path):
        yield from _tail_remote(board_path, from_start, poll_seconds)
        return
    pos = 0
    while not os.path.exists(board_path):
        time.sleep(0.1)
    with open(board_path, "r") as f:
        if not from_start:
            f.seek(0, os.SEEK_END)
        while True:
            line = f.readline()
            if line:
                yield line.rstrip("\n")
            else:
                if not os.path.exists(board_path):
                    return
                time.sleep(poll_seconds)


_TRUNC_MARKER_RE = None  # compiled lazily (module import stays light)


def _parse_trunc_marker(line: str):
    """Dropped-line count from the board's truncation marker, or None."""
    global _TRUNC_MARKER_RE
    if _TRUNC_MARKER_RE is None:
        import re
        _TRUNC_MARKER_RE = re.compile(
            r"^\[\.\.\. (\d+) earlier lines dropped ")
    m = _TRUNC_MARKER_RE.match(line)
    return int(m.group(1)) if m else None


def _tail_remote(board_path: str, from_start: bool, poll_seconds: float):
    """Delta-tracking by ABSOLUTE line position (dropped + visible index),
    not raw index: once the board's retained-line cap engages, every
    rewrite drops the oldest line and prepends/updates a truncation
    marker, so the visible line count plateaus and a raw-index tail would
    stall forever (and the marker would shift every index by one)."""
    from ..data import fsio

    seen_abs = 0  # total board lines ever observed (dropped + yielded)
    first = True
    missing_grace = True
    while True:
        try:
            text = fsio.read_bytes(board_path).decode("utf-8", "replace")
            missing_grace = False
        except FileNotFoundError:
            if missing_grace:  # not yet written: keep waiting for the job
                time.sleep(poll_seconds)
                continue
            return  # existed once, now gone: the board was removed
        except Exception:
            time.sleep(poll_seconds)
            continue
        # the board rewrite is not atomic on every store: only count lines
        # up to the last newline, so a partially-written final line is
        # neither emitted truncated nor marked seen (it completes next poll)
        complete = text[:text.rfind("\n") + 1]
        lines = complete.splitlines()
        dropped = 0
        if lines:
            d = _parse_trunc_marker(lines[0])
            if d is not None:
                dropped = d
                lines = lines[1:]
        total = dropped + len(lines)
        if first and not from_start:
            seen_abs = total
        first = False
        start = max(seen_abs - dropped, 0)  # lines past the cap are gone
        for line in lines[start:]:
            yield line
        seen_abs = max(seen_abs, total)
        time.sleep(poll_seconds)
