"""Console board: progress lines to stdout + an append-only board file.

Successor of the reference's 4-hop metrics pipeline (python socket ->
SocketServer -> ZooKeeper -> AM aggregate -> HDFS ClientConsoleBoard file ->
client TailThread; SURVEY.md section 5.5).  Under SPMD there is one program,
so the board is written directly: every line goes to stdout immediately and
is appended (flushed) to a board file that an external tail — or the
supervisor's liveness monitor — can follow.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Optional


class ConsoleBoard:
    def __init__(self, board_path: Optional[str] = None, echo: bool = True):
        self.board_path = board_path
        self.echo = echo
        self._fh = None
        if board_path:
            os.makedirs(os.path.dirname(os.path.abspath(board_path)), exist_ok=True)
            self._fh = open(board_path, "a", buffering=1)

    def __call__(self, line: str) -> None:
        stamped = f"[{time.strftime('%Y-%m-%d %H:%M:%S')}] {line}"
        if self.echo:
            print(stamped, flush=True)
        if self._fh is not None:
            self._fh.write(stamped + "\n")
            self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def tail_board(board_path: str, from_start: bool = True):
    """Generator yielding board lines as they appear (the reference client's
    TailThread, TensorflowClient.java:829-841). Stops when the file is
    removed; callers normally run it in a thread."""
    pos = 0
    while not os.path.exists(board_path):
        time.sleep(0.1)
    with open(board_path, "r") as f:
        if not from_start:
            f.seek(0, os.SEEK_END)
        while True:
            line = f.readline()
            if line:
                yield line.rstrip("\n")
            else:
                if not os.path.exists(board_path):
                    return
                time.sleep(0.2)
