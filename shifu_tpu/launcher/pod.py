"""Pod-scale launch: one command → one training process per host, per-host
log collection, and whole-gang supervised restart from checkpoint.

Successor of the reference's compute-acquisition path — the YARN client's
createApplication/submitApplication/monitorApplication loop
(yarn/client/TensorflowClient.java:339-426), the AM's container allocation
(yarn/appmaster/AMRMCallbackHandler.java:148-190), and its failed-worker
recovery (yarn/appmaster/TensorflowApplicationMaster.java:410-426).  On TPU
the accelerators are already attached to the pod's hosts, so "provisioning"
collapses to: derive the host list (explicit --hosts, SHIFU_TPU_HOSTS, or the
TPU runtime's own metadata), dispatch one SPMD process per host with ranks
assigned from list order, stream every host's output back into per-host log
files under the job dir, and supervise the gang as a unit: the first host
failure tears the rest down (a half-gang would block in collectives forever —
the SPMD analog of "any failed worker breaks the monitor loop",
TensorflowApplicationMaster.java:363-371) and the whole gang restarts from
the shared checkpoint, bounded by the same restart budget the single-host
supervisor uses.  Hot-standby backup containers have no SPMD equivalent;
checkpoint-restart of the full gang is the recovery story (SURVEY.md §5.3).

Transports:
- ``local`` (``--hosts local:N``): N coordinated processes on this machine —
  the simulated pod used by tests and dev runs (virtual CPU devices per
  process).
- ``ssh`` (``--hosts h1,h2,...`` or ``--hosts @hostfile``): one process per
  host over ``ssh -tt`` (the tty makes a parent-side kill propagate as HUP).
  Host order defines the jax.distributed process id, so list hosts in the
  TPU runtime's worker order (TPU_WORKER_HOSTNAMES order on Cloud TPU).
  Checkpoint/export paths must live on storage all hosts share (gs://,
  hdfs://, NFS) — the same contract the reference had with HDFS model paths.

The operator UX stays the reference's: one command, per-epoch lines on the
console (rank 0's stream is echoed live, every rank is captured to
``<out>/logs/host-<rank>.attempt-<k>.log``), per-host log locations printed,
exit status 0/1/3.
"""

from __future__ import annotations

import os
import shlex
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Optional, Sequence

ENV_HOSTS = "SHIFU_TPU_HOSTS"
ENV_COORDINATOR_PORT = "SHIFU_TPU_COORDINATOR_PORT"
DEFAULT_COORDINATOR_PORT = 8476
# per-host reconnects for ssh rc=255 with NO output yet (connect-level
# failure — host booting, transient network); a host that produced output
# and then died is a worker failure, handled by gang restart instead
SSH_CONNECT_RETRIES = 3


@dataclass(frozen=True)
class PodSpec:
    hosts: tuple[str, ...]           # rank i runs on hosts[i]
    transport: str                   # "local" | "ssh"
    coordinator_port: int = DEFAULT_COORDINATOR_PORT
    remote_python: str = sys.executable  # interpreter on the hosts


def parse_hosts(value: str, coordinator_port: int = 0) -> PodSpec:
    """``local:N`` → N simulated hosts here; ``@file`` → newline-separated
    host list; ``h1,h2,...`` → ssh to each host.

    `coordinator_port` (or SHIFU_TPU_COORDINATOR_PORT) overrides the ssh
    rendezvous port on hosts[0] — the escape hatch when the default 8476
    conflicts.  Resolved only on the ssh path: local transport picks a free
    port and ignores it, so a bad env value must not break local runs."""
    value = value.strip()
    if value.startswith("local:"):
        n = int(value.split(":", 1)[1])
        if n < 1:
            raise ValueError(f"--hosts {value!r}: need at least 1 process")
        return PodSpec(hosts=("local",) * n, transport="local")
    try:
        port = (coordinator_port
                or int(os.environ.get(ENV_COORDINATOR_PORT, "0") or 0)
                or DEFAULT_COORDINATOR_PORT)
    except ValueError:
        raise ValueError(
            f"{ENV_COORDINATOR_PORT}="
            f"{os.environ.get(ENV_COORDINATOR_PORT)!r} is not a port number")
    if not (0 < port < 65536):
        raise ValueError(f"coordinator port {port} out of range")
    if value.startswith("@"):
        with open(value[1:]) as f:
            hosts = tuple(h.strip() for h in f if h.strip()
                          and not h.lstrip().startswith("#"))
    else:
        hosts = tuple(h.strip() for h in value.split(",") if h.strip())
    if not hosts:
        raise ValueError(f"--hosts {value!r}: no hosts")
    return PodSpec(hosts=hosts, transport="ssh", coordinator_port=port)


def detect_hosts_env() -> Optional[str]:
    """The no-flag spelling: SHIFU_TPU_HOSTS.  Deliberately NOT
    TPU_WORKER_HOSTNAMES: the TPU runtime sets that on EVERY pod worker, and
    the established managed-pod pattern is to run the plain train command on
    all workers at once (`gcloud ... --worker=all`), each auto-joining via
    jax.distributed — auto-dispatching there would turn every worker into a
    dispatcher and launch N colliding gangs.  Dispatching is an explicit
    opt-in; `--hosts` docs point operators at the TPU_WORKER_HOSTNAMES value
    when they want driver-style launch from one machine."""
    return os.environ.get(ENV_HOSTS) or None


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _host_command(spec: PodSpec, rank: int, child_args: Sequence[str],
                  env_contract: dict[str, str]) -> tuple[list[str], Optional[dict]]:
    """(argv, env-or-None): local runs inherit+extend the parent env; ssh
    carries the contract inline (`env K=V ...`) so no remote shell profile
    can drop it."""
    module_argv = ["-m", "shifu_tpu.launcher.cli", *child_args]
    if spec.transport == "local":
        env = dict(os.environ)
        env.update(env_contract)
        # ranks die with THIS dispatcher even on its uncatchable death
        # (cli._arm_pdeathsig).  SIGKILL, not SIGTERM: a rank must stop
        # IMMEDIATELY (divergent drains deadlock gang collectives), and
        # rank-side libraries register SIGTERM handlers that would swallow
        # a catchable signal.  Set per-spawn — an inherited value from an
        # armed ancestor would record the WRONG parent pid and self-kill
        # the rank at startup.  ssh transport must NOT carry this: the
        # dispatcher pid is meaningless on the remote host (the ssh -tt
        # HUP tether covers remote parent-death there).
        import signal as signal_lib

        from .supervisor import ENV_PDEATHSIG
        env[ENV_PDEATHSIG] = f"{os.getpid()}:{int(signal_lib.SIGKILL)}"
        return [sys.executable, *module_argv], env
    assigns = [f"{k}={v}" for k, v in env_contract.items()]
    remote = " ".join(
        shlex.quote(p) for p in
        ["env", *assigns, spec.remote_python, *module_argv])
    return (["ssh", "-tt", "-o", "BatchMode=yes", spec.hosts[rank], remote],
            None)


def member_command(spec: PodSpec, rank: int, child_args: Sequence[str],
                   env_contract: dict[str, str]
                   ) -> tuple[list[str], Optional[dict]]:
    """(argv, env-or-None) to run one `shifu-tpu` child on host `rank` —
    the serving fleet's spawn path (runtime/fleet.py HostPlane): the
    SAME local/ssh transport wrapping the training gang uses, exposed
    for per-member dispatch instead of gang dispatch.  Local transport
    inherits+extends this env (with the pdeathsig tether); ssh carries
    the contract inline so no remote shell profile can drop it."""
    if not (0 <= rank < len(spec.hosts)):
        raise ValueError(f"member rank {rank} outside the host list "
                         f"({len(spec.hosts)} hosts)")
    return _host_command(spec, rank, child_args, env_contract)


def launch_gang(spec: PodSpec, child_args: Sequence[str], out_dir: str,
                attempt: int, liveness_seconds: float = 0.0,
                echo=print, deadline=None) -> tuple[int, tuple[int, ...]]:
    """Run one gang attempt: dispatch every rank, stream rank 0 to the
    console, capture all ranks to per-host logs, tear everyone down on the
    first failure (or on a liveness stall), return (gang exit code,
    culprit ranks).

    Culprit ranks are the ranks observed failing BEFORE the teardown began
    (failures after it are collateral SIGTERMs) — the signal the elastic
    reshape in supervise_pod uses to identify a permanently lost host.
    Empty on success, timeout, and liveness kills (a stall has no
    attributable culprit).

    `deadline` is a supervisor.JobDeadline for the JOB-level timeout: past
    it the gang is torn down and EXIT_TIMEOUT returned (the supervisor
    treats that as terminal)."""
    from .supervisor import EXIT_TIMEOUT
    n = len(spec.hosts)
    try:
        from ..data import fsio
        remote_out = fsio.is_remote(out_dir)
    except Exception:
        remote_out = False
    if remote_out:
        # per-host log PIPES are local files; a remote job dir keeps its
        # board/metrics/checkpoints remote while the dispatcher's raw host
        # logs live beside it on the dispatching machine
        import tempfile
        log_dir = tempfile.mkdtemp(prefix="shifu_tpu_pod_logs_")
    else:
        log_dir = os.path.join(out_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
    if spec.transport == "local":
        coordinator = f"127.0.0.1:{_free_port()}"
    else:
        coordinator = f"{spec.hosts[0]}:{spec.coordinator_port}"

    procs: list[subprocess.Popen] = []
    threads: list[threading.Thread] = []
    log_paths: list[str] = []
    # per-rank monotonic timestamp of the last output line — any rank's
    # output counts as gang progress for the liveness monitor (epoch lines
    # come from rank 0; other ranks are quiet when healthy)
    progress = [time.monotonic()] * n
    ssh_retries = [0] * n
    lock = threading.Lock()

    def _contract(rank: int) -> dict[str, str]:
        contract = {
            "SHIFU_TPU_COORDINATOR": coordinator,
            "SHIFU_TPU_NUM_PROCESSES": str(n),
            "SHIFU_TPU_PROCESS_ID": str(rank),
        }
        # an active chaos plan must reach every rank — local transport
        # inherits the dispatcher env, but ssh carries ONLY the contract
        # (the state path is only meaningful on shared storage; rank-scoped
        # process triggers need no state at all)
        from ..chaos import ENV_CHAOS_PLAN, ENV_CHAOS_STATE
        for key in (ENV_CHAOS_PLAN, ENV_CHAOS_STATE):
            val = os.environ.get(key)
            if val:
                contract[key] = val
        return contract

    def pump(rank: int, proc: subprocess.Popen, log_path: str,
             mode: str = "w") -> None:
        with open(log_path, mode) as log:
            for line in proc.stdout:  # text mode; closes on child exit
                log.write(line)
                log.flush()
                with lock:
                    progress[rank] = time.monotonic()
                if rank == 0:
                    echo(line.rstrip("\n"))

    def dispatch(rank: int, mode: str = "w") -> None:
        argv, env = _host_command(spec, rank, child_args, _contract(rank))
        try:
            # chaos site "pod.dispatch": the transport to one host fails
            # (ssh refused, container runtime down) — modeled as a stub
            # child exiting with the fault's code so the gang teardown /
            # ssh-retry / reshape machinery sees a real dead rank.  255
            # exercises the ssh transport budget specifically.
            from .. import chaos
            chaos.maybe_fail("pod.dispatch", rank=rank, attempt=attempt,
                             host=spec.hosts[rank])
        except chaos.ChaosError as e:
            echo(f"pod: chaos: host {rank} dispatch fails ({e})")
            argv = [sys.executable, "-c",
                    f"import sys; sys.exit({int(e.exit_code)})"]
            env = None
        proc = subprocess.Popen(argv, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
        procs[rank] = proc
        t = threading.Thread(target=pump,
                             args=(rank, proc, log_paths[rank], mode),
                             daemon=True)
        t.start()
        threads.append(t)

    for rank in range(n):
        log_paths.append(
            os.path.join(log_dir, f"host-{rank}.attempt-{attempt}.log"))
        procs.append(None)  # type: ignore[arg-type]
        dispatch(rank)

    echo(f"pod: attempt {attempt}: {n} processes "
         f"({spec.transport}), coordinator {coordinator}, "
         f"logs {log_dir}/host-*.attempt-{attempt}.log")

    status = 0
    failed_ranks: list[int] = []
    # teardown is deferred one short grace window after the FIRST failure
    # so every rank that fails on its own in that window is recorded as a
    # culprit too: blaming only the first-polled exit would let a
    # fast-dying collateral victim (a peer aborting on the dead host's
    # collective error inside the same poll interval) absorb the blame —
    # and the elastic reshape would then evict a healthy host.  Collateral
    # victims caught in the window make the culprit set ambiguous (size >
    # 1), which the reshape treats as "not one lost host" — conservative
    # by design.
    teardown_at: Optional[float] = None
    try:
        remaining = set(range(n))
        while remaining:
            for rank in sorted(remaining):
                rc = procs[rank].poll()
                if rc is None:
                    continue
                if (rc == 255 and spec.transport == "ssh"
                        and ssh_retries[rank] < SSH_CONNECT_RETRIES):
                    # rc=255 is the ssh CLIENT's own exit code — a
                    # transport-level failure, not a child exit: retry THIS
                    # host with backoff.  A pre-rendezvous connect failure
                    # (host booting, flaky network) reconnects cleanly; a
                    # mid-run drop killed the remote worker (-tt HUP), the
                    # re-join then fails fast and the gang restarts under
                    # supervise_pod's TRANSPORT budget — either way the
                    # training restart budget is never charged
                    ssh_retries[rank] += 1
                    echo(f"pod: host {rank} ({spec.hosts[rank]}) ssh "
                         f"connect failed (rc=255) — reconnect "
                         f"{ssh_retries[rank]}/{SSH_CONNECT_RETRIES}")
                    time.sleep(min(2.0 * ssh_retries[rank], 10.0))
                    dispatch(rank, mode="a")
                    continue
                remaining.discard(rank)
                if rc != 0:
                    if teardown_at is None:
                        failed_ranks.append(rank)
                        echo(f"pod: host {rank} ({spec.hosts[rank]}) "
                             f"exited rc={rc} — tearing down the gang "
                             f"(see {log_paths[rank]})")
                        teardown_at = time.monotonic() + 1.0
                    elif time.monotonic() < teardown_at:
                        # failed on its own inside the grace window:
                        # also a culprit (ambiguity blocks the reshape)
                        failed_ranks.append(rank)
                    status = status or rc
            if (teardown_at is not None and remaining
                    and time.monotonic() >= teardown_at):
                # culprit grace over: stop the survivors (idempotent —
                # repeat sweeps just re-signal already-terminating procs)
                for other in sorted(remaining):
                    procs[other].terminate()
            # deadline AFTER the poll drain: a gang that finished during the
            # last sleep must report its real status, not a phantom timeout
            if deadline is not None and remaining and deadline.expired():
                # no graceful drain here: multihost ranks deliberately do NOT
                # catch SIGTERM (one rank draining while peers issue
                # collectives would deadlock the step — train/loop.py), so
                # progress durability comes from the periodic checkpoint
                # cadence, and the teardown is immediate
                echo("pod: job timeout exceeded — tearing down the gang")
                for other in sorted(remaining):
                    procs[other].terminate()
                return EXIT_TIMEOUT, ()
            if liveness_seconds > 0 and remaining:
                with lock:
                    newest = max(progress)
                if time.monotonic() - newest > liveness_seconds:
                    echo(f"pod: no output from any host for "
                         f"{liveness_seconds}s — killing the gang")
                    status = status or -9
                    for other in sorted(remaining):
                        procs[other].kill()
            if remaining:
                time.sleep(0.5)
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        for t in threads:
            t.join(timeout=5)
    return status, tuple(failed_ranks)


def supervise_pod(spec: PodSpec, child_args: Sequence[str], out_dir: str,
                  max_restarts: int = 2, liveness_seconds: float = 0.0,
                  echo=print, checkpoint_dir: Optional[str] = None,
                  timeout_seconds: float = 0.0, min_hosts: int = 0) -> int:
    """Whole-gang restart supervision: any host failure restarts the ENTIRE
    gang (checkpoint auto-resume continues the job), bounded by max_restarts
    CONSECUTIVE failures without durable progress — the cross-host successor
    of `supervise()` and of the reference's backup-promotion recovery.
    Progress = the shared checkpoint's epoch advanced during the attempt
    (supervisor.ProgressProbe over the PROGRESS marker; works for gs://,
    hdfs://, NFS checkpoint dirs — which is also the shared-storage
    contract ssh pods already have): preemption-heavy pods legitimately
    restart many times, each resuming further, and only a crash loop that
    persists nothing exhausts the budget.

    timeout_seconds bounds the WHOLE JOB across attempts (one
    supervisor.JobDeadline from the first attempt's start); a timeout —
    whether hit by the gang's own children (exit 3) or by the dispatcher's
    deadline — is TERMINAL, never restarted (TensorflowClient.java:625-658
    kills the app once).

    min_hosts > 0 enables ELASTIC RESHAPE (RuntimeConfig.min_hosts): when
    the restart budget is exhausted and the attempts' culprit is one
    identifiable host, that host is presumed permanently lost — the gang
    restarts WITHOUT it (file shards rebalance through the env contract's
    new NUM_PROCESSES/PROCESS_ID, the train loop re-rounds the global
    batch to the new mesh, checkpoint auto-resume continues) with a fresh
    budget, as long as at least min_hosts remain.  The SPMD answer to the
    reference's >=95%-of-workers degraded start with task-index re-packing
    (TensorflowApplicationMaster.java:230-338).  Reshape assumes the job's
    state survives a world-size change — true for data-parallel jobs
    (replicated params; the default); model/pipe-sharded topologies should
    keep min_hosts=0."""
    import dataclasses as _dc

    from .supervisor import (EXIT_TIMEOUT, JobDeadline, ProgressProbe,
                             charge_restart_budget)

    attempts = 0
    failures_since_progress = 0
    transport_failures = 0
    # culprit accounting across the no-progress window: reshape drops a
    # host only when EVERY budgeted failure blames the same host (mixed
    # culprits look like a cluster-wide problem, not one lost host)
    window_culprits: set[int] = set()
    deadline = JobDeadline(timeout_seconds)

    def _reshape(reason: str) -> bool:
        nonlocal spec, failures_since_progress, transport_failures
        if min_hosts <= 0 or len(spec.hosts) <= max(min_hosts, 1):
            return False
        if len(window_culprits) != 1:
            return False
        drop = next(iter(window_culprits))
        gone = spec.hosts[drop]
        new_hosts = tuple(h for i, h in enumerate(spec.hosts) if i != drop)
        echo(f"pod: host {drop} ({gone}) {reason} — presumed permanently "
             f"lost; reshaping the gang to {len(new_hosts)} hosts "
             f"(floor {max(min_hosts, 1)}), rebalancing file shards, and "
             "resuming from checkpoint")
        spec = _dc.replace(spec, hosts=new_hosts)
        failures_since_progress = 0
        transport_failures = 0
        window_culprits.clear()
        return True

    while True:
        if deadline.expired():
            # don't dispatch a doomed gang just to kill it one poll later
            echo("pod: job timeout exceeded — terminal, no restart")
            return EXIT_TIMEOUT
        attempts += 1
        start = time.monotonic()
        probe = ProgressProbe(checkpoint_dir)
        rc, failed = launch_gang(spec, child_args, out_dir, attempts,
                                 liveness_seconds=liveness_seconds, echo=echo,
                                 deadline=deadline)
        if rc == 0:
            if attempts > 1:
                echo(f"pod: succeeded after {attempts} attempts")
            return 0
        if rc == EXIT_TIMEOUT:
            echo(f"pod: attempt {attempts} hit the job timeout — terminal, "
                 "no restart")
            return EXIT_TIMEOUT
        if rc == 255 and spec.transport == "ssh":
            # a mid-run ssh-level failure (rc=255 is the ssh client's own
            # code) is a TRANSPORT fault, not a training crash: restart the
            # gang on its own bounded budget so one flaky link cannot eat
            # the failure budget meant for real crash loops.  Like the
            # restart budget, it bounds CONSECUTIVE no-progress failures —
            # a multi-day job's occasional link drops, each resuming
            # further, must not accumulate to a terminal failure
            if probe.advanced():
                transport_failures = 0
                window_culprits.clear()
            transport_failures += 1
            window_culprits.update(failed)
            if transport_failures <= SSH_CONNECT_RETRIES:
                echo(f"pod: ssh transport failure — restarting the gang "
                     f"without charging the restart budget "
                     f"({transport_failures}/{SSH_CONNECT_RETRIES})")
                continue
            # an unreachable-forever host is the clearest permanent loss
            if _reshape("is unreachable over ssh after "
                        f"{transport_failures} consecutive attempts"):
                continue
            echo("pod: ssh transport failure budget exhausted")
            return 1
        progressed = probe.advanced()
        if progressed:
            window_culprits.clear()
        window_culprits.update(failed)
        failures_since_progress = charge_restart_budget(
            failures_since_progress, progressed, echo=echo, what="pod")
        echo(f"pod: attempt {attempts} failed rc={rc} after "
             f"{time.monotonic() - start:.1f}s")
        if failures_since_progress > max_restarts:
            if _reshape(f"failed {failures_since_progress} consecutive "
                        "attempts without progress"):
                continue
            echo(f"pod: restart budget exhausted ({max_restarts} restarts "
                 "without progress)")
            return rc if isinstance(rc, int) and rc > 0 else 1


# -- pod data-plane journal audit -------------------------------------------


def _pod_close_rows(events: Sequence[dict]) -> list[dict]:
    """Normalize per-epoch close records out of a merged event stream:
    `pod_epoch_close` rows (one per rank per epoch — the data-dryrun gang
    child journals them) plus the per-host rows embedded in each chief
    `host_skew` event (real multihost training runs).  Each normalized row:
    {epoch, rank, hosts, order_digest, shard_digest, ingest_bytes,
    ingest_s}."""
    rows: list[dict] = []
    for ev in events:
        kind = ev.get("kind")
        if kind == "pod_epoch_close":
            rows.append({
                "epoch": ev.get("epoch"), "rank": ev.get("rank"),
                "hosts": ev.get("hosts"),
                "order_digest": ev.get("order_digest"),
                "shard_digest": ev.get("shard_digest"),
                "ingest_bytes": ev.get("ingest_bytes"),
                "ingest_s": ev.get("ingest_s"),
            })
        elif kind == "host_skew":
            members = ev.get("hosts") or []
            for r in members:
                if not isinstance(r, dict):
                    continue
                rows.append({
                    "epoch": ev.get("epoch"), "rank": r.get("rank"),
                    "hosts": len(members),
                    "order_digest": r.get("order_digest"),
                    "shard_digest": r.get("shard_digest"),
                    "ingest_bytes": r.get("ingest_bytes"),
                    "ingest_s": r.get("ingest_s"),
                })
    return [r for r in rows
            if isinstance(r["epoch"], int) and isinstance(r["rank"], int)]


def pod_verify_events(events: Sequence[dict],
                      balance_limit: float = 1.5) -> dict:
    """Audit a pod training run's merged journals (obs/timeline.load_merged:
    root journal + one per-rank journal) against the pod data-plane
    invariants — the fleet-verify analog for the training gang.

    Checks:
    - epoch_coverage: every epoch up to the max observed was closed by a
      COMPLETE cohort — some gang width n whose ranks 0..n-1 all journaled
      a close row for it.  A killed attempt's partial rows are fine; an
      elastic reshape's narrower cohort is fine; an epoch NO cohort ever
      completed is not.
    - order_digest_agreement / shard_digest_agreement: inside every
      complete cohort all ranks carry the identical digest (the allgather-
      of-digests contract; rows without the field are skipped, so
      pre-field journals stay un-audited rather than failing).
    - ingest_balance: max/min cumulative per-rank source bytes at the last
      epoch <= balance_limit x the even share (only when >= 2 ranks
      ingested anything).
    - recovery: every injected `exit`/`raise` chaos fault is followed by a
      later (or same-epoch, re-run) complete cohort — the gang rebalanced
      / the host rejoined and the run still closed its epochs.
    """
    rows = _pod_close_rows(events)
    injections = [ev for ev in events
                  if ev.get("kind") == "chaos_inject"
                  and ev.get("action") in ("exit", "raise", "hang")]
    checks: list[dict] = []

    def check(name: str, ok: bool, detail: str) -> None:
        checks.append({"check": name, "ok": bool(ok), "detail": detail})

    by_epoch: dict[int, list[dict]] = {}
    for r in rows:
        by_epoch.setdefault(int(r["epoch"]), []).append(r)

    def complete_cohorts(epoch_rows: list[dict]) -> list[list[dict]]:
        """Groups by gang width whose ranks cover 0..n-1 (newest row per
        (width, rank) wins — a rank re-running an epoch after a restart
        supersedes its earlier row)."""
        by_width: dict[int, dict[int, dict]] = {}
        for r in epoch_rows:
            n = r.get("hosts")
            if isinstance(n, int) and n > 0:
                by_width.setdefault(n, {})[int(r["rank"])] = r
        return [list(ranks.values())
                for n, ranks in sorted(by_width.items())
                if set(ranks) == set(range(n))]

    epochs = sorted(by_epoch)
    missing = []
    disagree_order: list[int] = []
    disagree_shard: list[int] = []
    for ep in (range(epochs[-1] + 1) if epochs else ()):
        cohorts = complete_cohorts(by_epoch.get(ep, []))
        if not cohorts:
            missing.append(ep)
            continue
        for cohort in cohorts:
            for key, sink in (("order_digest", disagree_order),
                              ("shard_digest", disagree_shard)):
                vals = {r[key] for r in cohort if r.get(key) is not None}
                if len(vals) > 1:
                    sink.append(ep)
    n_epochs = (epochs[-1] + 1) if epochs else 0
    check("epoch_coverage", not missing and n_epochs > 0,
          f"{n_epochs - len(missing)}/{n_epochs} epochs closed by a "
          f"complete cohort" + (f"; missing {missing}" if missing else ""))
    check("order_digest_agreement", not disagree_order,
          "all complete cohorts agree" if not disagree_order
          else f"disagreement at epochs {sorted(set(disagree_order))}")
    check("shard_digest_agreement", not disagree_shard,
          "all complete cohorts agree" if not disagree_shard
          else f"disagreement at epochs {sorted(set(disagree_shard))}")

    # cumulative ingest bytes at each rank's LAST row (counters are
    # monotonic within an attempt; the last row is the attempt's total)
    last_by_rank: dict[int, int] = {}
    for r in sorted(rows, key=lambda r: (r["epoch"])):
        if isinstance(r.get("ingest_bytes"), (int, float)):
            last_by_rank[int(r["rank"])] = int(r["ingest_bytes"])
    loads = [b for b in last_by_rank.values() if b > 0]
    if len(loads) >= 2:
        share = sum(loads) / len(loads)
        worst = max(loads)
        ok = worst <= share * balance_limit
        check("ingest_balance", ok,
              f"max {worst} bytes vs even share {share:.0f} "
              f"(limit x{balance_limit:g}) across {len(loads)} ranks")
    else:
        check("ingest_balance", True,
              "fewer than 2 ranks recorded ingest bytes — skipped")
    if injections:
        last_inj_epoch = max(int(ev.get("epoch") or 0) for ev in injections)
        recovered = any(
            ep >= last_inj_epoch and complete_cohorts(by_epoch.get(ep, []))
            for ep in epochs)
        check("recovery", recovered,
              f"{len(injections)} injected kill(s), last at epoch "
              f"{last_inj_epoch}; "
              + ("a complete cohort closed at/after it"
                 if recovered else "no complete cohort after it"))
    verdict = "PASS" if all(c["ok"] for c in checks) else "FAIL"
    return {
        "verdict": verdict,
        "checks": checks,
        "counts": {
            "epochs": n_epochs,
            "close_rows": len(rows),
            "ranks": len({r["rank"] for r in rows}),
            "injections": len(injections),
        },
    }
