"""TPU slice provisioning — the compute-acquisition layer.

Successor of the reference's YARN resource acquisition: one command there
went `yarnClient.createApplication -> submitApplication ->
monitorApplication` (yarn/client/TensorflowClient.java:339-426) with the AM
allocating containers (yarn/appmaster/AMRMCallbackHandler.java:148-190).
On Cloud TPU the unit of compute is a *queued resource* — a slice request
the TPU scheduler fulfils when capacity frees — so acquisition is:

    create (queued-resources create)
      -> await ACTIVE (describe poll; WAITING_FOR_RESOURCES is the queue)
      -> derive worker hosts (tpu-vm describe networkEndpoints, worker order)
      -> run the pod (launcher/pod.py dispatch over ssh)
      -> release (queued-resources delete)

Everything shells out to `gcloud` (the supported control surface; no egress
assumptions beyond it), so tests drive the full flow against a fake gcloud
on PATH — the same technique as the fake-ssh transport e2e.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import time
from dataclasses import dataclass, replace
from typing import Callable, Optional, Sequence

# Hadoop-style XML keys (the shifu.* namespace, like every other subsystem)
KEY_NAME = "shifu.provision.name"
KEY_ACCELERATOR = "shifu.provision.accelerator-type"
KEY_ZONE = "shifu.provision.zone"
KEY_PROJECT = "shifu.provision.project"
KEY_RUNTIME_VERSION = "shifu.provision.runtime-version"
KEY_SPOT = "shifu.provision.spot"
KEY_TIMEOUT = "shifu.provision.ready-timeout-seconds"

# states a queued resource moves through (queued-resources describe)
_READY_STATES = ("ACTIVE",)
_PENDING_STATES = ("ACCEPTED", "PROVISIONING", "WAITING_FOR_RESOURCES",
                   "CREATING")
_DEAD_STATES = ("FAILED", "SUSPENDED", "SUSPENDING", "DELETING")


class ProvisionError(RuntimeError):
    """gcloud failed or the slice cannot become ready."""


@dataclass(frozen=True)
class ProvisionSpec:
    name: str
    accelerator_type: str            # e.g. v5litepod-16
    zone: str                        # e.g. us-west4-a
    project: str = ""                # "" = gcloud's configured default
    runtime_version: str = "tpu-ubuntu2204-base"
    spot: bool = False               # preemptible capacity
    ready_timeout_seconds: float = 1800.0
    poll_seconds: float = 10.0       # reference client polled every 10s
                                     # (TensorflowClient.java:625-658)

    def validate(self) -> None:
        missing = [k for k, v in (("name", self.name),
                                  ("accelerator-type", self.accelerator_type),
                                  ("zone", self.zone)) if not v]
        if missing:
            raise ProvisionError(
                "provisioning needs shifu.provision."
                + "/".join(missing)
                + " (or the matching --provision-* flags)")


def spec_from_xml(conf: dict, **overrides) -> ProvisionSpec:
    """Build a spec from shifu.provision.* keys, overridden by kwargs
    (CLI flags are the programmatic layer, like the reference's)."""
    from ..utils.xmlconfig import parse_bool
    raw_timeout = conf.get(KEY_TIMEOUT, ProvisionSpec.ready_timeout_seconds)
    try:
        timeout = float(raw_timeout)
    except (TypeError, ValueError):
        raise ProvisionError(
            f"{KEY_TIMEOUT} must be a number of seconds, got "
            f"{raw_timeout!r}") from None
    spec = ProvisionSpec(
        name=conf.get(KEY_NAME, ""),
        accelerator_type=conf.get(KEY_ACCELERATOR, ""),
        zone=conf.get(KEY_ZONE, ""),
        project=conf.get(KEY_PROJECT, ""),
        runtime_version=conf.get(KEY_RUNTIME_VERSION,
                                 ProvisionSpec.runtime_version),
        spot=parse_bool(conf.get(KEY_SPOT, False)),
        ready_timeout_seconds=timeout,
    )
    fields = {k: v for k, v in overrides.items() if v}
    return replace(spec, **fields) if fields else spec


def _gcloud_bin() -> str:
    path = shutil.which("gcloud")
    if not path:
        raise ProvisionError(
            "no `gcloud` on PATH — provisioning drives Cloud TPU queued "
            "resources through the gcloud CLI")
    return path


def _run(args: Sequence[str]) -> str:
    proc = subprocess.run([_gcloud_bin(), *args], capture_output=True,
                          text=True)
    if proc.returncode != 0:
        raise ProvisionError(
            f"gcloud {' '.join(args[:4])}... failed (rc={proc.returncode}): "
            f"{proc.stderr.strip()[:500]}")
    return proc.stdout


def _common(spec: ProvisionSpec) -> list[str]:
    out = ["--zone", spec.zone]
    if spec.project:
        out += ["--project", spec.project]
    return out


def create(spec: ProvisionSpec, echo=print) -> None:
    """Submit the slice request (node id == queued-resource id == name)."""
    spec.validate()
    args = ["compute", "tpus", "queued-resources", "create", spec.name,
            "--node-id", spec.name,
            "--accelerator-type", spec.accelerator_type,
            "--runtime-version", spec.runtime_version,
            *_common(spec)]
    if spec.spot:
        args.append("--spot")
    echo(f"provision: requesting {spec.accelerator_type} in {spec.zone} "
         f"as {spec.name!r}" + (" (spot)" if spec.spot else ""))
    _run(args)


def state(spec: ProvisionSpec) -> str:
    out = _run(["compute", "tpus", "queued-resources", "describe", spec.name,
                *_common(spec), "--format", "json"])
    doc = json.loads(out or "{}")
    st = doc.get("state")
    if isinstance(st, dict):  # API nests it: {"state": {"state": "ACTIVE"}}
        st = st.get("state")
    return str(st or "UNKNOWN").upper()


def await_ready(spec: ProvisionSpec, echo=print) -> None:
    """Poll until ACTIVE; raise on a dead state or timeout (the successor
    of the client-side monitor loop, TensorflowClient.java:625-658)."""
    deadline = time.monotonic() + spec.ready_timeout_seconds
    last = None
    while True:
        st = state(spec)
        if st != last:
            echo(f"provision: {spec.name} is {st}")
            last = st
        if st in _READY_STATES:
            return
        if st in _DEAD_STATES:
            raise ProvisionError(f"queued resource {spec.name} entered "
                                 f"terminal state {st}")
        if time.monotonic() > deadline:
            raise ProvisionError(
                f"queued resource {spec.name} not ready after "
                f"{spec.ready_timeout_seconds:.0f}s (last state {st}); it "
                "remains queued — `shifu-tpu provision delete` to release")
        time.sleep(spec.poll_seconds)


def worker_hosts(spec: ProvisionSpec) -> list[str]:
    """The slice's worker IPs in WORKER ORDER — the order that defines the
    jax.distributed process ids (launcher/pod.py dispatch)."""
    out = _run(["compute", "tpus", "tpu-vm", "describe", spec.name,
                *_common(spec), "--format", "json"])
    doc = json.loads(out or "{}")
    endpoints = doc.get("networkEndpoints") or []
    hosts = [e.get("ipAddress", "") for e in endpoints]
    hosts = [h for h in hosts if h]
    if not hosts:
        raise ProvisionError(
            f"tpu-vm describe {spec.name} returned no networkEndpoints — "
            "is the node ready?")
    return hosts


def serving_hosts(spec: ProvisionSpec) -> str:
    """The slice's workers as a `--hosts`-grammar string (comma-joined,
    worker order) — what `shifu-tpu fleet --hosts` / `shifu.fleet.hosts`
    consume to place serving members on a provisioned slice through the
    same launcher/pod.py ssh transport the training gang uses."""
    return ",".join(worker_hosts(spec))


def delete(spec: ProvisionSpec, echo=print) -> bool:
    """Release the slice (idempotent best-effort: releasing twice or
    releasing a failed create must not mask the original error).  Returns
    True when gcloud accepted the delete — callers keeping a release
    trail (the provision.json marker) must NOT clear it on False.

    A NOT_FOUND answer counts as a successful release: the resource never
    materialized (create itself failed) or is already gone, and either way
    there is nothing left to bill — treating it as failure would pin the
    marker forever and make every later `kill` retry a delete that can
    never succeed.  The match is anchored to the RESOURCE (the NOT_FOUND
    API code, or 'not found' near the slice's own name): a 'project foo
    not found' / 'zone bar not found' environment error at release time
    must stay a FAILURE so the still-billing slice keeps its trail."""
    import re as re_lib
    try:
        _run(["compute", "tpus", "queued-resources", "delete", spec.name,
              *_common(spec), "--quiet", "--force"])
        echo(f"provision: released {spec.name}")
        return True
    except ProvisionError as e:
        msg = str(e)
        name = re_lib.escape(spec.name)
        if ("NOT_FOUND" in msg
                or re_lib.search(name + r".{0,60}not found", msg, re_lib.I)
                or re_lib.search(r"not found.{0,60}" + name, msg, re_lib.I)):
            echo(f"provision: {spec.name} not found — nothing to release")
            return True
        echo(f"provision: release of {spec.name} failed ({e}); release "
             "manually with `gcloud compute tpus queued-resources delete`")
        return False


MARKER_FILE = "provision.json"


def write_marker(spec: ProvisionSpec, out_dir: str, keep: bool = False,
                 echo=print) -> None:
    """Durable record of the acquired slice in the JOB DIR: if the
    provisioning dispatcher dies uncleanly (SIGKILL, host loss) between
    create and release, the billing slice would otherwise leak with no
    record outside `gcloud list` — the marker lets `kill <job_dir>` (and
    an operator reading the dir) find and release it.  Best-effort and
    local-only: a remote job dir keeps its authoritative state in gcloud
    itself."""
    try:
        from ..data import fsio
        if fsio.is_remote(out_dir):
            return
        os.makedirs(out_dir, exist_ok=True)
        # the dispatcher's pid+host let a later `kill <job_dir>` tell a
        # LIVE foreground provision run (which writes no job.json) from a
        # dead one before releasing the slice out from under a gang
        with open(os.path.join(out_dir, MARKER_FILE), "w") as f:
            json.dump({"name": spec.name, "zone": spec.zone,
                       "project": spec.project, "keep": bool(keep),
                       "pid": os.getpid(), "host": os.uname().nodename,
                       "created_at": time.time()}, f)
    except Exception as e:  # never fail the job for bookkeeping
        echo(f"provision: could not record {MARKER_FILE} ({e})")


def clear_marker(out_dir: str) -> None:
    try:
        os.unlink(os.path.join(out_dir, MARKER_FILE))
    except OSError:
        pass


def read_marker(out_dir: str) -> Optional[dict]:
    try:
        with open(os.path.join(out_dir, MARKER_FILE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def release_from_marker(out_dir: str, echo=print) -> bool:
    """Release the slice a marker records (used by `kill <job_dir>` —
    YARN parity: killing the application frees its containers).  Returns
    True when a release was attempted and the marker cleared; a marker
    with keep=True is respected and left in place."""
    marker = read_marker(out_dir)
    if not marker or not marker.get("name"):
        return False
    if marker.get("keep"):
        echo(f"provision: slice {marker['name']!r} was kept deliberately "
             "(--keep-slice); not releasing")
        return False
    spec = ProvisionSpec(name=marker["name"],
                         accelerator_type="-",  # delete needs name+zone only
                         zone=marker.get("zone", ""),
                         project=marker.get("project", ""))
    if delete(spec, echo=echo):
        clear_marker(out_dir)  # gcloud REFUSED -> keep the release trail
        return True
    return False


def provision_and_run(spec: ProvisionSpec,
                      run_fn: Callable[[list[str]], int],
                      echo=print,
                      keep: bool = False,
                      marker_dir: Optional[str] = None) -> int:
    """The one-command lifecycle: nothing -> slice -> gang -> released.

    `run_fn(hosts)` runs the job (the pod dispatch) once the slice is
    ACTIVE; the slice is released on EVERY exit path unless `keep` (a
    failed run must not leak a billing TPU — the YARN analog was the RM
    reclaiming containers when the app died).  `marker_dir` records the
    acquisition durably so even an UNCLEAN dispatcher death leaves a
    release trail (write_marker) — written BEFORE the create call, so a
    death mid-create still leaves the trail (a marker for a slice that
    never materialized is harmless: delete answers NOT_FOUND, which counts
    as released, so the marker drains instead of orphaning)."""
    # Whether the marker dir already trailed THIS slice name before we
    # (re)wrote it: a same-name, unkept marker survives the clobber guard
    # precisely because it is the trail of a previous unclean death — and
    # that is also the case where create() answers ALREADY_EXISTS (the
    # dead run's slice still exists and bills).  In that case the marker
    # is the ONLY release path (`kill --force`), so it must be kept.
    prior_same_name_trail = False
    if marker_dir:
        # a marker dir holds ONE release trail: clobbering a previous
        # run's marker for a DIFFERENT slice — or for a deliberately KEPT
        # one — would destroy the only record of a still-billing TPU.
        # Refuse loudly; overwriting our own (same-name, unkept) stale
        # trail is fine — delete is idempotent for the same resource.
        existing = read_marker(marker_dir)
        if existing and existing.get("name") and (
                existing["name"] != spec.name or existing.get("keep")):
            raise ProvisionError(
                f"{marker_dir}/{MARKER_FILE} already records slice "
                f"{existing['name']!r}"
                + (" (kept with --keep-slice)" if existing.get("keep")
                   else "")
                + " — release it first (`shifu-tpu kill --force "
                f"{marker_dir}` or gcloud delete) or use a different "
                "--output")
        prior_same_name_trail = bool(existing and existing.get("name"))
        # written UNKEPT even under --keep-slice: the keep flag makes
        # release_from_marker refuse unconditionally, and until create()
        # succeeds this marker may be trailing a PREVIOUS unclean death's
        # still-billing slice (the ALREADY_EXISTS branch below), whose only
        # kill path it is.  The keep flag is recorded once create() proves
        # the slice is this run's own.
        write_marker(spec, marker_dir, keep=False, echo=echo)
    release = True
    try:
        # create() inside the release scope: a failed create still runs
        # the delete (NOT_FOUND -> released) so the marker never orphans.
        # EXCEPT name collisions: ALREADY_EXISTS means a slice this run
        # did NOT create (e.g. an earlier --keep-slice run) — releasing
        # it would tear down a live slice we don't own, so drop only our
        # marker and leave the resource alone.  UNLESS the marker dir
        # already trailed this same name before this run: then the
        # colliding slice is a previous unclean death's still-billing
        # resource and the marker is its only kill path — keep it.
        try:
            create(spec, echo=echo)
        except ProvisionError as e:
            if ("ALREADY_EXISTS" in str(e)
                    or "already exists" in str(e).lower()):
                release = False
                if marker_dir and not prior_same_name_trail:
                    clear_marker(marker_dir)
            raise
        if marker_dir and keep:
            # create succeeded: the slice is ours — NOW record the keep
            # flag so the clobber guard protects it from later runs
            write_marker(spec, marker_dir, keep=True, echo=echo)
        await_ready(spec, echo=echo)
        hosts = worker_hosts(spec)
        echo(f"provision: {len(hosts)} worker hosts: {', '.join(hosts)}")
        return run_fn(hosts)
    finally:
        if not release:
            pass
        elif keep:
            echo(f"provision: keeping {spec.name} (--keep-slice)")
        elif delete(spec, echo=echo) and marker_dir:
            clear_marker(marker_dir)
