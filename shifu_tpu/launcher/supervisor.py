"""Restart supervisor: failure detection + checkpoint-restart elasticity.

The SPMD successor of the reference's whole fault-tolerance subsystem
(SURVEY.md section 5.3): heartbeat liveness (TensorflowApplicationMaster.java:
63-112), exit-code accounting (TensorflowSession.java:417-460), and
hot-standby backup promotion (weakupBackup, TensorflowSession.java:748-781).
Under SPMD any chip loss kills the step, so hot standbys are replaced by:
run the training job as a child process; if it dies, restart it (bounded by
max_restarts) and let checkpoint auto-resume continue from the last saved
epoch; if it stops making progress (no board writes within the liveness
window), kill and restart — the heartbeat analog.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Optional, Sequence

# The reference's default liveness window was 1s heartbeat x 25 allowed
# misses (GlobalConfigurationKeys.java:76-79).  Here the heartbeat is the
# per-EPOCH board write, so a fixed 25s default would false-kill any epoch
# longer than that; liveness is off unless `shifu.liveness.seconds` (or the
# reference heartbeat key pair) sets a window sized to the job's epochs.

# Shifu-style exit status for a job timeout (mirrors cli.EXIT_TIMEOUT; kept
# local so the supervisor never imports the CLI module it launches).  A
# timeout is TERMINAL: the reference client killed the app once and stopped
# (TensorflowClient.java:625-658) — restarting a timed-out child would run
# the job forever in timeout-sized chunks, because each attempt checkpoints
# (progress resets the restart budget) and re-derives a fresh deadline.
EXIT_TIMEOUT = 3

# set on supervised attempt children (VALUE = the spawning parent's pid):
# cli._arm_pdeathsig reads it and arms PR_SET_PDEATHSIG(SIGTERM), so even
# an uncatchable supervisor death (SIGKILL / OOM kill) tears the training
# attempt down instead of leaving it spinning in its own session.  The pid
# value (not a bare flag) lets the child close the fork->arm race by
# comparing os.getppid() — correct even when the recorded parent is
# legitimately pid 1 (container entrypoint) or under a subreaper
ENV_PDEATHSIG = "SHIFU_TPU_PDEATHSIG"


def _marker_epoch(ckpt_dir: str) -> int:
    """Epoch from the `PROGRESS` marker file (-1 if absent/unreadable);
    works for remote (gs://, hdfs://) dirs via fsio."""
    import json

    from ..train.checkpoint import PROGRESS_MARKER

    try:
        from ..data import fsio
        if fsio.is_remote(ckpt_dir):
            raw = fsio.read_bytes(ckpt_dir.rstrip("/") + "/" + PROGRESS_MARKER)
        else:
            with open(os.path.join(ckpt_dir, PROGRESS_MARKER), "rb") as f:
                raw = f.read()
        return int(json.loads(raw).get("epoch", -1))
    except Exception:
        return -1


def _committed_step_epoch(ckpt_dir: str) -> int:
    """Epoch recorded in the newest FINALIZED orbax step's own `extra`
    metadata (-1 if none).  Crash-safe supplement to the marker: an async
    save can commit durably and the process die before the marker flush
    (the marker is only written once the save is KNOWN durable), so on a
    preemption-heavy job the marker may lag one epoch behind the
    restorable checkpoint — the checkpoint itself is the authority."""
    import json

    try:
        names = sorted((n for n in os.listdir(ckpt_dir) if n.isdigit()),
                       key=int, reverse=True)
    except OSError:
        return -1
    for name in names:
        step_dir = os.path.join(ckpt_dir, name)
        # _CHECKPOINT_METADATA exists only once orbax commits the step
        if not os.path.exists(os.path.join(step_dir, "_CHECKPOINT_METADATA")):
            continue
        try:
            with open(os.path.join(step_dir, "extra", "metadata")) as f:
                return int(json.load(f).get("epoch", -1))
        except (OSError, ValueError):
            continue
    return -1


def _committed_step_epoch_remote(ckpt_dir: str) -> int:
    """_committed_step_epoch for gs:// hdfs:// mock:// checkpoint dirs via
    fsio — one directory listing + two small reads per probe.  Without it a
    preemption-heavy remote-checkpoint job whose attempts each commit one
    async save (marker flush pending when the kill lands) would look like
    NO progress every attempt and exhaust the restart budget."""
    import json

    try:
        from pyarrow import fs as pafs

        from ..data import fsio
        filesystem, fs_path = fsio._filesystem(ckpt_dir)
        base = fs_path.rstrip("/")
        infos = filesystem.get_file_info(
            pafs.FileSelector(base, recursive=False))
        steps = sorted((int(i.base_name) for i in infos
                        if i.type == pafs.FileType.Directory
                        and i.base_name.isdigit()), reverse=True)
        for s in steps:
            meta = filesystem.get_file_info(
                f"{base}/{s}/_CHECKPOINT_METADATA")
            if meta.type != pafs.FileType.File:
                continue  # tmp/uncommitted step
            try:
                with filesystem.open_input_stream(
                        f"{base}/{s}/extra/metadata") as f:
                    return int(json.loads(f.read()).get("epoch", -1))
            except Exception:
                continue
    except Exception:
        return -1
    return -1


def checkpoint_progress(ckpt_dir: Optional[str]) -> int:
    """Durable progress of a checkpoint dir: the max of the EPOCH recorded
    in the `PROGRESS` marker and (local dirs) the epoch inside the newest
    committed orbax step's extra metadata (-1 if neither exists).

    Why epoch, not raw step: console/board lines print before the epoch's
    conditional save, so log text can claim progress a crash never
    persisted; and the global step re-inflates when a mid-epoch resume
    replays the interrupted epoch, so a deterministic mid-epoch crash loop
    would look like progress forever.  Both sources carry the epoch the
    train loop actually persisted.

    Last-resort fallback for pre-marker, pre-extra checkpoints (local
    only): the largest digit-named orbax step dir, counted as
    epoch-equivalent."""
    if not ckpt_dir:
        return -1
    marker = _marker_epoch(ckpt_dir)
    remote = False
    try:
        from ..data import fsio
        remote = fsio.is_remote(ckpt_dir)
    except Exception:
        pass
    committed = (_committed_step_epoch_remote(ckpt_dir) if remote
                 else _committed_step_epoch(ckpt_dir))
    if marker >= 0 or committed >= 0:
        return max(marker, committed)
    if not os.path.isdir(ckpt_dir):
        return -1
    best = -1
    try:
        for name in os.listdir(ckpt_dir):
            if name.isdigit():
                best = max(best, int(name))
    except OSError:
        return -1
    return best


class ProgressProbe:
    """Per-attempt durable-progress capture/compare, shared by both
    supervisors so the budget semantics stay defined once."""

    def __init__(self, ckpt_dir: Optional[str]):
        self._dir = ckpt_dir
        self._mark = checkpoint_progress(ckpt_dir)

    def advanced(self) -> bool:
        return (self._dir is not None
                and checkpoint_progress(self._dir) > self._mark)


def charge_restart_budget(failures_since_progress: int, progressed: bool,
                          echo=print, what: str = "supervisor") -> int:
    """Shared budget accounting for both supervisors: the budget bounds
    CONSECUTIVE failures without durable progress, not lifetime restarts —
    a long job on preemptible capacity legitimately restarts many times,
    each resuming further from checkpoint (monotone progress -> eventual
    completion); only a crash loop that persists nothing burns it."""
    if progressed:
        if failures_since_progress:
            echo(f"{what}: progress since last failure — restart budget "
                 "reset")
        return 1
    return failures_since_progress + 1


def _telemetry_dir(board_path: Optional[str]) -> Optional[str]:
    """Where the supervisor's journal lives: SHIFU_TPU_METRICS_DIR when
    set, else `<job dir>/telemetry` derived from the board path — the same
    dir the train child writes, so restarts and epochs interleave in ONE
    journal (append-only JSONL tolerates two writers)."""
    from .. import obs

    d = obs.resolve_metrics_dir()
    if d:
        return d
    if not board_path:
        return None
    try:
        from ..data import fsio
        if fsio.is_remote(board_path):
            return fsio.join(board_path.rsplit("/", 1)[0], "telemetry")
        return os.path.join(os.path.dirname(os.path.abspath(board_path)),
                            "telemetry")
    except Exception:
        return None


def _board_size(path: str) -> int:
    """Board progress signature for the liveness monitor, -1 when missing —
    fsio for remote (gs:// hdfs://) job dirs, os.stat locally.

    Remote boards fold the object's mtime into the signature: once the
    board's retained-line cap engages (console.py), every rewrite drops one
    line and appends one of similar length, so SIZE alone plateaus and a
    size-only probe would false-kill a healthy long job as 'no progress'.
    The store's mtime advances on every rewrite regardless."""
    try:
        from ..data import fsio
        if fsio.is_remote(path):
            size, mtime_ns = fsio.file_info(path)
            if size is None and mtime_ns is None:
                return -1
            return int(size or 0) + int(mtime_ns or 0)
    except Exception:
        return -1
    try:
        return os.path.getsize(path)
    except OSError:
        return -1


class JobDeadline:
    """ONE clock for the whole job, shared across attempts — the semantic
    core of the timeout-is-terminal fix, defined once for both supervisors
    (like charge_restart_budget for the restart budget).  The child
    re-derives a fresh per-attempt deadline it may never hit; the
    supervisors enforce this job-level one."""

    def __init__(self, timeout_seconds: float):
        self.seconds = timeout_seconds
        self._at = (time.monotonic() + timeout_seconds
                    if timeout_seconds > 0 else None)

    def expired(self) -> bool:
        return self._at is not None and time.monotonic() > self._at


class _Terminated(Exception):
    """A stop signal (SIGTERM from a scheduler, SIGHUP from an ssh drop)
    arrived at the supervisor parent."""


def _raise_terminated(signum, frame):
    raise _Terminated()


def _kill_tree(proc: subprocess.Popen, sig: Optional[int] = None,
               grace_seconds: float = 5.0) -> None:
    """Signal the child's whole process group (the child is spawned with
    start_new_session=True), escalating to SIGKILL after a grace window.
    A bare proc.kill() would orphan gang grandchildren under `--supervise
    --num-processes N`: the spawner dies uncatchably, its launch_gang
    teardown never runs, and the workers keep training after the CLI
    reported a terminal status.

    sig=None hard-kills immediately (liveness kills target a HUNG tree —
    grace would just wait on a wedged process); SIGTERM/SIGINT give the
    train loop's drain handler a window to finalize the in-flight
    checkpoint before the escalation."""
    import signal

    def _pg(s: int) -> None:
        try:
            os.killpg(proc.pid, s)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                proc.send_signal(s)
            except (ProcessLookupError, OSError):
                pass

    if sig is not None:
        _pg(sig)
        try:
            proc.wait(timeout=grace_seconds)
        except subprocess.TimeoutExpired:
            pass
    if proc.poll() is None:
        _pg(signal.SIGKILL)
    proc.wait()


def supervise(child_argv: Sequence[str],
              max_restarts: int = 2,
              board_path: Optional[str] = None,
              liveness_seconds: float = 0.0,
              poll_seconds: float = 0.5,
              python: Optional[str] = None,
              checkpoint_dir: Optional[str] = None,
              timeout_seconds: float = 0.0) -> int:
    """Run `python -m shifu_tpu.launcher.cli <child_argv>` with restarts.

    Returns the child's final exit code (0 on eventual success).  A child that
    fails (nonzero exit / killed) is restarted up to max_restarts times;
    checkpoint auto-resume makes the restart continue, not repeat.  If
    liveness_seconds > 0 and the board file stops growing for that long
    (a still-missing board counts as no progress, catching children wedged
    before their first write), the child is presumed hung, killed, and the
    restart budget is charged — heartbeat-expiry parity.  Size the window
    above startup (jax import + first compile) plus one epoch.

    timeout_seconds > 0 bounds the WHOLE JOB, not one attempt: the deadline
    is derived from the first attempt's start, and both a child exiting
    EXIT_TIMEOUT and the supervisor's own deadline check are terminal
    (exit 3, no restart) — client-side-timeout-kill parity
    (TensorflowClient.java:625-658).
    """
    import signal as signal_lib

    from .. import obs

    # journal-only sinks (scrape=False): the train CHILD owns the scrape
    # file; the parent journals the restart/liveness story beside it so
    # `shifu-tpu metrics` shows one merged timeline.  Local dirs share the
    # child's journal (O_APPEND tolerates two writers); REMOTE dirs get a
    # sidecar object — remote journals are whole-object rewrites of the
    # writer's own lines, so sharing one object would erase the child's
    # events on every parent flush (render merges the sidecar back in)
    tele_dir = _telemetry_dir(board_path)
    if tele_dir:
        remote_tele = False
        try:
            from ..data import fsio
            remote_tele = fsio.is_remote(tele_dir)
        except Exception:
            pass
        obs.configure(tele_dir, scrape=False, flush_every=1,
                      journal_name=("journal-supervisor.jsonl" if remote_tele
                                    else "journal.jsonl"))
    # journal events only, no parent-side counters: the parent never
    # exports a scrape file (scrape=False), so registry counters here
    # would be write-only — the supervisor_restart/liveness_kill events
    # carry the same data into the merged timeline
    obs.event("supervisor_start", max_restarts=max_restarts,
              liveness_seconds=liveness_seconds,
              timeout_seconds=timeout_seconds)
    python = python or sys.executable
    cmd = [python, "-m", "shifu_tpu.launcher.cli", *child_argv]
    attempts = 0
    failures_since_progress = 0
    deadline = JobDeadline(timeout_seconds)
    # the child runs in its own session (so kills reach the whole gang
    # tree), which detaches it from external group-wide signals — a
    # scheduler SIGTERM or an ssh-drop SIGHUP to this parent must be
    # forwarded, not orphan the training tree
    old_handlers: list[tuple[int, object]] = []
    try:
        for s in (signal_lib.SIGTERM, signal_lib.SIGHUP):
            if signal_lib.getsignal(s) is signal_lib.SIG_IGN:
                continue  # nohup'd: SIGHUP is ignored on purpose — keep it
            old_handlers.append((s, signal_lib.signal(s, _raise_terminated)))
    except ValueError:  # non-main thread: no handlers, kills still work
        pass
    proc: Optional[subprocess.Popen] = None
    try:
        while True:
            if deadline.expired():
                # don't spawn a doomed attempt just to kill it one poll later
                print("supervisor: job timeout exceeded — terminal, "
                      "no restart", flush=True)
                obs.event("supervisor_timeout", attempts=attempts)
                return EXIT_TIMEOUT
            attempts += 1
            start = time.monotonic()
            probe = ProgressProbe(checkpoint_dir)
            # the child arms PR_SET_PDEATHSIG against THIS process at its
            # startup (cli._arm_pdeathsig): an UNCATCHABLE supervisor death
            # (SIGKILL, OOM kill) must not orphan a training process in its
            # own session to spin forever — observed exactly that when a
            # detached daemon was SIGKILLed out from under its attempt
            child_env = dict(os.environ)
            child_env[ENV_PDEATHSIG] = str(os.getpid())
            spawn_cmd = cmd
            try:
                # chaos site "supervisor.spawn": a child that cannot even
                # start (bad node, OOM-killed at exec) — modeled as a stub
                # that exits with the fault's code, so the restart budget
                # and progress accounting see a real failed attempt
                from .. import chaos
                chaos.maybe_fail("supervisor.spawn", attempt=attempts)
            except chaos.ChaosError as e:
                print(f"supervisor: chaos: attempt {attempts} spawn fails "
                      f"({e})", flush=True)
                spawn_cmd = [python, "-c",
                             f"import sys; sys.exit({int(e.exit_code)})"]
            proc = subprocess.Popen(spawn_cmd, start_new_session=True,
                                    env=child_env)
            last_size = -1
            last_progress = time.monotonic()
            killed_for_hang = False
            try:
                while True:
                    rc = proc.poll()
                    if rc is not None:
                        break
                    if deadline.expired():
                        print(f"supervisor: job timeout "
                              f"({timeout_seconds:.0f}s) exceeded — killing "
                              f"attempt {attempts}", flush=True)
                        obs.event("supervisor_timeout", attempt=attempts)
                        # graceful first: the child is healthy (not hung) and
                        # its SIGTERM drain can finalize the checkpoint
                        _kill_tree(proc, signal_lib.SIGTERM)
                        return EXIT_TIMEOUT
                    if liveness_seconds > 0 and board_path:
                        # a missing board counts as "no progress since attempt
                        # start": a child wedged BEFORE its first board write
                        # (a stuck distributed rendezvous, a hung kinit) must
                        # be detected too — the window therefore has to cover
                        # startup (jax import + first compile) plus an epoch
                        size = _board_size(board_path)
                        if size != last_size:
                            last_size = size
                            last_progress = time.monotonic()
                        elif (time.monotonic() - last_progress
                                > liveness_seconds):
                            print(f"supervisor: no progress for "
                                  f"{liveness_seconds}s — killing attempt "
                                  f"{attempts}", flush=True)
                            obs.event("supervisor_liveness_kill",
                                      attempt=attempts,
                                      window_s=liveness_seconds)
                            # hung tree: no grace, hard-kill immediately
                            _kill_tree(proc)
                            rc = -9
                            killed_for_hang = True
                            break
                    time.sleep(poll_seconds)
            except KeyboardInterrupt:
                # the new session detaches the child from the terminal's
                # process group, so Ctrl-C no longer reaches it — forward
                # SIGINT (graceful unwind) before the SIGKILL escalation
                _kill_tree(proc, signal_lib.SIGINT)
                raise
            if rc == 0:
                if attempts > 1:
                    print(f"supervisor: succeeded after {attempts} attempts",
                          flush=True)
                obs.event("supervisor_done", attempts=attempts)
                return 0
            if rc == EXIT_TIMEOUT:
                # terminal: a timed-out job must not restart (each attempt
                # would checkpoint, reset the budget, and re-derive a fresh
                # deadline — an infinite loop in timeout-sized chunks)
                print(f"supervisor: attempt {attempts} hit the job timeout — "
                      "terminal, no restart", flush=True)
                return EXIT_TIMEOUT
            elapsed = time.monotonic() - start
            # durable progress only: the checkpoint epoch advanced this attempt
            progressed = probe.advanced()
            failures_since_progress = charge_restart_budget(
                failures_since_progress, progressed)
            print(f"supervisor: attempt {attempts} exited rc={rc} "
                  f"after {elapsed:.1f}s"
                  + (" (liveness kill)" if killed_for_hang else ""), flush=True)
            obs.event("supervisor_restart", attempt=attempts, rc=rc,
                      progressed=progressed,
                      liveness_kill=killed_for_hang,
                      elapsed_s=round(elapsed, 2))
            if failures_since_progress > max_restarts:
                print(f"supervisor: restart budget exhausted "
                      f"({max_restarts} restarts without progress)", flush=True)
                obs.event("supervisor_exhausted", attempts=attempts, rc=rc)
                return rc if isinstance(rc, int) and rc > 0 else 1
    except _Terminated:
        # catches the signal wherever it lands — inside the poll loop,
        # between attempts, or in the Popen→try window — so a live
        # session-leader child is always drained, never orphaned
        print("supervisor: stop signal (SIGTERM/SIGHUP) — draining the job",
              flush=True)
        # a second signal during the drain must not abort the drain (it
        # would skip the SIGKILL escalation and leak the child group)
        for s, _h in old_handlers:
            signal_lib.signal(s, signal_lib.SIG_IGN)
        if proc is not None and proc.poll() is None:
            # preemption grace: the child's SIGTERM drain saves one final
            # in-band checkpoint at the current step before exiting — give
            # that save a wider window than the hung-tree default before
            # the SIGKILL escalation (the wait returns as soon as the
            # child exits, so a fast drain pays nothing extra)
            _kill_tree(proc, signal_lib.SIGTERM, grace_seconds=15.0)
        return 143
    finally:
        for s, h in old_handlers:
            signal_lib.signal(s, h)
