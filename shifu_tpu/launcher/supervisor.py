"""Restart supervisor: failure detection + checkpoint-restart elasticity.

The SPMD successor of the reference's whole fault-tolerance subsystem
(SURVEY.md section 5.3): heartbeat liveness (TensorflowApplicationMaster.java:
63-112), exit-code accounting (TensorflowSession.java:417-460), and
hot-standby backup promotion (weakupBackup, TensorflowSession.java:748-781).
Under SPMD any chip loss kills the step, so hot standbys are replaced by:
run the training job as a child process; if it dies, restart it (bounded by
max_restarts) and let checkpoint auto-resume continue from the last saved
epoch; if it stops making progress (no board writes within the liveness
window), kill and restart — the heartbeat analog.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Optional, Sequence

# The reference's default liveness window was 1s heartbeat x 25 allowed
# misses (GlobalConfigurationKeys.java:76-79).  Here the heartbeat is the
# per-EPOCH board write, so a fixed 25s default would false-kill any epoch
# longer than that; liveness is off unless `shifu.liveness.seconds` (or the
# reference heartbeat key pair) sets a window sized to the job's epochs.


def checkpoint_progress(ckpt_dir: Optional[str]) -> int:
    """Durable progress of a checkpoint dir: the EPOCH recorded in the
    `PROGRESS` marker the train loop writes after every save (-1 if none).

    Why this signal: console/board lines print before the epoch's
    conditional save, so log text can claim progress a crash never
    persisted; the raw global step re-inflates when a mid-epoch resume
    replays the interrupted epoch, so a deterministic mid-epoch crash loop
    would look like progress forever.  The marker's epoch only advances
    when a NEW epoch's save lands.  Works for remote (gs://, hdfs://)
    checkpoint dirs too — one small file read via fsio.

    Fallback for pre-marker checkpoints (local only): the largest
    digit-named finalized orbax step dir, counted as epoch-equivalent."""
    if not ckpt_dir:
        return -1
    import json

    from ..train.checkpoint import PROGRESS_MARKER

    try:
        from ..data import fsio
        if fsio.is_remote(ckpt_dir):
            raw = fsio.read_bytes(ckpt_dir.rstrip("/") + "/" + PROGRESS_MARKER)
        else:
            with open(os.path.join(ckpt_dir, PROGRESS_MARKER), "rb") as f:
                raw = f.read()
        return int(json.loads(raw).get("epoch", -1))
    except Exception:
        pass
    if not os.path.isdir(ckpt_dir):
        return -1
    best = -1
    try:
        for name in os.listdir(ckpt_dir):
            if name.isdigit():
                best = max(best, int(name))
    except OSError:
        return -1
    return best


class ProgressProbe:
    """Per-attempt durable-progress capture/compare, shared by both
    supervisors so the budget semantics stay defined once."""

    def __init__(self, ckpt_dir: Optional[str]):
        self._dir = ckpt_dir
        self._mark = checkpoint_progress(ckpt_dir)

    def advanced(self) -> bool:
        return (self._dir is not None
                and checkpoint_progress(self._dir) > self._mark)


def charge_restart_budget(failures_since_progress: int, progressed: bool,
                          echo=print, what: str = "supervisor") -> int:
    """Shared budget accounting for both supervisors: the budget bounds
    CONSECUTIVE failures without durable progress, not lifetime restarts —
    a long job on preemptible capacity legitimately restarts many times,
    each resuming further from checkpoint (monotone progress -> eventual
    completion); only a crash loop that persists nothing burns it."""
    if progressed:
        if failures_since_progress:
            echo(f"{what}: progress since last failure — restart budget "
                 "reset")
        return 1
    return failures_since_progress + 1


def supervise(child_argv: Sequence[str],
              max_restarts: int = 2,
              board_path: Optional[str] = None,
              liveness_seconds: float = 0.0,
              poll_seconds: float = 0.5,
              python: Optional[str] = None,
              checkpoint_dir: Optional[str] = None) -> int:
    """Run `python -m shifu_tpu.launcher.cli <child_argv>` with restarts.

    Returns the child's final exit code (0 on eventual success).  A child that
    fails (nonzero exit / killed) is restarted up to max_restarts times;
    checkpoint auto-resume makes the restart continue, not repeat.  If
    liveness_seconds > 0 and the board file stops growing for that long
    (a still-missing board counts as no progress, catching children wedged
    before their first write), the child is presumed hung, killed, and the
    restart budget is charged — heartbeat-expiry parity.  Size the window
    above startup (jax import + first compile) plus one epoch.
    """
    python = python or sys.executable
    cmd = [python, "-m", "shifu_tpu.launcher.cli", *child_argv]
    attempts = 0
    failures_since_progress = 0
    while True:
        attempts += 1
        start = time.monotonic()
        probe = ProgressProbe(checkpoint_dir)
        proc = subprocess.Popen(cmd)
        last_size = -1
        last_progress = time.monotonic()
        killed_for_hang = False
        while True:
            rc = proc.poll()
            if rc is not None:
                break
            if liveness_seconds > 0 and board_path:
                # a missing board counts as "no progress since attempt
                # start": a child wedged BEFORE its first board write (a
                # stuck distributed rendezvous, a hung kinit) must be
                # detected too — the window therefore has to cover startup
                # (jax import + first compile) as well as an epoch
                size = (os.path.getsize(board_path)
                        if os.path.exists(board_path) else -1)
                if size != last_size:
                    last_size = size
                    last_progress = time.monotonic()
                elif time.monotonic() - last_progress > liveness_seconds:
                    print(f"supervisor: no progress for {liveness_seconds}s — "
                          f"killing attempt {attempts}", flush=True)
                    proc.kill()
                    proc.wait()
                    rc = -9
                    killed_for_hang = True
                    break
            time.sleep(poll_seconds)
        if rc == 0:
            if attempts > 1:
                print(f"supervisor: succeeded after {attempts} attempts", flush=True)
            return 0
        elapsed = time.monotonic() - start
        # durable progress only: the checkpoint's epoch advanced this attempt
        failures_since_progress = charge_restart_budget(
            failures_since_progress, probe.advanced())
        print(f"supervisor: attempt {attempts} exited rc={rc} "
              f"after {elapsed:.1f}s"
              + (" (liveness kill)" if killed_for_hang else ""), flush=True)
        if failures_since_progress > max_restarts:
            print(f"supervisor: restart budget exhausted "
                  f"({max_restarts} restarts without progress)", flush=True)
            return rc if isinstance(rc, int) and rc > 0 else 1
