"""Kerberos ticket acquisition for secured HDFS data access.

Successor of the reference client's delegation-token fetch
(TensorflowClient.java:481-502 obtained HDFS delegation tokens and shipped
them into every YARN container).  Under SPMD there are no containers to
ship credentials to: the single program authenticates once, before any
`hdfs://` I/O, via `kinit` against the configured principal/keytab
(`shifu.security.kerberos.{principal,keytab}`); libhdfs (pyarrow.fs
HadoopFileSystem — data/fsio.py) then reads the ambient ticket cache.
With no principal configured this is a no-op and any pre-existing ticket
cache is used as-is.
"""

from __future__ import annotations

import logging
import shutil
import subprocess

logger = logging.getLogger(__name__)


class KerberosError(RuntimeError):
    """kinit was required but unavailable or failed."""


def ensure_kerberos_ticket(principal: str = "", keytab: str = "") -> bool:
    """Acquire a ticket if a principal is configured.

    Returns True when a kinit ran successfully, False for the no-op case.
    Raises KerberosError when a principal is configured but the ticket
    cannot be obtained (missing kinit, missing keytab, kinit failure) —
    failing fast here beats an opaque libhdfs GSSAPI error mid-read.
    """
    principal = principal or ""
    keytab = keytab or ""
    if not principal:
        if keytab:
            raise KerberosError(
                f"shifu.security.kerberos.keytab={keytab!r} is configured "
                "without shifu.security.kerberos.principal — set the "
                "principal (misconfiguration would otherwise surface as an "
                "opaque GSSAPI failure mid-read)")
        return False
    if not keytab:
        # password-prompt kinit cannot work in a batch job (no tty to
        # prompt on); require the keytab rather than hang on stdin
        raise KerberosError(
            f"shifu.security.kerberos.principal={principal!r} is configured "
            "without shifu.security.kerberos.keytab — headless jobs need a "
            "keytab (interactive password entry is not supported)")
    kinit = shutil.which("kinit")
    if kinit is None:
        raise KerberosError(
            f"shifu.security.kerberos.principal={principal!r} is configured "
            "but no `kinit` binary is on PATH")
    cmd = [kinit, "-kt", keytab, principal]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120, stdin=subprocess.DEVNULL)
    except subprocess.TimeoutExpired as e:
        raise KerberosError(
            f"kinit timed out after 120s (KDC unreachable?): {' '.join(cmd)}"
        ) from e
    if proc.returncode != 0:
        raise KerberosError(
            f"kinit failed (rc={proc.returncode}): "
            f"{proc.stderr.strip() or proc.stdout.strip()}")
    logger.info("kerberos: ticket acquired for %s", principal)
    return True
