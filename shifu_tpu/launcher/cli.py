"""Job launcher CLI — the one-command successor of the reference's
TensorflowClient (yarn/client/TensorflowClient.java:290 main, args
`-globalconfig <xml> ...` at :147-154).

Usage:
    python -m shifu_tpu.launcher.cli train \
        --modelconfig ModelConfig.json --columnconfig ColumnConfig.json \
        --data /path/to/normalized [...] \
        [--globalconfig global.xml] [--output out_dir] [--devices N]
        [--supervise]

Where the reference client uploaded resources to HDFS, submitted a YARN AM,
and polled it every 10s (TensorflowClient.java:333-426,625-658), this runs
the single SPMD program in-process (or under the supervisor for
checkpoint-restart fault tolerance), streams per-epoch lines to the console
board, enforces the job timeout, exports the scoring artifact, and returns a
Shifu-style exit status (0 success / 1 failure / 3 timeout).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional, Sequence

EXIT_OK = 0
EXIT_FAIL = 1
EXIT_TIMEOUT = 3


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="shifu-tpu")
    sub = p.add_subparsers(dest="command", required=True)

    t = sub.add_parser("train", help="train a model from Shifu configs")
    t.add_argument("--modelconfig", required=True, help="Shifu ModelConfig.json")
    t.add_argument("--columnconfig", required=True, help="Shifu ColumnConfig.json")
    t.add_argument("--data", nargs="*", default=[], help="training data files/dirs")
    t.add_argument("--globalconfig", default=None,
                   help="Hadoop-style XML (-globalconfig parity)")
    t.add_argument("--output", default=None, help="job output dir")
    t.add_argument("--devices", type=int, default=0,
                   help="limit device count (0 = all)")
    t.add_argument("--epochs", type=int, default=0, help="override epochs")
    t.add_argument("--batch-size", type=int, default=0, help="override batch size")
    t.add_argument("--cache-dir", default=None,
                   help="parse-once columnar data cache dir (also via "
                        "SHIFU_TPU_DATA_CACHE)")
    t.add_argument("--timeout", type=int, default=0,
                   help="job timeout seconds (0 = none)")
    t.add_argument("--supervise", action="store_true",
                   help="run under the restart supervisor")
    t.add_argument("--num-processes", type=int, default=0,
                   help="spawn N coordinated processes on this machine "
                        "(multi-host simulation / multi-process training); "
                        "on a real pod run one process per host with the "
                        "SHIFU_TPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID env")
    t.add_argument("--hosts", default=None,
                   help="pod-scale launch: dispatch one process per host "
                        "with whole-gang supervised restart. Forms: "
                        "'h1,h2,...' (ssh, list in TPU worker order — the "
                        "TPU_WORKER_HOSTNAMES value), '@hostfile', or "
                        "'local:N' (simulated pod on this machine). Env "
                        "spelling: SHIFU_TPU_HOSTS")
    t.add_argument("--max-restarts", type=int, default=-1,
                   help="supervisor restart budget (-1 = from config)")
    t.add_argument("--coordinator-port", type=int, default=0,
                   help="ssh-pod rendezvous port on hosts[0] (default 8476; "
                        "env spelling: SHIFU_TPU_COORDINATOR_PORT)")
    t.add_argument("--detach", action="store_true",
                   help="submit and return immediately: the job runs under "
                        "a detached session-leader dispatcher that survives "
                        "this client (status/attach/kill drive it from the "
                        "job dir afterwards)")
    t.add_argument("--chaos-plan", default=None,
                   help="declarative fault-injection plan: inline JSON or a "
                        "path to a JSON file (schema in shifu_tpu/chaos/"
                        "plan.py, site catalog in docs/ROBUSTNESS.md); "
                        "exported to children as SHIFU_TPU_CHAOS_PLAN so a "
                        "supervised/pod job injects the same plan on every "
                        "attempt")
    t.add_argument("--provision", action="store_true",
                   help="acquire a TPU slice first (shifu.provision.* keys "
                        "/ --provision-* flags), dispatch the pod onto its "
                        "workers, release the slice when the job ends")
    t.add_argument("--keep-slice", action="store_true",
                   help="with --provision: leave the slice running after "
                        "the job (inspect/reuse; release with "
                        "`shifu-tpu provision delete`)")
    _add_provision_flags(t)

    pv = sub.add_parser(
        "provision", help="TPU slice lifecycle (queued resources): the "
                          "compute-acquisition step the reference client "
                          "got from YARN submitApplication")
    pv.add_argument("action", choices=["create", "status", "hosts", "delete"])
    pv.add_argument("--globalconfig", default=None,
                    help="Hadoop-style XML carrying shifu.provision.* keys")
    pv.add_argument("--wait", action="store_true",
                    help="with create: block until the slice is ACTIVE")
    _add_provision_flags(pv)

    st = sub.add_parser("status", help="report a detached job's state "
                                       "(RUNNING/FINISHED/FAILED + last "
                                       "progress line + telemetry summary) "
                                       "from its job dir")
    st.add_argument("job_dir")
    mt = sub.add_parser(
        "metrics", help="render a job's telemetry — run journal + "
                        "Prometheus scrape file — for a running or "
                        "finished job (see docs/OBSERVABILITY.md)")
    mt.add_argument("job_dir",
                    help="job dir, telemetry dir, or journal.jsonl path "
                         "(local or gs:// hdfs:// URI)")
    mt.add_argument("--json", action="store_true",
                    help="machine-readable summary dict instead of text")
    mt.add_argument("--follow", action="store_true",
                    help="stream journal events as JSONL until ^C "
                         "(tail_board for the structured stream)")
    pf = sub.add_parser(
        "profile", help="render a job's goodput ledger: per-epoch wall-time "
                        "buckets (compile/input/step/checkpoint/restore/"
                        "eval/other), MFU, top compiled functions by XLA "
                        "cost, and the recovery tax (docs/PERF.md "
                        "'Goodput & MFU')")
    pf.add_argument("job_dir",
                    help="job dir, telemetry dir, or journal.jsonl path "
                         "(local or gs:// hdfs:// URI)")
    pf.add_argument("--json", action="store_true",
                    help="machine-readable profile dict instead of text")
    tr = sub.add_parser(
        "trace", help="render a job's device flight recorder: per-kernel "
                      "device-time rollups from the captured trace "
                      "windows (compute- vs HBM-bound), the anomaly log "
                      "with its per-chunk ring, and HBM watermarks "
                      "(docs/OBSERVABILITY.md 'Device flight recorder')")
    tr.add_argument("job_dir",
                    help="job dir, telemetry dir, or journal.jsonl path "
                         "(local or gs:// hdfs:// URI)")
    tr.add_argument("--json", action="store_true",
                    help="machine-readable trace dict instead of text")
    tp = sub.add_parser(
        "top", help="live streaming view of a job or serving daemon — "
                    "rate/p50/p99, queue depth, lifecycle stage breakdown "
                    "(queue/coalesce/dispatch/device), active SLO alerts; "
                    "pass several dirs for a multi-daemon fleet rollup "
                    "(journal/scrape tail only — no jax import; "
                    "docs/OBSERVABILITY.md 'Serving SLO engine')")
    tp.add_argument("job_dirs", nargs="+",
                    help="job dir(s), telemetry dir(s), or journal.jsonl "
                         "path(s) — N dirs render the fleet rollup "
                         "(obs/aggregate.serving_rollup)")
    tp.add_argument("--once", action="store_true",
                    help="render one frame and exit (scripting / CI)")
    tp.add_argument("--json", action="store_true",
                    help="machine-readable frame(s): one JSON dict per "
                         "frame (JSONL when streaming)")
    tp.add_argument("--interval", type=float, default=2.0,
                    help="refresh seconds for the streaming view "
                         "(default 2)")
    tp.add_argument("--stale-after", type=float, default=None,
                    help="mark a daemon DOWN when its freshest signal "
                         "(fleet lease or journal tail) is older than "
                         "this many seconds (default: each member's own "
                         "lease ttl when present, else never)")
    ch = sub.add_parser(
        "cache", help="inspect the columnar data cache: list entries "
                      "(tier/version/bytes/source) and prune superseded, "
                      "orphaned, or legacy-format ones (data/cache.py, "
                      "docs/PERF.md 'Data plane')")
    ch.add_argument("cache_dir",
                    help="cache directory (DataConfig.cache_dir / "
                         "SHIFU_TPU_DATA_CACHE)")
    ch.add_argument("--prune", action="store_true",
                    help="remove tmp leftovers, legacy pre-v2 entries, and "
                         "entries whose source changed or vanished")
    ch.add_argument("--json", action="store_true",
                    help="machine-readable entry list instead of text")
    cv = sub.add_parser(
        "chaos-verify", help="audit a finished chaos drill: replay the "
                             "recorded plan against the run journal and "
                             "report injected-vs-recovered counts "
                             "(docs/ROBUSTNESS.md)")
    cv.add_argument("job_dir", help="job dir (or telemetry dir / journal "
                                    "path) of the finished run")
    cv.add_argument("--plan", default=None,
                    help="chaos plan to check against (inline JSON or "
                         "path); default: <job_dir>/chaos_plan.json")
    cv.add_argument("--json", action="store_true",
                    help="machine-readable report dict instead of text")
    at = sub.add_parser("attach", help="follow a detached job's console "
                                       "board until it ends (TailThread "
                                       "parity); exits with the job's code")
    at.add_argument("job_dir")
    at.add_argument("--tail", action="store_true",
                    help="start from the board's current end, not the top")
    kl = sub.add_parser("kill", help="terminate a detached job's whole "
                                     "process tree (SIGTERM drain, then "
                                     "SIGKILL)")
    kl.add_argument("job_dir")
    kl.add_argument("--force", action="store_true",
                    help="release a provisioned slice even when the marker "
                         "records a live foreground dispatcher")

    s = sub.add_parser("score", help="score rows with an exported artifact")
    s.add_argument("--model", required=True, help="artifact dir")
    s.add_argument("--input", required=True, help="rows file (pipe-delimited or .parquet)")
    s.add_argument("--output", default="-", help="output file (- = stdout)")
    s.add_argument("--native", action="store_true", help="use the C++ engine")
    s.add_argument("--engine", default="auto",
                   choices=["auto", "native", "numpy", "stablehlo", "jax",
                            "aot"],
                   help="scoring engine tier (auto = best available)")
    s.add_argument("--globalconfig", default=None,
                   help="Hadoop-style XML (shifu.security.* for secured HDFS)")

    sv = sub.add_parser(
        "serve", help="run the persistent scoring daemon on an exported "
                      "artifact: admission queue + adaptive micro-batching "
                      "under a latency budget, multi-model hot-swap, TCP "
                      "wire front-end (docs/SERVING.md)")
    sv.add_argument("model", help="artifact dir (the export output)")
    sv.add_argument("--engine", default=None,
                    choices=["auto", "native", "numpy", "stablehlo", "jax",
                            "aot"],
                    help="scoring engine tier (default: serving.engine / "
                         "auto)")
    sv.add_argument("--port", type=int, default=-1,
                    help="TCP port (0 = ephemeral, printed at startup; "
                         "default: shifu.serving.port / 8571)")
    sv.add_argument("--host", default=None,
                    help="bind host (default: shifu.serving.host / "
                         "127.0.0.1)")
    sv.add_argument("--budget-ms", type=float, default=0,
                    help="micro-batcher latency budget in ms: a lone "
                         "request is dispatched after at most this wait "
                         "(default: shifu.serving.latency-budget-ms / 2)")
    sv.add_argument("--max-batch", type=int, default=0,
                    help="largest coalesced batch (default: "
                         "shifu.serving.max-batch / 4096)")
    sv.add_argument("--workers", type=int, default=0,
                    help="scoring worker threads (default: "
                         "shifu.serving.workers / 1)")
    sv.add_argument("--globalconfig", default=None,
                    help="Hadoop-style XML carrying shifu.serving.* keys "
                         "(flags override)")
    sv.add_argument("--chaos-plan", default=None,
                    help="fault-injection plan for serving drills "
                         "(runtime.serve probe site, docs/ROBUSTNESS.md)")
    sv.add_argument("--allow-swap", action="store_true",
                    help="permit wire SWAP frames on a non-loopback bind "
                         "(hot-loads a filesystem path as the model — "
                         "loopback binds allow it by default; see the "
                         "trust model in docs/SERVING.md)")
    sv.add_argument("--heartbeat-s", type=float, default=0.0,
                    help="write a fleet membership lease into the metrics "
                         "dir every N seconds (0 = off; a FleetManager in "
                         "another process reads it — docs/SERVING.md "
                         "'Fleet')")
    sv.add_argument("--heartbeat-misses", type=int, default=3,
                    help="missed beats before the fleet marks this "
                         "daemon DOWN (rides in the lease; default 3)")

    fl = sub.add_parser(
        "fleet", help="run a fault-tolerant serving fleet: N scoring "
                      "daemons + hot standbys under heartbeat "
                      "supervision, a consistent-hash routing front-end "
                      "with hedged retries and overload shedding, "
                      "fleet-wide hot-swap, burn-rate scale loop "
                      "(runtime/fleet.py, docs/SERVING.md 'Fleet')")
    fl.add_argument("model", help="artifact dir (the export output)")
    fl.add_argument("--n-daemons", type=int, default=0,
                    help="fleet members (default: shifu.fleet.n-daemons "
                         "/ 2)")
    fl.add_argument("--standbys", type=int, default=-1,
                    help="hot-standby daemons pre-warmed on the current "
                         "artifact (default: shifu.fleet.standbys / 1)")
    fl.add_argument("--heartbeat-s", type=float, default=0,
                    help="membership lease cadence (default: "
                         "shifu.fleet.heartbeat-every-s / 0.5)")
    fl.add_argument("--heartbeat-misses", type=int, default=0,
                    help="missed beats before failover (default: "
                         "shifu.fleet.heartbeat-misses / 3)")
    fl.add_argument("--port", type=int, default=8571,
                    help="router front-end TCP port (0 = ephemeral, "
                         "printed at startup; default 8571)")
    fl.add_argument("--host", default="127.0.0.1",
                    help="router bind host (default 127.0.0.1)")
    fl.add_argument("--engine", default=None,
                    choices=["auto", "native", "numpy", "stablehlo",
                             "jax", "aot"],
                    help="member scoring engine tier")
    fl.add_argument("--budget-ms", type=float, default=0,
                    help="member micro-batcher latency budget "
                         "(default: shifu.serving.latency-budget-ms / 2)")
    fl.add_argument("--workers", type=int, default=0,
                    help="scoring worker threads per member")
    fl.add_argument("--scale-every-s", type=float, default=-1,
                    help="burn-rate scale-loop cadence, 0 disables "
                         "(default: shifu.fleet.scale-every-s / 0)")
    fl.add_argument("--root-dir", default=None,
                    help="fleet state dir for member leases + telemetry "
                         "(default: <model>/fleet)")
    fl.add_argument("--globalconfig", default=None,
                    help="Hadoop-style XML carrying shifu.fleet.* and "
                         "shifu.serving.* keys (flags override)")
    fl.add_argument("--hosts", default=None,
                    help="cross-host member placement (launcher/pod.py "
                         "grammar: local:N simulated hosts, h1,h2 or "
                         "@file over ssh; default: shifu.fleet.hosts / "
                         "single-host in-proc)")
    fl.add_argument("--member-mode", default=None,
                    choices=["auto", "inproc", "process"],
                    help="member spawn mode (default: "
                         "shifu.fleet.member-mode / auto — in-proc on "
                         "local transport, process children over ssh)")
    fl.add_argument("--chaos-plan", default=None,
                    help="fault-injection plan (fleet.heartbeat / "
                         "fleet.lease / fleet.sync / fleet.route / "
                         "runtime.serve sites, docs/ROBUSTNESS.md)")

    fv = sub.add_parser(
        "fleet-verify", help="audit a fleet run's journal: every "
                             "failover promoted a standby, swap "
                             "generations never regress, every swap "
                             "reached every live member exactly once "
                             "(the chaos-verify analog for the serving "
                             "fleet, docs/SERVING.md)")
    fv.add_argument("job_dir", help="fleet telemetry/job dir (or any "
                                    "dir holding its journal.jsonl)")
    fv.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")

    pdv = sub.add_parser(
        "pod-verify", help="audit a pod training run's per-rank journals: "
                           "every epoch closed by a complete agreeing "
                           "cohort (order + shard digests), per-host "
                           "ingest stayed balanced, and every injected "
                           "host kill was followed by recovery (the "
                           "fleet-verify analog for the training gang, "
                           "docs/DATA.md 'Multi-host data plane')")
    pdv.add_argument("job_dir", help="pod job/telemetry dir (per-rank "
                                     "journals are discovered one level "
                                     "below the root journal)")
    pdv.add_argument("--json", action="store_true",
                     help="machine-readable report on stdout")
    pdv.add_argument("--balance-limit", type=float, default=1.5,
                     help="max per-rank ingest bytes as a multiple of the "
                          "even share (default 1.5)")

    dd = sub.add_parser(
        "data-dryrun", help="pod data-plane dryrun rank: shard-local "
                            "ingest, per-epoch order/shard digests "
                            "journaled per rank, no device training — "
                            "the gang child the elastic recovery drill "
                            "and the bench scaling sweep dispatch under "
                            "`supervise_pod` (docs/DATA.md)")
    dd.add_argument("--data", required=True,
                    help="directory (or file) of delimited part files; "
                         "layout [target, f0..fN-1]")
    dd.add_argument("--out", required=True, help="job dir for per-rank "
                                                 "telemetry + progress")
    dd.add_argument("--features", type=int, default=8,
                    help="numeric feature count in the files (default 8)")
    dd.add_argument("--epochs", type=int, default=3)
    dd.add_argument("--batch-size", type=int, default=32)
    dd.add_argument("--delimiter", default="|")
    dd.add_argument("--seed", type=int, default=0,
                    help="shuffle seed pinning permutations and digests")
    dd.add_argument("--host-shard", default="auto",
                    choices=["auto", "static", "rotate"],
                    help="shard-assignment mode "
                         "(data/pipeline.host_shard_assignment)")
    dd.add_argument("--epoch-seconds", type=float, default=0.0,
                    help="simulated per-epoch wall (sleep) so kill/"
                         "liveness windows have something to land in")

    dr = sub.add_parser(
        "drift", help="model-quality / data-drift panel for a serving "
                      "daemon: per-feature PSI vs the frozen baseline "
                      "profile, score-distribution divergence, live AUC "
                      "decay from labeled feedback, and firing drift "
                      "alerts (journal tail only — no jax import; "
                      "docs/OBSERVABILITY.md 'Drift observatory')")
    dr.add_argument("job_dir",
                    help="serving job dir, telemetry dir, or "
                         "journal.jsonl path (train dirs render the "
                         "journaled baseline-profile summary)")
    dr.add_argument("--json", action="store_true",
                    help="machine-readable drift dict instead of text")
    dr.add_argument("--model", default=None,
                    help="restrict to one model_id (default: all)")
    dr.add_argument("--feature", default=None,
                    help="restrict the PSI table to one named feature")

    tl = sub.add_parser(
        "timeline", help="skew-corrected causal fleet timeline: merge "
                         "every member's journal into one ordered "
                         "event stream, stitch incidents (failover / "
                         "SLO / degraded-swap episodes) and show "
                         "sampled request traces end to end "
                         "(docs/OBSERVABILITY.md)")
    tl.add_argument("job_dir", help="fleet telemetry/job dir (member "
                                    "journals are discovered one "
                                    "level below)")
    tl.add_argument("--json", action="store_true",
                    help="machine-readable summary on stdout")
    tl.add_argument("--trace-id", default=None,
                    help="show one trace: its router hop spans and "
                         "per-member stage decompositions")
    tl.add_argument("--incident", action="store_true",
                    help="incident records only (root event, causal "
                         "chain, affected traces, recovery)")
    tl.add_argument("--no-skew-correct", action="store_true",
                    help="merge on raw per-host timestamps (skip the "
                         "heartbeat-derived clock-offset correction)")

    lt = sub.add_parser(
        "loadtest", help="open-loop (Poisson-arrival) load harness for "
                         "the scoring plane: reports scores/s and "
                         "p50/p99 latency (tools/loadtest.py, "
                         "docs/SERVING.md)")
    lt.add_argument("--model", default=None,
                    help="artifact dir — in-process mode: spin up a "
                         "daemon and drive it directly")
    lt.add_argument("--connect", default=None,
                    help="host:port of a running `shifu-tpu serve` "
                         "daemon — socket mode")
    lt.add_argument("--rate", type=float, default=50_000,
                    help="offered request rate per second (Poisson "
                         "arrivals; default 50000)")
    lt.add_argument("--duration", type=float, default=5.0,
                    help="seconds of offered load (default 5)")
    lt.add_argument("--engine", default="auto",
                    choices=["auto", "native", "numpy", "stablehlo", "jax",
                            "aot"],
                    help="engine tier for --model mode")
    lt.add_argument("--senders", type=int, default=2,
                    help="open-loop sender threads (the Poisson stream is "
                         "striped across them; default 2)")
    lt.add_argument("--budget-ms", type=float, default=0,
                    help="daemon latency budget for --model mode "
                         "(default: serving default)")
    lt.add_argument("--capacity", action="store_true",
                    help="ramp the offered rate to find the highest one "
                         "meeting the p99 target instead of a single run")
    lt.add_argument("--p99-target-ms", type=float, default=10.0,
                    help="p99 target for --capacity (default 10ms)")
    lt.add_argument("--trace-sample", type=int, default=0,
                    help="trace 1-in-N requests and report the trace "
                         "ids of the slowest sampled ones (p99 "
                         "exemplars; 0 = off, default)")
    lt.add_argument("--trace-exemplars", type=int, default=5,
                    help="how many slowest-trace exemplars to report "
                         "(default 5)")
    lt.add_argument("--drift-after", type=float, default=0.0,
                    help="drift drill: after this many seconds, draw "
                         "requests from a pool whose --drift-features "
                         "columns are shifted by --drift-shift "
                         "(0 = off, default; docs/OBSERVABILITY.md "
                         "'Drift observatory')")
    lt.add_argument("--drift-shift", type=float, default=2.0,
                    help="feature shift applied after --drift-after, in "
                         "raw feature units (default 2.0 — ~2 sigma on "
                         "the synthetic standard-normal pool)")
    lt.add_argument("--drift-features", default=None,
                    help="comma-separated feature indices to shift "
                         "(default: 0,1)")
    lt.add_argument("--feedback", action="store_true",
                    help="ship synthetic labeled feedback after the run "
                         "(calibrated labels pre-drift, coin-flips "
                         "post-drift) so the daemon's live AUC decays")
    lt.add_argument("--json", action="store_true",
                    help="machine-readable report instead of text")

    x = sub.add_parser(
        "export", help="re-export the scoring artifact from a checkpoint "
                       "(no retraining; crash-after-train recovery)")
    x.add_argument("--modelconfig", required=True, help="Shifu ModelConfig.json")
    x.add_argument("--columnconfig", required=True, help="Shifu ColumnConfig.json")
    x.add_argument("--checkpoint-dir", required=True,
                   help="orbax checkpoint dir (the job's tmp_model)")
    x.add_argument("--output", required=True, help="artifact output dir")
    x.add_argument("--globalconfig", default=None,
                   help="Hadoop-style XML (same layering as train)")
    x.add_argument("--aot-pack", action="store_true",
                   help="also compile + serialize the serving bucket-"
                        "ladder executables into aot/ (export/aot.py; "
                        "same opt-in as the shifu.serving.aot-pack key) "
                        "— fleet members then cold-start without XLA "
                        "compiles")

    e = sub.add_parser(
        "eval", help="score labeled rows and report AUC/error (the Shifu "
                     "eval step against this backend's artifacts)")
    e.add_argument("--model", required=True, help="artifact dir")
    e.add_argument("--columnconfig", required=True,
                   help="Shifu ColumnConfig.json (locates target/weight cols)")
    e.add_argument("--data", nargs="+", required=True,
                   help="labeled normalized data files/dirs")
    e.add_argument("--modelconfig", default=None,
                   help="optional ModelConfig.json (target/weight col names)")
    e.add_argument("--scores-output", default=None,
                   help="also write per-row scores to this file")
    e.add_argument("--native", action="store_true", help="use the C++ engine")
    e.add_argument("--engine", default="auto",
                   choices=["auto", "native", "numpy", "stablehlo", "jax",
                            "aot"],
                   help="scoring engine tier (auto = best available)")
    e.add_argument("--globalconfig", default=None,
                   help="Hadoop-style XML (shifu.security.* for secured HDFS)")
    return p


def _add_provision_flags(p) -> None:
    p.add_argument("--provision-name", default="",
                   help="queued-resource / node id (shifu.provision.name)")
    p.add_argument("--accelerator-type", default="",
                   help="e.g. v5litepod-16 (shifu.provision.accelerator-type)")
    p.add_argument("--zone", default="",
                   help="e.g. us-west4-a (shifu.provision.zone)")
    p.add_argument("--project", default="",
                   help="GCP project (shifu.provision.project; default = "
                        "gcloud's configured project)")
    p.add_argument("--runtime-version", default="",
                   help="TPU VM runtime (shifu.provision.runtime-version)")
    p.add_argument("--spot", action="store_true",
                   help="request spot/preemptible capacity "
                        "(shifu.provision.spot)")


def _provision_spec(args):
    """ProvisionSpec from --globalconfig shifu.provision.* keys with CLI
    flags as the top override layer."""
    from ..utils import xmlconfig
    from .provision import spec_from_xml

    conf: dict = {}
    if getattr(args, "globalconfig", None):
        conf = xmlconfig.parse_configuration_xml(args.globalconfig)
    return spec_from_xml(
        conf,
        name=getattr(args, "provision_name", ""),
        accelerator_type=getattr(args, "accelerator_type", ""),
        zone=getattr(args, "zone", ""),
        project=getattr(args, "project", ""),
        runtime_version=getattr(args, "runtime_version", ""),
        spot=getattr(args, "spot", False),
    )


def run_provision(args) -> int:
    from . import provision as prov

    try:
        spec = _provision_spec(args)
        if args.action == "create":
            prov.create(spec)
            if args.wait:
                prov.await_ready(spec)
            return EXIT_OK
        if args.action == "status":
            spec.validate()
            print(prov.state(spec))
            return EXIT_OK
        if args.action == "hosts":
            spec.validate()
            print(",".join(prov.worker_hosts(spec)))
            return EXIT_OK
        if args.action == "delete":
            spec.validate()
            prov.delete(spec)
            return EXIT_OK
    except prov.ProvisionError as e:
        print(f"provision: {e}", file=sys.stderr, flush=True)
        return EXIT_FAIL
    return EXIT_FAIL


def _kerberos_from_xml(globalconfig) -> int:
    """Acquire a Kerberos ticket for score/eval when --globalconfig carries
    shifu.security.kerberos.* keys (same fail-fast as run_train); returns an
    exit code (EXIT_OK to proceed)."""
    if not globalconfig:
        return EXIT_OK
    from ..utils import xmlconfig
    from .security import KerberosError, ensure_kerberos_ticket

    conf = xmlconfig.parse_configuration_xml(globalconfig)
    try:
        ensure_kerberos_ticket(conf.get(xmlconfig.KEY_KERBEROS_PRINCIPAL, ""),
                               conf.get(xmlconfig.KEY_KERBEROS_KEYTAB, ""))
    except KerberosError as e:
        print(f"kerberos auth failed: {e}", file=sys.stderr, flush=True)
        return EXIT_FAIL
    return EXIT_OK


def _assemble_job(args, write_files: bool = True) -> "JobConfig":
    import dataclasses

    from ..config import job_config_from_shifu
    from ..config.schema import CheckpointConfig
    from ..data import fsio
    from ..utils import xmlconfig

    job = job_config_from_shifu(args.modelconfig, args.columnconfig,
                                data_paths=tuple(args.data))

    merged_xml: dict[str, str] = {}
    if args.globalconfig:
        merged_xml = xmlconfig.parse_configuration_xml(args.globalconfig)
        job = xmlconfig.apply_to_job(job, merged_xml)

    out_dir = _resolve_out_dir(args)
    remote_out = fsio.is_remote(out_dir)
    if not remote_out:
        os.makedirs(out_dir, exist_ok=True)

    # overrides, highest precedence (the reference's programmatic layer)
    train = job.train
    if args.epochs:
        train = dataclasses.replace(train, epochs=args.epochs)
    data = job.data
    if args.batch_size:
        data = dataclasses.replace(data, batch_size=args.batch_size)
    if getattr(args, "cache_dir", None):
        data = dataclasses.replace(data, cache_dir=args.cache_dir)
    runtime = job.runtime
    if args.timeout:
        runtime = dataclasses.replace(runtime, timeout_seconds=args.timeout)
    if not runtime.checkpoint.directory:
        runtime = dataclasses.replace(
            runtime, checkpoint=dataclasses.replace(
                runtime.checkpoint,
                directory=fsio.join(out_dir, "tmp_model")))
    if not runtime.final_model_path:
        runtime = dataclasses.replace(
            runtime, final_model_path=fsio.join(out_dir, "final_model"))
    job = job.replace(train=train, data=data, runtime=runtime)

    if write_files:  # chief-only under multi-process (shared job dir)
        # persist the raw Shifu inputs beside the derived configs, like the
        # reference client's per-app upload of ModelConfig/ColumnConfig
        # (TensorflowClient.java:356-382) — the job dir alone reproduces the
        # run.  A remote (gs:// hdfs://) job dir writes through fsio, the
        # same contract the reference had with its per-app HDFS dir.
        for src in (args.modelconfig, args.columnconfig):
            dst = fsio.join(out_dir, os.path.basename(src))
            if remote_out:
                with open(src, "rb") as f:
                    fsio.write_bytes(dst, f.read())
            else:
                import shutil
                # realpath: a symlinked cwd can alias src and dst
                if os.path.realpath(src) != os.path.realpath(dst):
                    shutil.copyfile(src, dst)

        # persist the merged view (global-final.xml parity + typed JSON)
        final_conf = {**merged_xml,
                      "shifu.application.epochs": str(job.train.epochs),
                      "shifu.application.final-model-path":
                          job.runtime.final_model_path,
                      "shifu.application.tmp-model-path":
                          job.runtime.checkpoint.directory}
        if remote_out:
            fsio.write_bytes(fsio.join(out_dir, "global-final.xml"),
                             xmlconfig.configuration_xml_bytes(final_conf))
            fsio.write_bytes(fsio.join(out_dir, "job-config.json"),
                             job.to_json().encode())
        else:
            xmlconfig.write_configuration_xml(
                final_conf, os.path.join(out_dir, "global-final.xml"))
            with open(os.path.join(out_dir, "job-config.json"), "w") as f:
                f.write(job.to_json())
    return job, out_dir


def _resolve_out_dir(args) -> str:
    """The job output dir, resolved once (children/attempts must share it)."""
    return args.output or os.path.join(
        os.getcwd(), f"shifu_tpu_job_{time.strftime('%Y%m%d_%H%M%S')}")


def _child_train_args(args, out_dir: str,
                      num_processes: int = 0) -> list[str]:
    """Rebuild a `train` child argv from parsed args, with --output pinned
    (shared checkpoints/board) and supervisor/multi-process flags stripped
    unless re-requested via num_processes."""
    child = ["train",
             "--modelconfig", args.modelconfig,
             "--columnconfig", args.columnconfig,
             "--output", out_dir]
    if args.data:
        child += ["--data", *args.data]
    if args.globalconfig:
        child += ["--globalconfig", args.globalconfig]
    if num_processes > 1:
        child += ["--num-processes", str(num_processes)]
    for flag, val in (("--devices", args.devices), ("--epochs", args.epochs),
                      ("--batch-size", args.batch_size),
                      ("--timeout", args.timeout),
                      ("--cache-dir", getattr(args, "cache_dir", None))):
        if val:
            child += [flag, str(val)]
    return child


def _spawn_processes(args, out_dir: str) -> int:
    """Local multi-process mode (`--num-processes N`): a simulated pod on
    this machine — the single-machine spelling of `--hosts local:N`,
    delegating to the pod launcher for the spawn/stream/teardown mechanics
    (one gang attempt; restarts come from the outer `--supervise` wrapper,
    which re-enters here with a fresh gang)."""
    from . import pod as pod_lib

    if args.devices:
        # a device *prefix* of the global list would strand non-chief
        # processes outside the mesh; device counts are per-process here
        print("--devices cannot combine with --num-processes "
              "(set SHIFU_TPU_CPU_DEVICES per process instead)",
              file=sys.stderr, flush=True)
        return EXIT_FAIL

    os.makedirs(out_dir, exist_ok=True)
    spec = pod_lib.PodSpec(hosts=("local",) * args.num_processes,
                           transport="local")
    rc, _failed = pod_lib.launch_gang(spec, _child_train_args(args, out_dir),
                                      out_dir, attempt=1)
    return rc


def _activate_chaos(args) -> int:
    """Export `--chaos-plan` into the environment (children inherit it on
    every restart), validate it NOW (a typo'd plan must fail the launch,
    not silently never inject), pin the job-scoped trigger state file into
    the job dir, and persist the resolved plan beside the job so
    `chaos-verify` can replay it.  Returns nonzero on a bad plan."""
    from .. import chaos

    plan_arg = getattr(args, "chaos_plan", None)
    try:
        if plan_arg:
            # export the resolved plan CONTENT, never a path: ssh-dispatched
            # pod ranks inherit the env on other machines where a local
            # plan file does not exist (and the detach daemon may run from
            # another cwd) — inline JSON works everywhere
            base = chaos.load_plan(plan_arg.strip())
            os.environ[chaos.ENV_CHAOS_PLAN] = base.to_json(indent=None)
        plan = chaos.reload_from_env()
    except chaos.ChaosPlanError as e:
        print(f"chaos plan: {e}", file=sys.stderr, flush=True)
        return EXIT_FAIL
    if plan is None or not plan.faults:
        return EXIT_OK
    if chaos.ENV_CHAOS_STATE not in os.environ:
        out_dir = _resolve_out_dir(args)
        args.output = out_dir  # pin: a re-resolve could timestamp anew
        from ..data import fsio
        if not fsio.is_remote(out_dir):
            os.makedirs(out_dir, exist_ok=True)
            os.environ[chaos.ENV_CHAOS_STATE] = os.path.join(
                out_dir, "chaos_state.json")
            try:  # the audit trail chaos-verify replays
                with open(os.path.join(out_dir, "chaos_plan.json"),
                          "w") as f:
                    f.write(plan.to_json())
            except OSError:
                pass
        else:
            try:  # remote job dir: the audit trail still persists via fsio
                fsio.write_bytes(fsio.join(out_dir, "chaos_plan.json"),
                                 plan.to_json().encode())
            except Exception:
                pass
            if any(f.scope == "job" for f in plan.faults):
                # no local state file to pin -> job-scoped counters degrade
                # to per-process and would re-fire each restart; say so
                # LOUDLY instead of silently changing the drill's semantics
                print("chaos: job dir is remote and SHIFU_TPU_CHAOS_STATE "
                      "is unset — scope=\"job\" triggers degrade to "
                      "per-process counters (set SHIFU_TPU_CHAOS_STATE to "
                      "a local path to keep job-wide counting)",
                      file=sys.stderr, flush=True)
    return EXIT_OK


def run_train(args) -> int:
    # Order matters: the supervisor parent must NOT join the distributed
    # rendezvous (its child re-registers the same process id), and a
    # supervised multi-process job restarts as a whole gang — supervisor
    # wraps the spawner, spawner wraps the worker processes.

    # chaos plane first: the plan env must be exported before ANY child
    # (detach daemon, supervisor attempt, pod rank) is spawned, and a
    # malformed plan must fail here, at submit time
    rc_chaos = _activate_chaos(args)
    if rc_chaos != EXIT_OK:
        return rc_chaos

    # --detach: re-launch this dispatcher as a session-leader daemon and
    # return (YARN parity: the job outlives the submitting client,
    # TensorflowClient.java:625-658; status/attach/kill drive it after)
    from . import detach as detach_lib
    if getattr(args, "detach", False) \
            and detach_lib.ENV_DETACHED not in os.environ:
        out_dir = _resolve_out_dir(args)
        args.output = out_dir
        child = _child_train_args(
            args, out_dir, num_processes=getattr(args, "num_processes", 0))
        # preserve the orchestration flags the slim child argv strips
        if getattr(args, "hosts", None):
            child += ["--hosts", args.hosts]
        if getattr(args, "provision", False):
            child += ["--provision"]
            for flag, attr in (("--provision-name", "provision_name"),
                               ("--accelerator-type", "accelerator_type"),
                               ("--zone", "zone"), ("--project", "project"),
                               ("--runtime-version", "runtime_version")):
                if getattr(args, attr, ""):
                    child += [flag, getattr(args, attr)]
            if getattr(args, "spot", False):
                child += ["--spot"]
            if getattr(args, "keep_slice", False):
                child += ["--keep-slice"]
        elif getattr(args, "supervise", False) or not getattr(args, "hosts", None):
            child += ["--supervise"]  # a detached job should self-heal
        if getattr(args, "max_restarts", -1) >= 0:
            child += ["--max-restarts", str(args.max_restarts)]
        if getattr(args, "coordinator_port", 0):
            child += ["--coordinator-port", str(args.coordinator_port)]
        return detach_lib.submit(child, out_dir)

    # pod-scale launch (successor of the YARN submit/monitor path): the
    # dispatcher routes here only in the PARENT — dispatched children carry
    # the SHIFU_TPU_PROCESS_ID env and run the plain train path below.
    # Gang supervision (restart budget + liveness) is built into the pod
    # path, so --supervise is implied.
    from ..parallel.distributed import ENV_PROCESS_ID
    from . import pod as pod_lib
    pod_hosts = getattr(args, "hosts", None) or pod_lib.detect_hosts_env()

    # --provision: acquire a slice, dispatch the pod onto its workers,
    # release on every exit path (successor of createApplication ->
    # submitApplication -> monitorApplication, TensorflowClient.java:339-426)
    if getattr(args, "provision", False) and ENV_PROCESS_ID not in os.environ:
        from . import provision as prov
        if pod_hosts:
            print("--provision and --hosts are exclusive (provisioning "
                  "derives the hosts from the new slice)",
                  file=sys.stderr, flush=True)
            return EXIT_FAIL
        try:
            spec = _provision_spec(args)
            spec.validate()
        except prov.ProvisionError as e:
            print(f"provision: {e}", file=sys.stderr, flush=True)
            return EXIT_FAIL

        def _dispatch(hosts: list) -> int:
            args.hosts = ",".join(hosts)
            args.provision = False  # re-entry takes the pod branch below
            return run_train(args)

        # a scheduler SIGTERM mid-lifecycle would terminate Python WITHOUT
        # running finally blocks (default disposition) — the release in
        # provision_and_run's finally must still run, so SIGTERM raises
        # SystemExit for the duration (the marker covers SIGKILL; this
        # covers the catchable case without waiting for a manual `kill`)
        import signal as signal_lib

        def _term_to_exit(signum, frame):
            # first SIGTERM starts the unwind; LATER ones are ignored until
            # the finally restores the disposition — schedulers often repeat
            # SIGTERM on a cadence, and a second signal landing inside the
            # release's own gcloud call would abort the delete and leak the
            # slice the unwind exists to release
            signal_lib.signal(signal_lib.SIGTERM, signal_lib.SIG_IGN)
            raise SystemExit(128 + signum)

        old_term, installed = None, False
        try:
            old_term = signal_lib.signal(signal_lib.SIGTERM, _term_to_exit)
            installed = True  # old_term may be None (C-installed handler)
        except ValueError:
            pass  # non-main thread: no handler; the marker still covers it
        try:
            # marker in the job dir: an UNCLEAN dispatcher death between
            # create and release must leave a trail `kill <job_dir>` (or
            # an operator) can release from — see provision.write_marker
            args.output = _resolve_out_dir(args)
            return prov.provision_and_run(
                spec, _dispatch, keep=getattr(args, "keep_slice", False),
                marker_dir=args.output)
        except prov.ProvisionError as e:
            print(f"provision: {e}", file=sys.stderr, flush=True)
            return EXIT_FAIL
        finally:
            if installed:
                signal_lib.signal(signal_lib.SIGTERM,
                                  old_term if old_term is not None
                                  else signal_lib.SIG_DFL)

    if pod_hosts and ENV_PROCESS_ID not in os.environ:
        try:
            spec = pod_lib.parse_hosts(
                pod_hosts, getattr(args, "coordinator_port", 0))
        except (ValueError, OSError) as e:
            print(f"--hosts: {e}", file=sys.stderr, flush=True)
            return EXIT_FAIL
        if getattr(args, "num_processes", 0) > 1:
            print("--hosts and --num-processes are alternative spellings of "
                  "a process gang; use one", file=sys.stderr, flush=True)
            return EXIT_FAIL
        from ..data import fsio as fsio_mod
        out_dir = _resolve_out_dir(args)
        args.output = out_dir  # pin: a second resolve could timestamp anew,
        if not fsio_mod.is_remote(out_dir):  # desyncing the checkpoint probe
            os.makedirs(out_dir, exist_ok=True)
        sup_job = _assemble_job(args, write_files=False)[0]
        max_restarts = (args.max_restarts if args.max_restarts >= 0
                        else sup_job.runtime.max_restarts)
        return pod_lib.supervise_pod(
            spec, _child_train_args(args, out_dir), out_dir,
            max_restarts=max_restarts,
            liveness_seconds=sup_job.runtime.liveness_seconds,
            checkpoint_dir=sup_job.runtime.checkpoint.directory,
            timeout_seconds=sup_job.runtime.timeout_seconds,
            min_hosts=sup_job.runtime.min_hosts)

    if args.supervise:
        from ..data import fsio as fsio_mod
        from .supervisor import supervise
        out_dir = _resolve_out_dir(args)
        args.output = out_dir  # pin: a second resolve could timestamp anew,
        if not fsio_mod.is_remote(out_dir):  # desyncing the checkpoint probe
            os.makedirs(out_dir, exist_ok=True)
        sup_job = _assemble_job(args, write_files=False)[0]
        max_restarts = (args.max_restarts if args.max_restarts >= 0
                        else sup_job.runtime.max_restarts)
        child_args = _child_train_args(
            args, out_dir, num_processes=getattr(args, "num_processes", 0))
        return supervise(child_args, max_restarts=max_restarts,
                         board_path=fsio_mod.join(out_dir, "console.board"),
                         liveness_seconds=sup_job.runtime.liveness_seconds,
                         checkpoint_dir=sup_job.runtime.checkpoint.directory,
                         timeout_seconds=sup_job.runtime.timeout_seconds)

    if getattr(args, "num_processes", 0) > 1:
        return _spawn_processes(args, _resolve_out_dir(args))

    # chaos site "launcher.start": process startup, BEFORE the rendezvous —
    # a fault here models a host that never joins (the dead rank's peers
    # are torn down by the gang dispatcher; a permanently-down rank drives
    # the pod supervisor's elastic reshape).  The legacy
    # SHIFU_TPU_FAULT_HOST_DOWN env hook synthesizes exactly this fault
    # (chaos/plan.py plan_from_legacy_env).
    from .. import chaos as chaos_lib
    try:
        chaos_lib.maybe_fail("launcher.start")
    except chaos_lib.ChaosError as e:
        print(f"chaos: {e}", file=sys.stderr, flush=True)
        return EXIT_FAIL

    # multi-host rendezvous (no-op without the env contract / pod runtime);
    # must run before any jax device use so every process joins the global
    # mesh — the successor of the ZooKeeper ip:port registration dance
    # (TensorflowSession.java:551-594)
    from ..parallel import distributed
    distributed.initialize()
    chief = distributed.is_chief()

    job, out_dir = _assemble_job(args, write_files=chief)

    # secured HDFS: acquire the Kerberos ticket before any data access
    # (successor of the reference client's delegation-token fetch,
    # TensorflowClient.java:481-502); no-op unless a principal is configured
    from .security import KerberosError, ensure_kerberos_ticket
    try:
        # supervisor restarts re-enter run_train in fresh child processes,
        # re-running kinit; healthy long runs renew periodically from the
        # epoch callback below
        ensure_kerberos_ticket(job.runtime.kerberos_principal,
                               job.runtime.kerberos_keytab)
    except KerberosError as e:
        print(f"kerberos auth failed: {e}", file=sys.stderr, flush=True)
        return EXIT_FAIL

    import jax

    if jax.process_count() > 1 and args.devices:
        print("--devices is not supported under multi-host (device counts "
              "are per-process)", file=sys.stderr, flush=True)
        return EXIT_FAIL

    from ..parallel import data_parallel_mesh
    from ..train import train
    from .console import ConsoleBoard

    from .. import obs
    from ..data import fsio as fsio_lib
    t_run = time.monotonic()
    if chief:
        # telemetry sinks: SHIFU_TPU_METRICS_DIR wins, else the job dir —
        # `shifu-tpu metrics <job_dir>` then finds journal + scrape file
        # under <job_dir>/telemetry without any env setup
        metrics_dir = obs.resolve_metrics_dir() \
            or fsio_lib.join(out_dir, "telemetry")
        try:
            obs.configure(metrics_dir)
        except Exception:
            pass  # telemetry must never block the job
    obs.counter("launcher_runs_total", "train runs started").inc()
    if chief:
        board = ConsoleBoard(fsio_lib.join(out_dir, "console.board"))
    else:  # non-chief processes train silently (reference: only the AM's
        class board:  # aggregated view reached the console board)
            def __call__(self, _s): pass
            def close(self): pass
        board = board()
    n_devices = len(jax.devices())
    if args.devices:
        n_devices = min(n_devices, args.devices)
    mesh_cfg = job.runtime.mesh
    need = mesh_cfg.num_devices
    if need > 1:
        # explicit topology from config (shifu.mesh.* — dp size, tp,
        # sequence and/or pipeline parallelism); all-axes-1 means "unset"
        # and defaults to data parallelism over every visible device
        from ..parallel import make_mesh
        if need > n_devices:
            board(f"mesh {mesh_cfg} needs {need} devices, have {n_devices}")
            board.close()
            return EXIT_FAIL
        mesh = make_mesh(mesh_cfg, jax.devices()[:need])
        devices_in_use = need
    else:
        mesh = data_parallel_mesh(n_devices) if n_devices > 1 else None
        devices_in_use = n_devices
    if job.model.attention_impl in ("ring", "ulysses") and (
            mesh is None or mesh.shape.get("seq", 1) <= 1):
        board(f"warning: attention_impl={job.model.attention_impl!r} needs a "
              "mesh with a seq axis > 1 (runtime.mesh.seq); falling back to "
              "local attention")
    if job.model.attention_impl == "flash" and (
            mesh is not None and mesh.shape.get("seq", 1) > 1):
        board("warning: attention_impl='flash' is a per-device kernel and "
              "ignores the mesh seq axis; use 'ring' or 'ulysses' for "
              "sequence parallelism")
    if job.model.pipeline_stages > 1 and (
            mesh is None or mesh.shape.get("pipe", 1) <= 1):
        board(f"warning: pipeline_stages={job.model.pipeline_stages} needs a "
              "mesh with a pipe axis > 1 (shifu.mesh.pipe); running the "
              "stacked trunk on one stage")
    if job.model.pipeline_stages <= 1 and (
            mesh is not None and mesh.shape.get("pipe", 1) > 1):
        board(f"warning: mesh pipe axis = {mesh.shape['pipe']} but the model "
              "is not pipelined (PipelineStages in ModelConfig params); the "
              "pipe group replicates work — fold those devices into "
              "shifu.mesh.data instead")

    board(f"shifu_tpu train: {job.runtime.app_name} "
          f"devices={devices_in_use}/{n_devices} "
          f"mesh={dict(mesh.shape) if mesh is not None else None} "
          f"model={job.model.model_type} epochs={job.train.epochs} "
          f"batch={job.data.batch_size}")
    obs.gauge("launcher_devices_in_use",
              "devices this run trains on").set(devices_in_use)
    obs.event("run_start", command="train", app_name=job.runtime.app_name,
              devices=devices_in_use,
              mesh=dict(mesh.shape) if mesh is not None else None,
              model=job.model.model_type, epochs=job.train.epochs,
              batch_size=job.data.batch_size,
              processes=jax.process_count())

    def _finish(rc: int) -> int:
        # terminal journal record + scrape-file write on EVERY exit path,
        # so `shifu-tpu metrics` reads a complete story for failed and
        # timed-out runs too
        obs.event("run_end", exit=rc,
                  wall_s=round(time.monotonic() - t_run, 2))
        obs.flush()
        return rc

    from .supervisor import JobDeadline
    deadline = JobDeadline(job.runtime.timeout_seconds)

    # ticket renewal for healthy long runs: re-kinit from the per-epoch
    # callback once half a typical 10h ticket lifetime has passed, so a job
    # streaming hdfs:// data never outlives its credentials mid-read
    kinit_renew_s = 4 * 3600
    last_kinit = time.monotonic()

    def check_timeout(_m):
        nonlocal last_kinit
        if deadline.expired():
            board(f"job timeout ({job.runtime.timeout_seconds}s) exceeded — aborting")
            raise TimeoutError("job timeout")
        if (job.runtime.kerberos_principal
                and time.monotonic() - last_kinit > kinit_renew_s):
            ensure_kerberos_ticket(job.runtime.kerberos_principal,
                                   job.runtime.kerberos_keytab)
            last_kinit = time.monotonic()
        _maybe_inject_fault(_m, board)

    try:
        result = train(job, mesh=mesh, console=board, epoch_callback=check_timeout)
    except TimeoutError:
        board.close()
        return _finish(EXIT_TIMEOUT)
    except Exception as e:  # noqa: BLE001 - job boundary
        board(f"training failed: {type(e).__name__}: {e}")
        obs.event("run_error", error=f"{type(e).__name__}: {e}"[:500])
        board.close()
        return _finish(EXIT_FAIL)

    params = result.state.params
    if jax.process_count() > 1 and mesh is not None:
        # collective: EVERY process participates in replicating (all-gather)
        # any model-sharded params so the chief holds full values to export
        from jax.sharding import NamedSharding, PartitionSpec
        replicate = jax.jit(
            lambda t: t, out_shardings=NamedSharding(mesh, PartitionSpec()))
        params = jax.device_get(replicate(params))
    if chief:
        # make_forward_fn inside: meshless rebuild for single-host export
        # (the training loop's frozen reference profile rides along as
        # baseline_profile.json — the drift observatory's anchor)
        aot_pack, aot_buckets = _export_aot_opts(args)
        _export_and_pack(params, job, job.runtime.final_model_path, board,
                         baseline_profile=result.baseline_profile,
                         aot_pack=aot_pack, aot_buckets=aot_buckets)
        _write_metrics_jsonl(result, fsio_lib.join(out_dir, "metrics.jsonl"))
        if result.history:
            last = result.history[-1]
            board(f"final: valid_error={last.valid_error:.6f} "
                  f"valid_auc={last.valid_auc:.4f}")
    if jax.process_count() > 1:
        from ..parallel import distributed as dist
        dist.barrier("export_done")
    board.close()
    return _finish(EXIT_OK)


def _write_metrics_jsonl(result, path: str) -> None:
    """Structured per-epoch metrics next to the human console board — the
    machine-readable successor of the reference's Java-serialized
    TrainingIntermediateResult znodes (core/TrainingIntermediateResult.java:
    97-102; SURVEY.md section 5.5 flagged Java serialization as a quirk)."""
    import dataclasses
    import json
    import math

    def _clean(v):
        # NaN/Inf are not valid JSON; strict JSONL consumers need null
        if isinstance(v, float) and not math.isfinite(v):
            return None
        return v

    lines = []
    for m in result.history:
        rec = {k: _clean(v) for k, v in dataclasses.asdict(m).items()}
        lines.append(json.dumps(rec, allow_nan=False))
    payload = ("\n".join(lines) + "\n") if lines else ""
    try:
        from ..data import fsio
        if fsio.is_remote(path):
            fsio.write_bytes(path, payload.encode())
        else:
            with open(path, "w") as f:
                f.write(payload)
    except Exception:
        pass  # metrics sink is best-effort; the board already has the lines


def _maybe_inject_fault(metrics, board) -> None:
    """Chaos site "train.epoch": the post-epoch boundary (after the epoch's
    conditional checkpoint save) — the successor of the reference's
    commented-out PS-killer (yarn/util/CommonUtils.java:265-274).  The
    legacy SHIFU_TPU_FAULT_EPOCH / _FAULT_EVERY_EPOCH / _FAULT_PROCESS /
    SHIFU_TPU_HANG_EPOCH env hooks still work: chaos/plan.py synthesizes
    equivalent plan faults from them (crash-after-epoch-k, die-after-every-
    epoch-below-n, rank-limited injection, hang-for-liveness-detection)."""
    from .. import chaos

    def echo(msg: str) -> None:
        # print as well: a non-chief rank's board is silent, but its stdout
        # is captured into the per-host log by the pod launcher
        print(msg, flush=True)
        board(msg)

    chaos.maybe_fail("train.epoch", echo=echo, epoch=metrics.epoch)


def _load_scorer(model_dir: str, native: bool, engine: str = "auto"):
    """Pick a scoring engine: `--native` or --engine native = the C++
    op-list engine; numpy / stablehlo / jax select an explicit tier
    (debugging, cross-engine verification); auto = best available
    (export.load_scorer's order).  Raises ValueError with the fix spelled
    out on contradictory flags or a tier the artifact cannot serve.
    The tier ladder itself is runtime/serve.load_engine — one resolver
    for score/eval and the serving daemon's model loads."""
    if native and engine not in ("auto", "native"):
        raise ValueError(
            f"--native contradicts --engine {engine}; drop one of them")
    from ..runtime.serve import load_engine
    return load_engine(model_dir, "native" if native else engine)


def _project_features(rows, model_dir: str, scorer):
    """Select the artifact's feature columns from raw normalized rows.

    The artifact's own `topology.json` selected_indices are the authority
    (the ColumnConfig on disk may have drifted since training — e.g. variable
    selection re-run); full-width inputs pass through, and NaNs impute to 0
    the way training did (data/reader.py project_columns)."""
    import numpy as np

    n_feat = getattr(scorer, "num_features", None) or rows.shape[1]
    if rows.shape[1] != n_feat:
        sel = None
        try:
            with open(os.path.join(model_dir, "topology.json")) as f:
                sel = json.load(f).get("selected_indices")
        except (OSError, ValueError):
            pass
        if sel and rows.shape[1] > max(sel):
            rows = rows[:, sel]
        else:
            rows = rows[:, :n_feat]
    return np.nan_to_num(rows, nan=0.0)


def run_metrics(args) -> int:
    """`shifu-tpu metrics <dir>`: render the run journal + registry scrape
    for a running or finished job — the operator view of the unified
    telemetry layer (obs/), succeeding the reference client's poll of the
    AM's aggregated metrics."""
    from .. import obs
    from ..obs import render as obs_render

    if getattr(args, "follow", False):
        jpath = obs_render.find_journal(args.job_dir)
        if jpath is None:
            print(f"no telemetry journal found under {args.job_dir}",
                  file=sys.stderr, flush=True)
            return EXIT_FAIL
        try:
            for rec in obs.tail_journal(jpath):
                print(json.dumps(rec), flush=True)
        except KeyboardInterrupt:
            pass
        return EXIT_OK
    try:
        summary = obs_render.summarize(args.job_dir)
    except Exception as e:
        print(f"metrics: {e}", file=sys.stderr, flush=True)
        return EXIT_FAIL
    if summary is None:
        print(f"no telemetry journal found under {args.job_dir} (expected "
              f"<job_dir>/telemetry/journal.jsonl — run with "
              f"SHIFU_TPU_METRICS_DIR or a CLI train job)",
              file=sys.stderr, flush=True)
        return EXIT_FAIL
    print(json.dumps(summary) if args.json
          else obs_render.render_text(summary))
    return EXIT_OK


def run_profile(args) -> int:
    """`shifu-tpu profile <dir>`: the goodput / XLA-cost view of a run —
    where the wall time and FLOPs went, epoch by epoch, straight from the
    `goodput` / `xla_compile` journal events (obs/goodput.py,
    obs/introspect.py)."""
    from ..obs import render as obs_render

    try:
        summary = obs_render.profile_summary(args.job_dir)
    except Exception as e:
        print(f"profile: {e}", file=sys.stderr, flush=True)
        return EXIT_FAIL
    if summary is None:
        print(f"no telemetry journal found under {args.job_dir} (expected "
              f"<job_dir>/telemetry/journal.jsonl — run with "
              f"SHIFU_TPU_METRICS_DIR or a CLI train job)",
              file=sys.stderr, flush=True)
        return EXIT_FAIL
    print(json.dumps(summary) if args.json
          else obs_render.render_profile_text(summary))
    return EXIT_OK


def run_trace(args) -> int:
    """`shifu-tpu trace <dir>`: the device flight-recorder view of a run —
    which kernels own the device time (and whether each is compute- or
    HBM-bound), what the anomaly detector caught, and where HBM peaked —
    straight from the `device_profile` / `anomaly` / `hbm_watermark`
    journal events (obs/devprof.py)."""
    from ..obs import render as obs_render

    try:
        summary = obs_render.trace_summary(args.job_dir)
    except Exception as e:
        print(f"trace: {e}", file=sys.stderr, flush=True)
        return EXIT_FAIL
    if summary is None:
        print(f"no telemetry journal found under {args.job_dir} (expected "
              f"<job_dir>/telemetry/journal.jsonl — run with "
              f"SHIFU_TPU_METRICS_DIR or a CLI train job)",
              file=sys.stderr, flush=True)
        return EXIT_FAIL
    print(json.dumps(summary) if args.json
          else obs_render.render_trace_text(summary))
    return EXIT_OK


def run_top(args) -> int:
    """`shifu-tpu top <dir> [...]`: the live operator view of the serving
    and device planes joined — rate / p50 / p99 / queue depth, the
    per-request lifecycle stage breakdown (where a p99 excursion's time
    actually goes), and active SLO burn-rate alerts; a train job dir
    renders epoch progress + goodput instead.  Journal/scrape-file reads
    only — safe to point at a LIVE daemon from any machine that can read
    the dir, and never imports jax."""
    from ..obs import aggregate as obs_aggregate
    from ..obs import render as obs_render

    stale_after = getattr(args, "stale_after", None)

    def frame() -> tuple:
        if len(args.job_dirs) > 1:
            rollup = obs_aggregate.serving_rollup(
                args.job_dirs, stale_after_s=stale_after)
            return rollup, obs_render.render_top_fleet_text(rollup)
        summary = obs_render.top_summary(args.job_dirs[0],
                                         stale_after_s=stale_after)
        if summary is None:
            return None, None
        return summary, obs_render.render_top_text(summary)

    try:
        while True:
            data, text = frame()
            if data is None:
                print(f"no telemetry journal found under "
                      f"{args.job_dirs[0]} (expected <dir>/telemetry/"
                      f"journal.jsonl — a `shifu-tpu serve`/train job "
                      f"writes one)", file=sys.stderr, flush=True)
                return EXIT_FAIL
            if args.json:
                print(json.dumps(data), flush=True)
            else:
                if not args.once:
                    # clear + home: a terminal frame, not a scrolling log
                    print("\x1b[2J\x1b[H", end="")
                print(text, flush=True)
            if args.once:
                return EXIT_OK
            time.sleep(max(args.interval, 0.1))
    except KeyboardInterrupt:
        return EXIT_OK


def run_drift(args) -> int:
    """`shifu-tpu drift <dir>`: the model-quality / data-drift panel —
    per-feature PSI vs the frozen baseline profile, score-distribution
    divergence, and live AUC decay from labeled feedback, straight off
    the journal tail (obs/render.drift_summary).  Never imports jax —
    safe to point at a LIVE daemon from any machine reading the dir."""
    from ..obs import render as obs_render

    try:
        summary = obs_render.drift_summary(
            args.job_dir, model=getattr(args, "model", None),
            feature=getattr(args, "feature", None))
    except Exception as e:
        print(f"drift: {e}", file=sys.stderr, flush=True)
        return EXIT_FAIL
    if summary is None:
        print(f"no telemetry journal found under {args.job_dir} "
              f"(expected <dir>/telemetry/journal.jsonl — a `shifu-tpu "
              f"serve` daemon with a baseline profile writes drift "
              f"reports there)", file=sys.stderr, flush=True)
        return EXIT_FAIL
    print(json.dumps(summary) if args.json
          else obs_render.render_drift_text(summary))
    return EXIT_OK


def run_cache(args) -> int:
    """`shifu-tpu cache <dir>`: the operator view of the columnar cache —
    every artifact classified (raw / projected / consolidated dataset,
    format version, bytes, recorded source, freshness), and `--prune` to
    reclaim the disk held by superseded, orphaned, legacy, or half-written
    entries.  File reads only: no jax import."""
    from ..data import cache as cache_lib

    if not os.path.isdir(args.cache_dir):
        print(f"cache: no such directory: {args.cache_dir}",
              file=sys.stderr, flush=True)
        return EXIT_FAIL
    try:
        entries = cache_lib.scan_cache(args.cache_dir)
    except OSError as e:
        print(f"cache: {e}", file=sys.stderr, flush=True)
        return EXIT_FAIL
    removed = cache_lib.prune_cache(args.cache_dir, entries) \
        if args.prune else []
    kept = [e for e in entries if e not in removed]
    if args.json:
        print(json.dumps({"cache_dir": args.cache_dir, "entries": kept,
                          "pruned": removed,
                          "total_bytes": sum(e["bytes"] for e in kept)}))
        return EXIT_OK
    if not entries:
        print(f"{args.cache_dir}: empty cache")
        return EXIT_OK

    def line(e):
        src = e["source"] or "-"
        ver = e["version"] if e["version"] is not None else "-"
        return (f"  {e['tier']:<9} v{ver:<3} {e['bytes']:>12,} B  "
                f"{e['status']:<8} {e['name']}"
                + (f"  <- {src}" if src != "-" else ""))

    print(f"{args.cache_dir}: {len(kept)} entries, "
          f"{sum(e['bytes'] for e in kept):,} bytes")
    for e in kept:
        print(line(e))
    if args.prune:
        print(f"pruned {len(removed)} entries "
              f"({sum(e['bytes'] for e in removed):,} bytes reclaimed)")
        for e in removed:
            print(f"  removed [{e['status']}] {e['name']}")
    else:
        stale = [e for e in kept
                 if e["status"] in cache_lib.PRUNE_STATUSES]
        if stale:
            print(f"{len(stale)} prunable entries "
                  f"({sum(e['bytes'] for e in stale):,} bytes) — "
                  f"rerun with --prune to reclaim")
    return EXIT_OK


def run_chaos_verify(args) -> int:
    """`shifu-tpu chaos-verify <job_dir>`: audit a finished chaos drill.

    Replays the recorded plan (default: the `chaos_plan.json` the launcher
    persisted beside the job) against the run journal: which sites actually
    injected, how often, and what the recovery machinery did about it
    (restarts, checkpoint fallbacks, preemption-grace saves, resumes).
    Exit 0 = the run completed (a `run_end exit=0` / `supervisor_done` is
    present) AND every planned fault site injected at least once — i.e. the
    drill both FIRED and was SURVIVED; anything else is exit 1."""
    from .. import chaos
    from ..data import fsio
    from ..obs import journal as journal_mod
    from ..obs import render as obs_render

    jpath = obs_render.find_journal(args.job_dir)
    if jpath is None:
        print(f"no telemetry journal found under {args.job_dir}",
              file=sys.stderr, flush=True)
        return EXIT_FAIL
    events = journal_mod.read_journal(jpath)

    plan = None
    plan_src = getattr(args, "plan", None)
    if not plan_src:
        cand = fsio.join(args.job_dir, "chaos_plan.json")
        if os.path.exists(cand) or (fsio.is_remote(cand)
                                    and obs_render._exists(cand)):
            plan_src = cand
    if plan_src:
        try:
            plan = chaos.load_plan(plan_src)
        except chaos.ChaosPlanError as e:
            print(f"chaos plan: {e}", file=sys.stderr, flush=True)
            return EXIT_FAIL

    injected: dict[str, int] = {}
    recovered: dict[str, int] = {}
    run_exits: list[int] = []
    recovery_kinds = ("supervisor_restart", "supervisor_done",
                      "checkpoint_fallback", "checkpoint_fallback_resolved",
                      "train_resume", "preemption_grace",
                      "supervisor_liveness_kill", "chaos_corrupt")
    for rec in events:
        kind = rec.get("kind")
        if kind == "chaos_inject":
            site = str(rec.get("site", "?"))
            injected[site] = injected.get(site, 0) + 1
        elif kind in recovery_kinds:
            recovered[kind] = recovered.get(kind, 0) + 1
        elif kind == "run_end":
            try:
                run_exits.append(int(rec.get("exit")))
            except (TypeError, ValueError):
                pass

    planned_sites = sorted({f.site for f in plan.faults}) if plan else []
    # a glob site ("fsio.*") counts as fired when ANY injected site matches
    import fnmatch as fnmatch_mod
    silent = [s for s in planned_sites
              if not any(i == s or fnmatch_mod.fnmatchcase(i, s)
                         for i in injected)]
    completed = (recovered.get("supervisor_done", 0) > 0
                 or (run_exits and run_exits[-1] == 0))
    report = {
        "journal": jpath,
        "plan": plan_src,
        "planned_sites": planned_sites,
        "injected": dict(sorted(injected.items())),
        "injected_total": sum(injected.values()),
        "silent_sites": silent,
        "recovered": dict(sorted(recovered.items())),
        "final_run_exit": run_exits[-1] if run_exits else None,
        "completed": bool(completed),
        "verdict": ("PASS" if completed and not silent
                    else "INCOMPLETE" if not completed else "SILENT_SITES"),
    }
    if getattr(args, "json", False):
        print(json.dumps(report))
    else:
        print(f"chaos-verify: {report['verdict']} — "
              f"{report['injected_total']} injection(s) across "
              f"{len(injected)} site(s), final exit "
              f"{report['final_run_exit']}")
        if planned_sites:
            print(f"  planned sites: {', '.join(planned_sites)}")
        for site, n in sorted(injected.items()):
            print(f"  injected  {site}: {n}")
        for kind, n in sorted(recovered.items()):
            print(f"  recovered {kind}: {n}")
        if silent:
            print(f"  NEVER FIRED: {', '.join(silent)} (trigger never "
                  "matched — check at_call/at_epoch/rank against the run)")
    return EXIT_OK if report["verdict"] == "PASS" else EXIT_FAIL


def run_fleet_verify(args) -> int:
    """`shifu-tpu fleet-verify <dir>`: audit a fleet run's journal
    against the fleet lifecycle invariants (runtime/fleet.py
    fleet_verify_events — the chaos-verify analog for the serving
    plane).  Exit 0 = every check holds.

    Process-mode members journal into their own tele dirs on their own
    clocks, so the audit runs on the skew-corrected merged timeline
    (obs/timeline.py): raw cross-host timestamps can make a later swap
    generation appear to precede an earlier one and fail the ordering
    checks spuriously."""
    from ..obs import timeline as timeline_mod
    from ..runtime.fleet import fleet_verify_events

    merged = timeline_mod.load_merged(args.job_dir, tail_bytes=None)
    if merged is None:
        print(f"no telemetry journal found under {args.job_dir}",
              file=sys.stderr, flush=True)
        return EXIT_FAIL
    report = fleet_verify_events(merged["events"])
    report["journal"] = merged["journals"][0]
    report["journals"] = merged["journals"]
    report["skew_correct"] = merged["skew_correct"]
    if getattr(args, "json", False):
        print(json.dumps(report))
    else:
        counts = report["counts"]
        print(f"fleet-verify: {report['verdict']} — "
              f"{counts['failovers']} failover(s), "
              f"{counts['swaps']} fleet swap(s), "
              f"{counts['member_swaps']} member application(s), "
              f"{counts['rejoins']} rejoin(s), "
              f"{counts['degraded']} degraded, "
              f"{counts['syncs']} host sync(s)")
        for c in report["checks"]:
            mark = "ok " if c["ok"] else "FAIL"
            print(f"  [{mark}] {c['check']}: {c['detail']}")
    return EXIT_OK if report["verdict"] == "PASS" else EXIT_FAIL


def run_pod_verify(args) -> int:
    """`shifu-tpu pod-verify <dir>`: audit a pod training run's merged
    per-rank journals against the pod data-plane invariants
    (launcher/pod.pod_verify_events — epoch coverage by complete cohorts,
    cross-host order/shard digest agreement, ingest balance, recovery
    after injected kills).  Exit 0 = every check holds."""
    from ..obs import timeline as timeline_mod
    from .pod import pod_verify_events

    merged = timeline_mod.load_merged(args.job_dir, tail_bytes=None)
    if merged is None:
        print(f"no telemetry journal found under {args.job_dir}",
              file=sys.stderr, flush=True)
        return EXIT_FAIL
    report = pod_verify_events(merged["events"],
                               balance_limit=args.balance_limit)
    report["journals"] = merged["journals"]
    if getattr(args, "json", False):
        print(json.dumps(report))
    else:
        counts = report["counts"]
        print(f"pod-verify: {report['verdict']} — "
              f"{counts['epochs']} epoch(s), "
              f"{counts['close_rows']} close row(s) from "
              f"{counts['ranks']} rank(s), "
              f"{counts['injections']} injection(s)")
        for c in report["checks"]:
            mark = "ok " if c["ok"] else "FAIL"
            print(f"  [{mark}] {c['check']}: {c['detail']}")
    return EXIT_OK if report["verdict"] == "PASS" else EXIT_FAIL


def _dryrun_progress_start(prog_dir: str, num_hosts: int) -> int:
    """First epoch this attempt should run: min completed epoch across the
    CURRENT gang's ranks + 1 (a rank file missing → that rank completed
    nothing → start at 0).  The gang-wide min makes a restart re-run any
    epoch a killed rank never closed, so the journal always ends with a
    complete per-epoch cohort — rank-local resume would let the survivors'
    head start leave holes `pod-verify` flags."""
    start = None
    for rank in range(num_hosts):
        p = os.path.join(prog_dir, f"rank-{rank}.json")
        try:
            with open(p) as f:
                done = int(json.load(f).get("epoch", -1))
        except (OSError, ValueError):
            done = -1
        start = done if start is None else min(start, done)
    return (start if start is not None else -1) + 1


def _dryrun_progress_mark(prog_dir: str, rank: int, epoch: int) -> None:
    os.makedirs(prog_dir, exist_ok=True)
    tmp = os.path.join(prog_dir, f".rank-{rank}.tmp")
    with open(tmp, "w") as f:
        json.dump({"epoch": int(epoch)}, f)
    os.replace(tmp, os.path.join(prog_dir, f"rank-{rank}.json"))


def run_data_dryrun(args) -> int:
    """`shifu-tpu data-dryrun`: one pod data-plane rank — shard-local
    ingest of this host's slice, per-epoch order/shard digests, one
    `pod_epoch_close` journal row per epoch — with NO device training and
    NO cross-process collectives, so it runs on any backend (the CPU
    backend cannot run multi-process collectives; the data plane is pure
    host work and needs none).  Rank identity comes from the pod env
    contract (SHIFU_TPU_PROCESS_ID / SHIFU_TPU_NUM_PROCESSES) that
    `supervise_pod` re-derives each attempt, so an elastic reshape
    rebalances the shard assignment automatically.  Every digest is a pure
    function of (seed, epoch, gang width), and the drill dataset's equal
    part files give every rank the same local row count — so the journaled
    cohorts must agree, which is exactly what `pod-verify` audits."""
    from .. import chaos
    from .. import obs
    from ..config.schema import DataConfig
    from ..data import pipeline as pipe
    from ..data import synthetic

    try:
        rank = int(os.environ.get("SHIFU_TPU_PROCESS_ID", "0") or 0)
        nproc = int(os.environ.get("SHIFU_TPU_NUM_PROCESSES", "1") or 1)
    except ValueError:
        rank, nproc = 0, 1
    chaos.reload_from_env()
    out = args.out
    tele = (os.path.join(out, "telemetry") if rank == 0
            else os.path.join(out, "telemetry", f"rank-{rank}"))
    from ..obs import _sinks
    _sinks.configure(tele)
    schema = synthetic.make_schema(num_features=args.features)
    # valid_ratio=0: the drill's agreement contract needs every rank's
    # LOCAL train-row count equal (no allgathered min without
    # collectives), and the hash split would skew counts per shard
    data = DataConfig(paths=(args.data,), delimiter=args.delimiter,
                      batch_size=int(args.batch_size), valid_ratio=0.0,
                      shuffle_seed=int(args.seed),
                      host_shard=args.host_shard)
    data.validate()
    prog_dir = os.path.join(out, "data_progress")
    start = _dryrun_progress_start(prog_dir, nproc)
    obs.event("pod_data_dryrun_start", rank=rank, hosts=nproc,
              epoch_start=start, epochs=int(args.epochs),
              host_shard=args.host_shard)
    n_files = pipe.count_source_files(data)
    reg = obs.default_registry()
    train_rows = None
    for ep in range(start, int(args.epochs)):
        # fires the `data.host_shard` chaos probe with epoch context —
        # the elastic drill's kill lands here, mid-epoch
        mine = pipe.host_file_shard(data, rank, nproc, epoch=ep)
        if train_rows is None:
            train_ds, _valid_ds = pipe.load_datasets(schema, data, rank,
                                                     nproc)
            train_rows = int(train_ds.num_rows)
        if args.epoch_seconds > 0:
            time.sleep(float(args.epoch_seconds))
        order_digest = pipe.epoch_order_digest(
            "batch", train_rows, int(args.batch_size), shuffle=True,
            seed=int(args.seed), epoch=ep)
        shard_digest = pipe.shard_assignment_digest(
            n_files, nproc, seed=int(args.seed), epoch=ep,
            mode=args.host_shard)
        obs.event(
            "pod_epoch_close", epoch=ep, rank=rank, hosts=nproc,
            files=len(mine), rows=train_rows,
            order_digest=order_digest, shard_digest=shard_digest,
            ingest_bytes=int(
                reg.counter("ingest_source_bytes_total").total()),
            ingest_s=round(
                reg.counter("ingest_seconds_total").total(), 6))
        obs.flush()
        _dryrun_progress_mark(prog_dir, rank, ep)
        print(f"data-dryrun rank {rank}/{nproc}: epoch {ep} "
              f"files={len(mine)} rows={train_rows}", flush=True)
    obs.event("pod_data_dryrun_done", rank=rank, hosts=nproc,
              epochs=int(args.epochs))
    obs.flush()
    return EXIT_OK


def run_timeline(args) -> int:
    """`shifu-tpu timeline <dir>`: the skew-corrected causal fleet
    timeline (obs/timeline.py) — merged member journals, incident
    records, sampled request traces.  Journal reads only: never imports
    jax, bounded tails, safe against a live fleet from any machine."""
    from ..obs import timeline as timeline_mod

    summary = timeline_mod.timeline_summary(
        args.job_dir,
        trace_id=getattr(args, "trace_id", None),
        incidents_only=getattr(args, "incident", False),
        skew_correct=not getattr(args, "no_skew_correct", False))
    if summary is None:
        print(f"no telemetry journal found under {args.job_dir}",
              file=sys.stderr, flush=True)
        return EXIT_FAIL
    if getattr(args, "json", False):
        print(json.dumps(summary))
    else:
        print(timeline_mod.render_timeline_text(summary))
    return EXIT_OK


def run_score(args) -> int:
    from .. import obs
    from ..data import reader

    obs.configure_from_env()
    rc = _kerberos_from_xml(args.globalconfig)
    if rc != EXIT_OK:
        return rc
    rows = reader.read_file(args.input)
    try:
        scorer = _load_scorer(args.model, args.native, args.engine)
    except (ValueError, OSError, KeyError, RuntimeError) as e:
        # a tier the artifact cannot serve (missing jaxexport/model_spec)
        # or contradictory flags: report, don't traceback
        print(f"scorer: {e}", file=sys.stderr, flush=True)
        return EXIT_FAIL
    feats = _project_features(rows, args.model, scorer)
    out = sys.stdout if args.output == "-" else open(args.output, "w")
    # chunked scoring + incremental writes: peak memory stays bounded by the
    # chunk, not the input (the reference scored one row per JNI call)
    chunk = 65536
    for lo in range(0, feats.shape[0], chunk):
        for s in scorer.compute_batch(feats[lo:lo + chunk]):
            out.write("|".join(f"{v:.6f}" for v in s) + "\n")
    if out is not sys.stdout:
        out.close()
    obs.event("score_run", rows=int(feats.shape[0]), model=args.model)
    obs.flush()
    return EXIT_OK


def _serving_config(args) -> "ServingConfig":
    """ServingConfig from `--globalconfig` shifu.serving.* keys with CLI
    flags as the top override layer (the same layering train uses)."""
    import dataclasses

    from ..config.schema import ServingConfig
    from ..utils import xmlconfig

    cfg = ServingConfig()
    if getattr(args, "globalconfig", None):
        conf = xmlconfig.parse_configuration_xml(args.globalconfig)
        cfg = xmlconfig.serving_config_from_conf(conf, cfg)
    kw = {}
    if getattr(args, "engine", None):
        kw["engine"] = args.engine
    if getattr(args, "port", -1) >= 0:
        kw["port"] = args.port
    if getattr(args, "host", None):
        kw["host"] = args.host
    if getattr(args, "budget_ms", 0):
        kw["latency_budget_ms"] = args.budget_ms
    if getattr(args, "max_batch", 0):
        kw["max_batch"] = args.max_batch
    if getattr(args, "workers", 0):
        kw["workers"] = args.workers
    if kw:
        cfg = dataclasses.replace(cfg, **kw)
    cfg.validate()
    return cfg


def run_serve(args) -> int:
    """`shifu-tpu serve <artifact>`: the persistent scoring daemon —
    admission queue + adaptive micro-batching under a latency budget,
    hot-swappable model registry, TCP wire front-end (runtime/serve.py,
    docs/SERVING.md).  Telemetry lands like a train job's: the
    SHIFU_TPU_METRICS_DIR env wins, else <artifact>/telemetry — so
    `shifu-tpu metrics <artifact>` reads the serving_report stream."""
    from .. import chaos, obs
    from ..config.schema import ConfigError
    from ..data import fsio

    if getattr(args, "chaos_plan", None):
        try:
            base = chaos.load_plan(args.chaos_plan.strip())
            os.environ[chaos.ENV_CHAOS_PLAN] = base.to_json(indent=None)
            chaos.reload_from_env()
        except chaos.ChaosPlanError as e:
            print(f"chaos plan: {e}", file=sys.stderr, flush=True)
            return EXIT_FAIL
    try:
        config = _serving_config(args)
    except (ConfigError, ValueError) as e:
        print(f"serve: {e}", file=sys.stderr, flush=True)
        return EXIT_FAIL
    metrics_dir = obs.resolve_metrics_dir() \
        or fsio.join(args.model, "telemetry")
    try:
        obs.configure(metrics_dir)
    except Exception:
        pass  # telemetry must never block serving
    from ..runtime.serve import serve_forever
    try:
        rc = serve_forever(args.model, config,
                           echo=lambda s: print(s, flush=True),
                           allow_swap=(True if getattr(args, "allow_swap",
                                                       False) else None),
                           heartbeat_every_s=getattr(args, "heartbeat_s",
                                                     0.0) or 0.0,
                           heartbeat_misses=getattr(args,
                                                    "heartbeat_misses",
                                                    3))
    except (ValueError, OSError, KeyError, RuntimeError) as e:
        print(f"serve: {e}", file=sys.stderr, flush=True)
        return EXIT_FAIL
    obs.flush()
    return rc


def run_fleet(args) -> int:
    """`shifu-tpu fleet <artifact>`: N scoring daemons + hot standbys
    under heartbeat supervision behind a hedging router front-end
    (runtime/fleet.py, runtime/router.py, docs/SERVING.md 'Fleet')."""
    import dataclasses

    from .. import chaos, obs
    from ..config.schema import ConfigError, FleetConfig
    from ..data import fsio
    from ..utils import xmlconfig

    if getattr(args, "chaos_plan", None):
        try:
            base = chaos.load_plan(args.chaos_plan.strip())
            os.environ[chaos.ENV_CHAOS_PLAN] = base.to_json(indent=None)
            chaos.reload_from_env()
        except chaos.ChaosPlanError as e:
            print(f"chaos plan: {e}", file=sys.stderr, flush=True)
            return EXIT_FAIL
    fleet_cfg = FleetConfig()
    if getattr(args, "globalconfig", None):
        conf = xmlconfig.parse_configuration_xml(args.globalconfig)
        fleet_cfg = xmlconfig.fleet_config_from_conf(conf, fleet_cfg)
    kw = {}
    if args.n_daemons > 0:
        kw["n_daemons"] = args.n_daemons
    if args.standbys >= 0:
        kw["standbys"] = args.standbys
    if args.heartbeat_s > 0:
        kw["heartbeat_every_s"] = args.heartbeat_s
    if args.heartbeat_misses > 0:
        kw["heartbeat_misses"] = args.heartbeat_misses
    if args.scale_every_s >= 0:
        kw["scale_every_s"] = args.scale_every_s
    if getattr(args, "hosts", None) is not None:
        kw["hosts"] = args.hosts
    if getattr(args, "member_mode", None) is not None:
        kw["member_mode"] = args.member_mode
    if kw:
        fleet_cfg = dataclasses.replace(fleet_cfg, **kw)
    try:
        fleet_cfg.validate()
        serving = _serving_config(args)
    except (ConfigError, ValueError) as e:
        print(f"fleet: {e}", file=sys.stderr, flush=True)
        return EXIT_FAIL
    metrics_dir = obs.resolve_metrics_dir() \
        or fsio.join(args.model, "telemetry")
    try:
        obs.configure(metrics_dir)
    except Exception:
        pass  # telemetry must never block serving
    root_dir = getattr(args, "root_dir", None) \
        or fsio.join(args.model, "fleet")
    from ..runtime.fleet import fleet_forever
    try:
        rc = fleet_forever(args.model, fleet=fleet_cfg, serving=serving,
                           router_host=args.host, router_port=args.port,
                           root_dir=root_dir,
                           echo=lambda s: print(s, flush=True))
    except (ValueError, OSError, KeyError, RuntimeError) as e:
        print(f"fleet: {e}", file=sys.stderr, flush=True)
        return EXIT_FAIL
    obs.flush()
    return rc


def run_loadtest(args) -> int:
    """`shifu-tpu loadtest`: the open-loop Poisson harness
    (runtime/loadtest.py; standalone spelling in tools/loadtest.py)."""
    from .. import obs
    from ..config.schema import ServingConfig
    from ..runtime import loadtest as lt

    if bool(args.model) == bool(args.connect):
        print("loadtest: exactly one of --model / --connect",
              file=sys.stderr, flush=True)
        return EXIT_FAIL
    obs.configure_from_env()
    config = None
    if getattr(args, "budget_ms", 0):
        config = ServingConfig(engine=args.engine,
                               latency_budget_ms=args.budget_ms,
                               report_every_s=0.0)
    try:
        if args.capacity:
            if not args.model:
                print("loadtest: --capacity needs --model",
                      file=sys.stderr, flush=True)
                return EXIT_FAIL
            report = lt.find_capacity(args.model, engine=args.engine,
                                      p99_target_ms=args.p99_target_ms,
                                      senders=args.senders, config=config)
        else:
            feats = getattr(args, "drift_features", None)
            if feats:
                feats = [int(v) for v in str(feats).split(",") if v]
            report = lt.run_loadtest(
                args.model, connect=args.connect,
                engine=args.engine, rate=args.rate,
                duration=args.duration, senders=args.senders,
                config=config,
                trace_sample=getattr(args, "trace_sample", 0),
                trace_exemplars=getattr(args, "trace_exemplars", 5),
                drift_after=getattr(args, "drift_after", 0.0),
                drift_shift=getattr(args, "drift_shift", 2.0),
                drift_features=feats,
                feedback=getattr(args, "feedback", False))
    except (ValueError, OSError, KeyError, RuntimeError) as e:
        print(f"loadtest: {e}", file=sys.stderr, flush=True)
        return EXIT_FAIL
    print(json.dumps(report) if args.json else lt.render_report(report))
    obs.flush()
    return EXIT_OK if report.get("completed") \
        or report.get("capacity_scores_per_sec") else EXIT_FAIL


def _apply_platform_env() -> None:
    """Honor SHIFU_TPU_PLATFORM / SHIFU_TPU_CPU_DEVICES before backend init.

    Needed because this image's sitecustomize force-registers the TPU backend
    regardless of JAX_PLATFORMS, so subprocess tests (and CPU-only users)
    need an in-process override."""
    plat = os.environ.get("SHIFU_TPU_PLATFORM")
    if not plat:
        return
    import jax
    try:
        jax.config.update("jax_platforms", plat)
        n = os.environ.get("SHIFU_TPU_CPU_DEVICES")
        if n and plat == "cpu":
            try:
                jax.config.update("jax_num_cpu_devices", int(n))
            except AttributeError:
                # older jax: no such option — fall back to XLA_FLAGS so a
                # cold CLI path (status/attach/kill) never tracebacks
                flags = os.environ.get("XLA_FLAGS", "")
                if "xla_force_host_platform_device_count" not in flags:
                    os.environ["XLA_FLAGS"] = (
                        flags
                        + f" --xla_force_host_platform_device_count={int(n)}"
                    ).strip()
    except RuntimeError:
        pass  # backends already initialized


def run_eval(args) -> int:
    """The Shifu `eval` step against this backend: score labeled normalized
    rows, report AUC + weighted error (successor of the reference's eval
    module feeding scores back into Shifu's PerformanceEvaluator via
    TensorflowModel.compute, TensorflowModel.java:52-109) — with the batch
    scoring and in-process metrics the reference's row-at-a-time JNI path
    could not offer."""

    from .. import obs
    from ..config.shifu_compat import load_json, parse_column_config
    from ..data import reader

    obs.configure_from_env()
    rc = _kerberos_from_xml(args.globalconfig)
    if rc != EXIT_OK:
        return rc
    target_name = weight_name = multi_targets = None
    if args.modelconfig:
        dataset = load_json(args.modelconfig).get("dataSet", {}) or {}
        target_name = dataset.get("targetColumnName")
        weight_name = dataset.get("weightColumnName")
        multi_targets = dataset.get("multiTargetColumnNames")
    schema = parse_column_config(load_json(args.columnconfig),
                                 target_column_name=target_name,
                                 weight_column_name=weight_name,
                                 multi_target_names=multi_targets)

    paths: list[str] = []
    for p in args.data:
        # handles local/remote, file-or-directory, with marker-file filtering
        paths.extend(reader.list_data_files(p))
    if not paths:
        print("eval: no data files found", file=sys.stderr)
        return EXIT_FAIL
    try:
        scorer = _load_scorer(args.model, args.native, args.engine)
    except (ValueError, OSError, KeyError, RuntimeError) as e:
        # a tier the artifact cannot serve (missing jaxexport/model_spec)
        # or contradictory flags: report, don't traceback
        print(f"scorer: {e}", file=sys.stderr, flush=True)
        return EXIT_FAIL
    # Stream file by file: metrics accumulate out-of-core (exact weighted
    # error; binned weighted AUC over the [0,1] sigmoid range, error <1e-6)
    # so eval-set size is bounded by disk, not RAM — the reference's eval
    # was row-at-a-time through JNI with aggregation left to the Shifu host.
    from ..ops.metrics import StreamingMetrics

    accs: list = []
    n_heads = 0
    score_sum = 0.0
    pos_count = 0
    scores_out = None  # created lazily so failure paths leave no stray file
    try:
        for p in sorted(paths):
            raw = reader.read_file(p)
            if raw.shape[0] == 0:
                continue
            if args.scores_output and scores_out is None:
                scores_out = open(args.scores_output, "w")
            cols = reader.project_columns(raw, schema)
            scores = scorer.compute_batch(
                _project_features(raw, args.model, scorer))
            labels_m, weights = cols["target"], cols["weight"][:, 0]
            if not accs:
                if scores.shape[1] != labels_m.shape[1]:
                    print(f"eval: artifact has {scores.shape[1]} heads but "
                          f"{labels_m.shape[1]} target columns resolved from "
                          "the configs — reporting the overlap only",
                          file=sys.stderr)
                n_heads = min(scores.shape[1], labels_m.shape[1])
                accs = [StreamingMetrics() for _ in range(n_heads)]
            for h in range(n_heads):
                accs[h].update(scores[:, h], labels_m[:, h], weights)
            score_sum += float(scores[:, 0].sum())
            pos_count += int((labels_m[:, 0] > 0.5).sum())
            if scores_out is not None:
                for row in scores:
                    scores_out.write("|".join(f"{v:.6f}" for v in row) + "\n")
    finally:
        if scores_out is not None:
            scores_out.close()
    if not accs:
        print("eval: no data rows found", file=sys.stderr)
        return EXIT_FAIL

    def _round_finite(v: float, nd: int = 6):
        # NaN (e.g. single-class AUC) is not valid JSON; emit null instead
        import math
        return round(float(v), nd) if math.isfinite(float(v)) else None

    # Head names come from the schema's *resolved* target columns (in
    # target-index order), not the raw multiTargetColumnNames list — a name
    # the ColumnConfig doesn't contain would otherwise shift every
    # subsequent head's metrics under the wrong label.
    name_by_index = {c.index: c.name for c in schema.columns}
    resolved_names = [name_by_index.get(i, f"head_{h}")
                      for h, i in enumerate(schema.all_target_indices)]
    rows = accs[0].rows
    heads = [
        {"name": resolved_names[h] if h < len(resolved_names) else f"head_{h}",
         "auc": _round_finite(accs[h].auc()),
         "weighted_error": _round_finite(accs[h].weighted_error())}
        for h in range(n_heads)]
    summary = {
        "rows": int(rows),
        "auc": heads[0]["auc"],
        "weighted_error": heads[0]["weighted_error"],
        "mean_score": _round_finite(score_sum / max(rows, 1)),
        "positive_rate": _round_finite(pos_count / max(rows, 1)),
    }
    if n_heads > 1:
        summary["heads"] = heads
    print(json.dumps(summary))
    obs.event("eval_run", rows=int(rows), auc=summary["auc"],
              weighted_error=summary["weighted_error"], model=args.model)
    obs.flush()
    return EXIT_OK


def _export_aot_opts(args) -> tuple:
    """(aot_pack, aot_buckets) for the export sequence: opt-in via the
    `shifu.serving.aot-pack` key in --globalconfig or the export
    command's --aot-pack flag; the rung grid comes from the SAME conf's
    serving ladder keys so the pack matches what the fleet will serve."""
    from ..utils import xmlconfig

    cfg = None
    if getattr(args, "globalconfig", None):
        try:
            conf = xmlconfig.parse_configuration_xml(args.globalconfig)
            cfg = xmlconfig.serving_config_from_conf(conf)
        except Exception:
            cfg = None
    if not (getattr(args, "aot_pack", False) or (cfg and cfg.aot_pack)):
        return False, None
    from ..config.schema import ServingConfig
    from ..runtime.serve import bucket_ladder

    sc = cfg or ServingConfig()
    return True, bucket_ladder(sc.min_batch_bucket, sc.max_batch)


def _export_and_pack(params, job, out_dir, console,
                     baseline_profile=None, aot_pack=False,
                     aot_buckets=None) -> str:
    """The one export sequence (artifact + best-effort native pack) shared
    by the train tail and the export recovery command — divergence here
    would give the recovery path different artifacts than training.

    A remote (gs:// hdfs://) destination builds the artifact in a local
    temp dir (the exporters and the native pack write real files) and
    uploads it through fsio — the reference likewise exported to
    FINAL_MODEL_PATH on HDFS (ssgd_monitor.py:302-345)."""
    from .. import obs
    from ..data import fsio
    from ..export import save_artifact
    from ..train import make_forward_fn

    with obs.span("export", journal=False):
        remote = fsio.is_remote(out_dir)
        local_dir = out_dir
        if remote:
            import tempfile
            local_dir = tempfile.mkdtemp(prefix="shifu_tpu_export_")
        export_dir = save_artifact(params, job, local_dir,
                                   forward_fn=make_forward_fn(job),
                                   baseline_profile=baseline_profile,
                                   aot_pack=aot_pack,
                                   aot_buckets=aot_buckets)
        try:
            from ..runtime import pack_native
            pack_native(export_dir)
        except Exception as e:  # native pack is best-effort
            console(f"native pack skipped: {e}")
        if remote:
            import shutil
            fsio.upload_dir(export_dir, out_dir)
            shutil.rmtree(local_dir, ignore_errors=True)
            export_dir = out_dir
    obs.event("export", dest=export_dir)
    console(f"model exported to {export_dir}")
    return export_dir


def run_export(args) -> int:
    """Rebuild the scoring artifact from the newest checkpoint — the
    recovery path when a job trained but died before (or during) export,
    and the way to ship a resumed/early-stopped state without retraining."""
    import jax

    from ..config import job_config_from_shifu
    from ..train import init_state
    from ..train import checkpoint as ckpt_lib
    from ..utils import xmlconfig

    job = job_config_from_shifu(args.modelconfig, args.columnconfig)
    if args.globalconfig:
        job = xmlconfig.apply_to_job(
            job, xmlconfig.parse_configuration_xml(args.globalconfig))

    if not os.path.isdir(args.checkpoint_dir):
        # restore-only path: never materialize an empty orbax tree at a
        # typo'd location as a side effect of the manager
        print(f"no checkpoint directory: {args.checkpoint_dir}",
              file=sys.stderr, flush=True)
        return EXIT_FAIL
    manager = ckpt_lib.make_manager(args.checkpoint_dir)
    state = init_state(job, job.schema.feature_count)
    from ..train.loop import restore_latest_any_layout
    restored = restore_latest_any_layout(manager, state, job,
                                         lambda s: print(s, flush=True))
    if restored is None:
        print(f"no checkpoint found under {args.checkpoint_dir}",
              file=sys.stderr, flush=True)
        return EXIT_FAIL
    r_state, extra, step = restored
    print(f"exporting checkpoint step {step} "
          f"(epoch {(extra or {}).get('epoch', '?')})", flush=True)
    aot_pack, aot_buckets = _export_aot_opts(args)
    _export_and_pack(jax.device_get(r_state.params), job, args.output,
                     lambda s: print(s, flush=True),
                     aot_pack=aot_pack, aot_buckets=aot_buckets)
    return EXIT_OK


def _arm_pdeathsig() -> None:
    """Supervised attempt children die with their supervisor.

    The supervisor spawns attempts in their OWN session (so kill-tree
    reaches the gang), which also detaches them from the supervisor's
    fate: a SIGTERM is forwarded by handler, but an UNCATCHABLE
    supervisor death (SIGKILL, OOM kill) would orphan the attempt to
    train its full epoch budget alone — observed as a 50k-epoch child
    spinning after its detached daemon was SIGKILLed.  When the
    supervisor marks the environment (supervisor.ENV_PDEATHSIG = its own
    pid), arm Linux PR_SET_PDEATHSIG(SIGTERM) so the kernel itself
    delivers the drain signal on parent death; SIGTERM (not SIGKILL) so
    the train loop's drain still checkpoints.  Closes the fork->arm race
    by self-signaling when os.getppid() no longer matches the recorded
    spawner — a pid compare, not a `== 1` check, so a supervisor that
    legitimately IS pid 1 (container entrypoint) or a subreaper
    environment cannot false-positive.
    """
    # literal env name: supervisor.ENV_PDEATHSIG (kept in sync by
    # tests/test_launcher.py); the cold path (status/attach/kill polls)
    # must not import the supervisor module just to read this.
    # Value: "<spawner_pid>" or "<spawner_pid>:<signum>".  The spawner
    # picks the signal: SIGTERM (default) for a single supervised child
    # whose drain handler checkpoints; SIGKILL for gang ranks — a rank
    # must terminate IMMEDIATELY on dispatcher death (divergent drains
    # deadlock collectives, train/loop.py), and libraries in the rank
    # (orbax preemption hooks) register SIGTERM handlers that would
    # swallow a catchable signal and leave the rank training forever.
    # pop, don't read: the arm applies to THIS process only, and any
    # descendant spawned with inherited env (a hook shelling out to
    # `shifu-tpu export`, a rank, a nested dispatcher) would otherwise see
    # a stale parent pid, fail the getppid compare, and self-kill at
    # startup; spawners that want armed children set the var fresh
    val = os.environ.pop("SHIFU_TPU_PDEATHSIG", None)
    if not val or sys.platform != "linux":
        return
    try:
        import signal as signal_lib

        parts = val.split(":")
        expected_parent = int(parts[0])
        sig = int(parts[1]) if len(parts) > 1 else int(signal_lib.SIGTERM)
    except ValueError:
        return
    try:
        import ctypes

        libc = ctypes.CDLL(None, use_errno=True)
        libc.prctl(1, sig, 0, 0, 0)  # 1 = PR_SET_PDEATHSIG
        if os.getppid() != expected_parent:
            # parent died (or we were reparented) before the arm landed
            os.kill(os.getpid(), sig)
    except Exception:
        pass  # best-effort hardening; never block startup


def main(argv: Optional[Sequence[str]] = None) -> int:
    _arm_pdeathsig()
    _apply_platform_env()
    args = build_parser().parse_args(argv)
    if args.command in ("train", "score", "eval", "export", "serve",
                        "loadtest", "fleet"):
        # repeat compiles (supervisor restarts, re-runs of the same job)
        # deserialize from the persistent cache instead of recompiling.
        # Only for commands that compile: status/attach/kill/provision are
        # file/CLI operations and must not pay the jax import.  Serving
        # paths drop the persistence floor to 0: padded-bucket scorer
        # programs compile in tens of ms — below the 0.5s train-path
        # floor, which would silently skip exactly the compiles a member
        # restart pays again (hit/miss verdicts ride every xla_compile
        # event through the observe_compile seam)
        from ..utils.compilecache import enable_persistent_cache
        serving_cmd = args.command in ("serve", "loadtest", "fleet")
        enable_persistent_cache(
            min_compile_time_secs=0.0 if serving_cmd else 0.5)
    if args.command == "train":
        # daemonized dispatcher: record the terminal state for `status`
        # even when the run unwinds via SystemExit (the provision branch
        # turns a scheduler SIGTERM into one so release finallys run) —
        # a cleanly drained kill must read as FAILED(143), not DEAD
        from . import detach as detach_lib
        detached_dir = os.environ.get(detach_lib.ENV_DETACHED)

        def _record(rc: int) -> None:
            if detached_dir and not getattr(args, "detach", False):
                detach_lib.write_status(detached_dir, rc)

        try:
            rc = run_train(args)
        except SystemExit as e:
            _record(e.code if isinstance(e.code, int) else 1)
            raise
        _record(rc)
        return rc
    if args.command == "score":
        return run_score(args)
    if args.command == "serve":
        return run_serve(args)
    if args.command == "fleet":
        return run_fleet(args)
    if args.command == "loadtest":
        return run_loadtest(args)
    if args.command == "eval":
        return run_eval(args)
    if args.command == "export":
        return run_export(args)
    if args.command == "provision":
        return run_provision(args)
    if args.command == "metrics":
        # pure file reads — must not pay the jax import or compile cache
        return run_metrics(args)
    if args.command == "profile":
        # likewise journal reads only — no jax import
        return run_profile(args)
    if args.command == "trace":
        # likewise journal reads only — no jax import
        return run_trace(args)
    if args.command == "top":
        # likewise journal/scrape tail only — no jax import, safe to
        # point at a live daemon from any machine
        return run_top(args)
    if args.command == "drift":
        # likewise journal tail only — no jax import
        return run_drift(args)
    if args.command == "chaos-verify":
        # likewise journal/plan reads only — no jax import
        return run_chaos_verify(args)
    if args.command == "fleet-verify":
        # likewise journal reads only — no jax import
        return run_fleet_verify(args)
    if args.command == "pod-verify":
        # likewise journal reads only — no jax import
        return run_pod_verify(args)
    if args.command == "data-dryrun":
        # host-side ingest only — no device work, no collectives
        return run_data_dryrun(args)
    if args.command == "timeline":
        # likewise journal reads only — no jax import
        return run_timeline(args)
    if args.command == "cache":
        # cache-dir file reads only — no jax import
        return run_cache(args)
    from . import detach as detach_lib
    if args.command == "status":
        return detach_lib.run_status(args.job_dir)
    if args.command == "attach":
        return detach_lib.attach(args.job_dir, from_start=not args.tail)
    if args.command == "kill":
        return detach_lib.kill(args.job_dir,
                               force=getattr(args, "force", False))
    return EXIT_FAIL


if __name__ == "__main__":
    sys.exit(main())
