from .cli import EXIT_FAIL, EXIT_OK, EXIT_TIMEOUT, main
from .console import ConsoleBoard, tail_board
from .supervisor import supervise

__all__ = ["EXIT_FAIL", "EXIT_OK", "EXIT_TIMEOUT", "main", "ConsoleBoard",
           "tail_board", "supervise"]
