"""Train state: params + optimizer state + step, as one pytree.

Replaces the reference's PS-hosted variable set + global_step
(resources/ssgd_monitor.py:123-127): under SPMD the whole state is one pytree
placed by sharding rule (replicated by default, embedding tables sharded),
and `step` is the successor of the chief-maintained global_step.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    # moment slots for sparse-updated embedding tables
    # (train/sparse_embed.py); None for dense jobs, so their state pytree
    # (and checkpoints) are unchanged
    table_slots: Any = None

    def apply_gradients(self, grads: Any) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(step=self.step + 1, params=new_params, opt_state=new_opt_state)

    @classmethod
    def create(cls, apply_fn: Callable, params: Any,
               tx: optax.GradientTransformation,
               table_slots: Any = None) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            apply_fn=apply_fn,
            tx=tx,
            table_slots=table_slots,
        )
