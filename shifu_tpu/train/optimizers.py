"""Optimizer factory.

The reference backend uses exactly one optimizer — Adadelta at ModelConfig's
LearningRate (resources/ssgd_monitor.py:140, fallback lr 0.003), wrapped in
SyncReplicasOptimizer for cross-worker aggregation.  Under SPMD the
aggregation is the mean-gradient all-reduce XLA inserts for a data-sharded
batch, so the optimizer here is just the local update rule.  Gradient
accumulation (optax.MultiSteps) is the analog of SAGN's k-step local window
(resources/SAGN.py:110-142).
"""

from __future__ import annotations

import optax

from ..config.schema import ConfigError, OptimizerConfig

# TF 1.4 AdadeltaOptimizer defaults (the reference passes only learning_rate):
# rho=0.95, epsilon=1e-8.
_TF_ADADELTA_RHO = 0.95
_TF_ADADELTA_EPS = 1e-8


def _learning_rate(cfg: OptimizerConfig):
    """The LR or optax schedule per OptimizerConfig.schedule (counted in
    optimizer steps; the reference only ever had a constant LR)."""
    lr = cfg.learning_rate
    if cfg.schedule == "constant":
        return lr
    if cfg.schedule == "cosine":
        return optax.cosine_decay_schedule(lr, cfg.decay_steps,
                                           alpha=cfg.end_lr_factor)
    if cfg.schedule == "exponential":
        return optax.exponential_decay(lr, cfg.decay_steps, cfg.decay_rate)
    if cfg.schedule == "warmup_cosine":
        return optax.warmup_cosine_decay_schedule(
            0.0, lr, cfg.warmup_steps, cfg.decay_steps,
            end_value=lr * cfg.end_lr_factor)
    raise ConfigError(f"unknown schedule {cfg.schedule!r}")


def build_optimizer(cfg: OptimizerConfig) -> optax.GradientTransformation:
    name = cfg.name.lower()
    lr = _learning_rate(cfg)
    if name == "adadelta":
        tx = optax.adadelta(learning_rate=lr, rho=_TF_ADADELTA_RHO, eps=_TF_ADADELTA_EPS)
    elif name == "adam":
        tx = optax.adam(lr)
    elif name == "adamw":
        tx = optax.adamw(lr, weight_decay=cfg.weight_decay)
    elif name in ("sgd", "gradientdescent"):
        tx = optax.sgd(lr)
    elif name == "momentum":
        tx = optax.sgd(lr, momentum=cfg.momentum)
    elif name == "rmsprop":
        tx = optax.rmsprop(lr)
    elif name == "adagrad":
        tx = optax.adagrad(lr)
    else:
        raise ConfigError(f"unknown optimizer {cfg.name!r}")

    chain = []
    if cfg.grad_clip_norm > 0:
        chain.append(optax.clip_by_global_norm(cfg.grad_clip_norm))
    chain.append(tx)
    out = optax.chain(*chain) if len(chain) > 1 else tx
    if cfg.accumulate_steps > 1:
        out = optax.MultiSteps(out, every_k_schedule=cfg.accumulate_steps)
    return out
