"""Tracing / profiling subsystem.

Parity-plus over the reference's hand-rolled timing (per-epoch wall clock in
the metrics line, slowest-worker sort in the AM — SURVEY.md section 5.1;
reference: resources/ssgd_monitor.py:270-293, appmaster/TensorflowSession.java:
538-546; TensorBoard support was vestigial, ssgd_monitor.py:493-502):

- `StepTimer`: cheap per-step wall timing with percentile summaries — the
  straggler view's SPMD successor (under SPMD the interesting skew is
  host-side input time vs device step time, both captured here).
- `trace`: context manager around `jax.profiler` emitting a TensorBoard-
  loadable trace directory (the real version of the reference's dead
  start_tensorboard).
- `profile_epoch` hook for the train loop via SHIFU_TPU_PROFILE_DIR.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Callable, Iterator, Optional

import numpy as np


class StepTimer:
    """Accumulates per-step host/device timings for one epoch.

    `on_chunk(input_s, step_s)`, when given, is called at every
    mark_step_done with the chunk's input wait and dispatch-to-done time
    — the device flight recorder's feed (obs/devprof.py: ring buffer +
    anomaly detector), so every input tier gets anomaly detection
    without per-tier loop changes.  The callback must be cheap (it runs
    on the chunk boundary) and never raise (exceptions are swallowed —
    timing must not fail the chunk it times)."""

    def __init__(self, on_chunk: Optional[Callable[[float, float],
                                                   None]] = None) -> None:
        self.input_times: list[float] = []
        self.step_times: list[float] = []
        self._t: Optional[float] = None
        self._on_chunk = on_chunk

    def start(self) -> None:
        self._t = time.perf_counter()

    def mark_input_ready(self) -> None:
        now = time.perf_counter()
        if self._t is not None:
            self.input_times.append(now - self._t)
        self._t = now

    def mark_step_done(self) -> None:
        now = time.perf_counter()
        if self._t is not None:
            self.step_times.append(now - self._t)
        self._t = now
        if self._on_chunk is not None and self.step_times:
            try:
                self._on_chunk(
                    self.input_times[-1] if self.input_times else 0.0,
                    self.step_times[-1])
            except Exception:
                pass

    def emit(self, prefix: str = "train", **labels) -> None:
        """Feed this epoch's per-step timings into the telemetry registry
        (obs/metrics.py): `<prefix>_input_seconds` / `<prefix>_step_seconds`
        histograms — the unified home the per-epoch console line used to be
        the only view of.  Call once per epoch; an empty epoch is a no-op."""
        from .. import obs

        hin = obs.histogram(f"{prefix}_input_seconds",
                            "host input wait per step/chunk")
        hstep = obs.histogram(f"{prefix}_step_seconds",
                              "device step/chunk dispatch-to-done time")
        for v in self.input_times:
            if v == v and v != float("inf"):  # finite only, like summary()
                hin.observe(v, **labels)
        for v in self.step_times:
            if v == v and v != float("inf"):
                hstep.observe(v, **labels)

    def summary(self) -> dict[str, float]:
        def stats(xs: list[float], prefix: str) -> dict[str, float]:
            # finite samples only: one NaN timing (a clock hiccup, a
            # poisoned mark) would otherwise propagate into EVERY field
            # via mean/percentile, and a single-chunk epoch (the scan
            # tiers dispatch once per epoch) must still produce a
            # well-formed record — p50 == p99 == the sample, never NaN
            arr = np.asarray([x for x in xs if x == x and x != float("inf")],
                             dtype=np.float64)
            if arr.size == 0:
                return {}
            if arr.size == 1:
                v_ms = float(arr[0]) * 1e3
                return {f"{prefix}_mean_ms": v_ms,
                        f"{prefix}_p50_ms": v_ms,
                        f"{prefix}_p99_ms": v_ms,
                        f"{prefix}_total_s": float(arr[0])}
            return {
                f"{prefix}_mean_ms": float(arr.mean() * 1e3),
                f"{prefix}_p50_ms": float(np.percentile(arr, 50) * 1e3),
                f"{prefix}_p99_ms": float(np.percentile(arr, 99) * 1e3),
                f"{prefix}_total_s": float(arr.sum()),
            }
        out = {}
        out.update(stats(self.input_times, "input"))
        out.update(stats(self.step_times, "step"))
        if "input_total_s" in out and "step_total_s" in out:
            total = out["input_total_s"] + out["step_total_s"]
            out["input_fraction"] = float(out["input_total_s"]
                                          / max(total, 1e-9))
        return out

    def console_line(self) -> str:
        s = self.summary()
        if not s:
            return "timing: no steps"
        return (f"timing: input p50 {s.get('input_p50_ms', 0):.2f}ms "
                f"step p50 {s.get('step_p50_ms', 0):.2f}ms "
                f"input fraction {s.get('input_fraction', 0):.1%}")


def straggler_line(epoch: int, epoch_time: float, valid_time: float,
                   input_seconds: float, console,
                   extra: Optional[dict] = None) -> None:
    """Cross-host per-epoch timing aggregation — the successor of the
    reference AM's slowest-first worker sort (appmaster/
    TensorflowSession.java:515-549: every worker's TrainingIntermediateResult
    collected, epoch times summed/averaged, then sorted slowest-first into
    one log line).  Every rank contributes (input_seconds, epoch_time,
    valid_time, hostname) through ONE small allgather; the chief prints
    hosts slowest-first so a degraded disk/NIC shows up as a named straggler
    instead of silently stalling the gang.

    Sorted by HOST INPUT SECONDS, not epoch time — a deliberate deviation
    from the reference's epoch-time sort: its workers ran async SGD, so a
    slow worker's epoch genuinely took longer; under SPMD every collective
    synchronizes the gang, epoch wall time converges on every rank, and the
    only per-host-attributable cost is host-side input production (SURVEY
    §5.1: "per-host input-pipeline timing still matters").

    COLLECTIVE: every process must call this each epoch (the train loop
    does, gated on multihost); only process 0 prints.

    Implementation lives in obs/aggregate.py since the telemetry
    unification: the same gather also journals a `host_skew` event, so the
    table survives the run as structured data, not just a log line.

    `extra` fields (pod data plane: cumulative ingest bytes/seconds, epoch
    order digest, shard-assignment digest) ride each host's row through the
    same gather — one allgather per epoch, never two."""
    from .. import obs

    obs.aggregate.epoch_skew(epoch, input_seconds, epoch_time, valid_time,
                             console=console, extra=extra)


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """jax.profiler trace (TensorBoard `Profile` plugin format)."""
    import jax

    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def maybe_trace(log_dir: Optional[str]):
    """trace() if a directory is given, else a no-op context."""
    if log_dir:
        return trace(log_dir)
    return contextlib.nullcontext()
