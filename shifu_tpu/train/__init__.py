from .checkpoint import make_manager, restore, restore_latest, save
from .loop import EpochMetrics, TrainResult, evaluate, init_state, train
from .optimizers import build_optimizer
from .step import (make_device_epoch_step, make_epoch_scan_step,
                   make_eval_step, make_forward_fn, make_local_sgd_epoch_step,
                   make_loss_fn, make_train_step)
from .train_state import TrainState

__all__ = [
    "make_manager",
    "restore",
    "restore_latest",
    "save",
    "EpochMetrics",
    "TrainResult",
    "evaluate",
    "init_state",
    "train",
    "build_optimizer",
    "make_device_epoch_step",
    "make_local_sgd_epoch_step",
    "make_epoch_scan_step",
    "make_eval_step",
    "make_forward_fn",
    "make_loss_fn",
    "make_train_step",
    "TrainState",
]
